"""North-star benchmark: FedAvg rounds/sec, CIFAR10 + ResNet-18-GN,
128 clients (BASELINE.json).

One full federated round = 128 clients × 1 local epoch over their CIFAR
shard (50k samples total, bs=32) + sample-weighted aggregation — all as one
jit-compiled program (vmap over the cohort; on a multi-device mesh the
aggregation is an ICI psum).  The reference equivalent is 129 MPI processes
exchanging pickled state dicts with a CPU aggregation loop
(fedml_api/distributed/fedavg/*, SURVEY.md §3.1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

`vs_baseline` compares against an ESTIMATE of the reference's 8×V100
throughput on the same workload, since the reference publishes no
rounds/sec (BASELINE.md): 50k samples/round × ~3.5 GFLOP fwd+bwd per
sample (ResNet-18 @32×32 ≈ 0.58 GFLOP fwd) ≈ 1.7e14 FLOP/round; 8×V100
at 125 TFLOP/s peak fp16 and a generous 35% utilization ≈ 350 TFLOP/s
⇒ ~0.5 s/round ⇒ ~2.0 rounds/s. We use 2.0 — conservative (favors the
reference: real FedML additionally pays MPI serialization + CPU
aggregation per round).  Sensitivity of vs_baseline to the utilization
assumption ({25%, 35%, 50%} ⇒ denominator 1.47/2.06/2.94) is tabulated
in PERF.md §"Baseline sensitivity".
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

ESTIMATED_REFERENCE_ROUNDS_PER_SEC = 2.0

# bump when the JSON line's keys change meaning; BENCH_*.json trajectory
# consumers key on this instead of guessing from key presence.
# v2: + schema_version, git_sha, rounds (per-round transfer records),
#     obs (observability rollup, present only under FEDML_OBS_DIR)
# v3: + h2d_bytes_per_round (transfer-compression byte accounting: mean
#     host->device payload bytes per timed round — 0 on this
#     resident-cohort bench, filled by streaming/block-stream variants);
#     per-round records in "rounds" additionally carry "h2d_bytes"
# v4: + "mode" ("sync" | "async") and "async" block (committed_updates,
#     staleness_p50/p95, buffer_occupancy_mean, deadline_commits —
#     `python bench.py --mode async`, fedml_tpu/async_); null in sync
#     mode, so v3 readers that ignore unknown keys keep working
# v5: + "ingest" block (`python bench.py --mode ingest`, the
#     concurrent-uplink ingestion torture, fedml_tpu/async_/torture.py):
#     a "legacy" arm (the PR-5 path faithfully: inline decode on recv
#     threads + unbounded inbox + drained O(K·P) commit), a
#     "legacy_bounded_inbox" arm (same path + this PR's inbox
#     backpressure — isolates the queue-discipline win), and
#     decode-into+streaming "arms" per pool size, each carrying
#     committed_updates_per_sec, decode_p50_s/decode_p95_s and
#     lock_wait_seconds, plus the headline "speedup_vs_legacy"
#     (best arm / legacy — the ISSUE-6 >=2x acceptance gate); null in
#     sync/async modes
# v6: + "critical_path" block (ISSUE 7, fedml_tpu/obs/timeline.py):
#     per-round stage attribution (stages {train/commit/decode/fold/
#     wait...: seconds}, stage_totals_s/stage_share, round_wall_p50/
#     p95_s, and p95_attribution naming the stage that explains p95
#     round wall).  Computed from the live span tracer, so it is null
#     unless the run is traced (FEDML_OBS_DIR); v5 readers that ignore
#     unknown keys keep working
# v7: + "chaos" block (`python bench.py --mode chaos`, ISSUE 8 —
#     fedml_tpu/comm/chaos.py + reliability.py over the ingest torture):
#     a "clean" reliable arm, a goodput-vs-fault-rate "curve" (loss/
#     dup/corrupt sweeps, each row carrying the rates,
#     committed_updates_per_sec, goodput_ratio vs clean, and the
#     retries/dups_suppressed/quarantined/recv_thread_deaths counters),
#     and a "mixed" arm (5% loss + 1% dup + 0.5% corrupt — the
#     acceptance shape) with its goodput_vs_clean headline; null in
#     other modes, so v6 readers keep working
# v9: + "serve" block (`python bench.py --mode serve`, ISSUE 10 —
#     fedml_tpu/scale/): the million-client serving-spine bench — one
#     row per simulated population (default 10k/100k/1M) from the
#     virtual-time serve loop (scale/serve.py: sharded registry +
#     streaming cohort sampler + trace-driven arrivals driving the
#     PR-6 streaming buffer), each carrying committed_updates_per_sec,
#     registry_bytes / registry_bytes_per_client (the <= ~100 B/client
#     sub-linear-memory gate, recorded in "sublinear_ok"), sampler
#     scratch bytes, rss_bytes and the virtual-time arrival stats;
#     null in other modes, so v8 readers keep working
# v10: + "connections" block (`python bench.py --mode connections`,
#     ISSUE 11 — fedml_tpu/comm/reactor.py + connswarm.py over the
#     live-connection torture): one row per live-connection count
#     (default 256/1k/10k), each with a clean, a mixed-chaos (5% loss +
#     1% dup + 0.5% corrupt) and a storm (chaos + connection storm +
#     reconnect churn) arm carrying committed_updates_per_sec,
#     admission_p50_s/admission_p95_s, open_connections_peak, the
#     evicted{stall|rate|shed} / uplinks_shed / recv_thread_deaths /
#     fd_leaked counters and loop_lag_p95_s, plus per-row
#     storm_goodput_ratio (the >= 0.5x-of-clean acceptance gate) —
#     null in other modes, so v9 readers keep working
# v11: + "slo" block (ISSUE 12, fedml_tpu/obs/slo.py) on EVERY mode —
#     the default serving-spine SLO pack evaluated per bench arm
#     ({"pack", "arms": {arm: {breaches, breached, healthy}}}); clean
#     arms must stay breach-free (tools/bench_diff.py's
#     slo_clean_breaches verdict) while chaos/storm arms breach BY
#     DESIGN with named attribution — and + "programs" block
#     (fedml_tpu/obs/programs.py): the per-jit-program-family profile
#     ({"window_s", "peak_flops", "families": [{family, stage,
#     dispatches, dispatch_wall_s, dispatch_p50/p95_s, flops/bytes per
#     dispatch, mfu}], "total"}), the PERF.md stage table as a standing
#     artifact; v10 readers that ignore unknown keys keep working
# v12: + "multihost" block (`python bench.py --mode multihost`,
#     ISSUE 13 — fedml_tpu/parallel/multihost.py): the weak-scaling
#     two-level-aggregation sweep — N worker processes (spawn_cluster,
#     one block of clients per process, constant per-process work)
#     each train their cohort blocks on a LOCAL mesh and allreduce the
#     P-sized flat f32 carry over the HostChannel, one row per process
#     count (default 1/2/4) carrying rounds_per_sec,
#     carry_allreduce_bytes_per_round, ranks_agree and process_deaths,
#     plus weak_efficiency_2p/4p (rounds/sec vs the 1-process arm; the
#     >= 0.5x-at-2 gate is the documented GIL/gloo floor on the 2-core
#     box — exp_POD prices it on a real pod slice) and
#     bitwise_2proc_ok (the 1-vs-2-process same-block-partition digest
#     pin); null in other modes, so v11 readers keep working
# v13: the "multihost" block gains the elastic "chaos" arm (ISSUE 14 —
#     ElasticChannel/ElasticRunner in fedml_tpu/parallel/multihost.py):
#     a 3-process ELASTIC cluster with a seeded kill of rank 1 mid-run
#     vs the clean elastic same-partition run — survivor_goodput_ratio
#     (killed/clean rounds/sec, >= 0.5x gate), view_changes +
#     view_change_latency_s (death detection -> survivors re-tasked),
#     survivor_deaths (must be 0 — only the killed rank dies),
#     epoch_final, and bitwise_after_death_ok (the killed run's commit
#     digests byte-identical to the clean run's, FedAvg resident AND
#     streaming — the re-adopted blocks are pure functions of [seed,
#     round, block], so the fold is topology-independent); plus
#     elastic_fail_fast_default_ok (fail-fast stays the default policy:
#     the weak-scaling arms above still run non-elastic).  --mh_arms
#     selects weak/bitwise/chaos subsets; v12 readers that ignore
#     unknown keys keep working
# v14: the "multihost" block gains the "compress" arm (ISSUE 16 —
#     fedml_tpu/parallel/carry_codec.py + the overlapped exchange in
#     multihost.py): paired 2-process clusters at the SAME block
#     partition price the compressed inter-host carry tier — an f32
#     serial baseline, the f32+overlap escape-hatch run (digests must
#     be byte-identical to serial: bitwise_f32_escape_ok), and one row
#     per compressed codec (int8, int8_ef; overlap on, eval on)
#     carrying carry_wire_bytes_per_round (measured ON the wire via
#     the channel's per-round delta, not inferred host-side),
#     carry_compression_ratio (raw f32 bytes / encoded payload),
#     wire_reduction_vs_f32 (>= 3x gate rides bench_diff),
#     overlap_fraction (> 0 when the DCN exchange hides behind block
#     compute), eval_acc + acc_delta_vs_f32 (abs; the quality band),
#     and efficiency_at_constant_bytes ((rps_codec/rps_f32) x
#     wire_reduction — rounds per byte-budget).  --mh_arms grows
#     "compress"; v13 readers that ignore unknown keys keep working
# v15: the "multihost" block gains the "straggler" block (ISSUE 17 —
#     fedml_tpu/obs/cluster.py, the cluster observatory): rank 0 keeps
#     an always-on barrier ledger (per-rank arrival timestamps at every
#     gather/allgather/exchange), so the chaos arm now also reports WHO
#     gated each round — barriers observed on the clean and killed
#     elastic runs, per-rank gating_counts, top_gating_rank,
#     worst_gate_margin_s, per_rank_wait_s percentiles, the last few
#     ledger entries (each naming its round_gating_rank), plus the
#     cluster SLO verdicts: cluster_clean_breaches (must be 0 — the
#     clean arm's cluster pack is green) and cluster_killed_breached
#     (the killed arm MUST breach cluster_no_rank_deaths with rank 1
#     named in the attribution — straggler_attribution_ok pins that);
#     v14 readers that ignore unknown keys keep working
# v8: + "attack" block (`python bench.py --mode attack`, ISSUE 9 —
#     fedml_tpu/async_/adversary.py + defense.py): a "matrix" of
#     attack x defense arms on the async MNIST-LR workload (each row:
#     attack mode, defended flag, test_acc, quarantine counts with
#     honest/byzantine attribution), the "mixed" acceptance trio
#     (20% byzantine boost+labelflip — defended_acc vs undefended_acc
#     vs clean_acc, false_positive_quarantines), and an "overhead"
#     ingest-torture pair (admission screen on vs off) whose
#     throughput_ratio prices the fused screen (the >=0.9x target is
#     the chip-side gate — on the 2-core CI box the serial fold is the
#     bottleneck and the paired median is ~0.73x, PERF.md); null in
#     other modes, so v7 readers keep working
# v16: + "cluster" block (`python bench.py --mode cluster`, ISSUE 18 —
#     fedml_tpu/scale/cluster.py, the fused serving cluster): live
#     connswarm sockets feed registry-sharded lanes on each host of an
#     elastic multi-host tier, lane partials folding cross-host at
#     every commit barrier.  Rows sweep host counts (1/2/4 by default,
#     one multi-target swarm striped across the endpoints):
#     cluster_updates_per_sec, admission p95 (max over ranks),
#     ranks_agree (the cross-rank digest pin, live ingest).  The
#     chaos_everything arm composes ALL the fault layers at once —
#     connection storm + seeded wire faults + a rank killed mid-run —
#     and reports survivor_goodput_ratio (>= 0.5 floor),
#     bitwise_after_death_ok (survivor digests agree), and the full
#     evictions/sheds/drops ledger; v15 readers that ignore unknown
#     keys keep working
# v17: the "multihost" block gains the "sparse" arm and the "cluster"
#     block a "sparse" sub-block (ISSUE 19 — topk/topk_ef carry codecs
#     in fedml_tpu/parallel/carry_codec.py + the sparse_topk uplink
#     transport in comm/message.py).  multihost sparse: same paired
#     2-process protocol as the compress arm, one row per sparse codec
#     (topk, topk_ef; overlap on, eval on) with the SAME columns —
#     carry_wire_bytes_per_round (channel-measured),
#     carry_compression_ratio, wire_reduction_vs_f32 (the ISSUE-19
#     >= 6x gate rides bench_diff), overlap_fraction, eval_acc +
#     acc_delta_vs_f32 (quality band; topk is LOSSY where int8 was
#     near-lossless, so this column carries the judgment), ranks_agree,
#     and efficiency_at_constant_bytes; plus bitwise_f32_escape_ok
#     re-pinned on the f32 baseline pair.  cluster sparse: a paired
#     dense-vs-sparse_topk uplink run at the same host count —
#     uplink_bytes_per_update (frame bytes on the wire),
#     uplink_reduction_vs_dense, sparse committed-updates/sec and
#     throughput_ratio_vs_dense (>= 0.9x on 2-core rides bench_diff),
#     digests_equal on a <= k-sparse replay (sparse_topk round-trips
#     <= k-nonzero rows exactly, so dense and sparse ingest commit
#     identical bits); v16 readers that ignore unknown keys keep
#     working
# v18: + "secure" block (`python bench.py --mode secure`, ISSUE 20 —
#     fedml_tpu/secure/secagg.py, the pairwise-mask data plane): the
#     privacy-tax table on the live async messaging FSM (MNIST-LR,
#     full-cohort barrier) — plain vs masked committed-updates/sec
#     (privacy_tax_ratio), plain/secure/dp accuracy (the end-to-end
#     private mode's quality cost), the masks_cancel_bitwise_ok
#     protocol pin (full-cohort masked field sum == plain fixed-point
#     sum, exact integers), measured encoded-frame uplink bytes
#     (plain f32 pytree frame vs masked u32 words at the same model —
#     uplink_bytes_ratio; masked words are incompressible by design,
#     so codec-v2 compression buys nothing), below_threshold_commits
#     (MUST be 0 on the
#     clean arms — masks only fail to cancel when survivors dip under
#     the reconstruction threshold), and the two masked-byzantine
#     arms: in-field boost (fits the quantizer range -> sails through,
#     because the admission screen reads plaintext rows and is BLINDED
#     under masks) vs overflow boost (the client-side quantizer range
#     refusal — the ONE norm-bound enforcement masking cannot blind —
#     drops the uplink and dropout recovery carries the round); v17
#     readers that ignore unknown keys keep working
SCHEMA_VERSION = 18


# the programs block's window opens when main() configures obs (set
# there; None until then so helper calls stay harmless)
_PROGRAMS_T0 = None


def _programs_doc():
    """Schema-v11 programs block: the per-jit-program-family profile
    over this bench invocation's window (dispatch counts + host walls
    always; flops/bytes/MFU when the census ran — see main())."""
    from fedml_tpu.obs import programs
    return programs.report(_PROGRAMS_T0)


def _slo_doc(arms: dict) -> dict:
    """Schema-v11 slo block: the default-pack verdicts per bench arm.
    `arms` maps arm name -> an SloEngine.arm_summary() (or a torture
    report's "slo_arm").  Arm names matter: tools/bench_diff.py treats
    arms whose name contains chaos/storm/mixed/curve as
    breach-by-design and judges only the clean ones."""
    from fedml_tpu.obs import slo
    return {"pack": slo.DEFAULT_PACK_NAME,
            "arms": {k: v for k, v in arms.items() if v is not None}}


def _slo_window():
    """A primed default-pack engine for modes that are one arm (sync/
    async/serve population loops): prime now, summarize at arm end."""
    from fedml_tpu.obs import slo
    eng = slo.SloEngine(slo.default_slo_pack())
    eng.prime()
    return eng


def _slo_close(eng) -> dict:
    eng.evaluate()
    return eng.arm_summary()


def _critical_path_doc():
    """Schema-v6 critical_path block from the live tracer (None when
    the run is untraced — spans are the input, metrics alone cannot
    place stages on a timeline)."""
    from fedml_tpu import obs
    t = obs.tracer()
    if t is None:
        return None
    from fedml_tpu.obs import timeline
    report = timeline.critical_path(t.events())
    report.pop("rounds", None)       # per-round detail stays in obs_dir
    return report


def _git_sha() -> str:
    """Short sha of the bench's code state, best-effort ("unknown" when
    git is absent) — BENCH_*.json rows stay attributable across PRs."""
    import subprocess
    try:
        r = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        if r.returncode == 0:
            return r.stdout.strip()
    except Exception:
        pass
    return "unknown"


def _stamp(doc: dict) -> dict:
    doc["schema_version"] = SCHEMA_VERSION
    doc["git_sha"] = _git_sha()
    return doc

N_CLIENTS = 128
BATCH_SIZE = 32
SAMPLES_PER_CLIENT = 50_000 // N_CLIENTS      # ≈ CIFAR10 over 128 clients
WARMUP_ROUNDS = 2
TIMED_ROUNDS = 8     # measured run-to-run spread at 5 was 0.544-0.549
                     # rounds/sec; 8 tightens the single-run estimate
                     # for ~5 s extra driver time


def _probe_devices(timeout: float) -> tuple[bool, str]:
    """Attach-probe in a subprocess: a wedged TPU tunnel makes
    jax.devices() HANG (not raise), which would surface as a driver
    timeout/crash instead of an interpretable artifact.  The probe pays
    one extra attach on the happy path; the backend cache makes the
    second attach in main() cheap."""
    import subprocess
    try:
        # the environment's sitecustomize force-sets jax_platforms
        # "axon,cpu" regardless of JAX_PLATFORMS (see tests/conftest.py);
        # pin the config back so an explicit JAX_PLATFORMS=cpu dev run
        # doesn't block on the tunnel backend
        r = subprocess.run(
            [sys.executable, "-c",
             "import os, jax; p = os.environ.get('JAX_PLATFORMS');\n"
             "jax.config.update('jax_platforms', p) if p else None;\n"
             "d = jax.devices(); assert d; print(d[0].platform)"],
            timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False, f"device attach timed out after {timeout:.0f}s"
    if r.returncode != 0:
        return False, (r.stderr.strip().splitlines() or ["unknown"])[-1]
    return True, r.stdout.strip()


def _probe_with_retry() -> tuple[bool, str]:
    """Attach-probe with retry + backoff (VERDICT r4 #1: one transient
    tunnel wedge zeroed the round-4 record).  Each attempt gets
    BENCH_PROBE_TIMEOUT (default 300 s — a healthy attach is <60 s);
    attempts repeat with growing sleeps until the BENCH_PROBE_BUDGET
    (default 600 s — bounded so probe + timed bench stays inside the
    driver's patience) wall-clock budget is spent, because the tunnel's
    observed outage mode is minutes-long wedges that sometimes clear."""
    per_try = float(os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
    budget = float(os.environ.get("BENCH_PROBE_BUDGET", "600"))
    t0 = time.monotonic()
    attempt, backoff = 0, 20.0
    while True:
        attempt += 1
        remaining = budget - (time.monotonic() - t0)
        ok, detail = _probe_devices(min(per_try, max(remaining, 30.0)))
        if ok and detail == "cpu" and os.environ.get(
                "JAX_PLATFORMS") != "cpu":
            # the tunnel backend failed FAST and jax fell through to the
            # sitecustomize's cpu fallback: without an explicit
            # JAX_PLATFORMS=cpu opt-in, a cpu bench would record a ~100x
            # "regression" that is really a chip outage
            ok, detail = False, "tunnel backend fell back to cpu"
        if ok:
            return ok, detail
        remaining = budget - (time.monotonic() - t0)
        if remaining <= backoff + 30.0:
            return False, f"{detail} (after {attempt} attempts)"
        print(f"attach attempt {attempt} failed ({detail}); retrying in "
              f"{backoff:.0f}s, {remaining:.0f}s of budget left",
              file=sys.stderr)
        time.sleep(backoff)
        backoff = min(backoff * 2, 120.0)


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser("bench")
    ap.add_argument("--mode",
                    choices=("sync", "async", "ingest", "chaos", "attack",
                             "serve", "connections", "multihost",
                             "cluster", "secure"),
                    default="sync",
                    help="sync: the north-star resident-cohort rounds/sec "
                         "bench; async: the buffered staleness-aware "
                         "scheduler (fedml_tpu/async_) — committed "
                         "updates/sec + staleness percentiles under the "
                         "seeded lognormal-latency lifecycle; ingest: the "
                         "concurrent-uplink ingestion torture "
                         "(fedml_tpu/async_/torture.py) — sustained "
                         "committed-updates/sec of the server's "
                         "decode+aggregate path under N saturating "
                         "clients, legacy vs decode-into+streaming A/B; "
                         "chaos: the same torture under seeded wire "
                         "faults (fedml_tpu/comm/chaos.py) with the "
                         "reliability envelope on — goodput-vs-fault-"
                         "rate curves for loss/dup/corrupt; attack: "
                         "the adversarial-robustness matrix (ISSUE 9, "
                         "fedml_tpu/async_/adversary.py + defense.py) "
                         "— attack x defense accuracy on the async "
                         "MNIST-LR workload plus the admission-screen "
                         "ingest-overhead pair; serve: the "
                         "million-client serving-spine bench (ISSUE 10, "
                         "fedml_tpu/scale/) — sustained committed-"
                         "updates/sec and server registry memory vs "
                         "simulated population (10k/100k/1M) under a "
                         "trace-driven arrival process in virtual time; "
                         "connections: the live-connection reactor bench "
                         "(ISSUE 11, fedml_tpu/comm/reactor.py) — "
                         "sustained committed-updates/sec + p95 admission "
                         "latency vs live socket count (256/1k/10k), "
                         "clean vs mixed-chaos vs storm arms; multihost: "
                         "the weak-scaling two-level-aggregation sweep "
                         "(ISSUE 13, fedml_tpu/parallel/multihost.py) — "
                         "N spawned processes train one client block "
                         "each on local meshes and allreduce the flat "
                         "f32 carry over the HostChannel; rounds/sec + "
                         "carry bytes vs process count (1/2/4) plus the "
                         "1-vs-2-process bitwise pin; cluster: the "
                         "fused serving cluster (ISSUE 18, "
                         "fedml_tpu/scale/cluster.py) — live connswarm "
                         "sockets feed registry-sharded lanes on each "
                         "host of an elastic multi-host tier; "
                         "committed-updates/sec + p95 admission vs "
                         "(hosts x connections) at 1/2/4 hosts, plus "
                         "the chaos-everything arm (storm + wire "
                         "faults + rank kill at once); secure: the "
                         "pairwise-mask privacy-tax bench (ISSUE 20, "
                         "fedml_tpu/secure/) — plain vs masked "
                         "committed-updates/sec on the live async FSM, "
                         "plain/secure/dp accuracy, the masks-cancel "
                         "bitwise pin, and the masked-byzantine pair "
                         "(blinded screen vs quantizer range refusal)")
    ap.add_argument("--ingest_clients", type=int, default=32,
                    help="ingest mode: concurrent uplink clients")
    ap.add_argument("--ingest_backend", default="TCP",
                    choices=("TCP", "GRPC", "INPROC"),
                    help="ingest mode: transport under torture")
    ap.add_argument("--ingest_pools", default="1,4,8",
                    help="ingest mode: comma-separated decode-pool sizes "
                         "for the decode-into+streaming arms")
    ap.add_argument("--ingest_commits", type=int, default=30,
                    help="ingest mode: timed commits per arm")
    ap.add_argument("--chaos_clients", type=int, default=32,
                    help="chaos mode: concurrent reliable uplink clients")
    ap.add_argument("--chaos_backend", default="TCP",
                    choices=("TCP", "GRPC", "INPROC"),
                    help="chaos mode: transport under fault injection")
    ap.add_argument("--chaos_commits", type=int, default=12,
                    help="chaos mode: timed commits per arm (the curve "
                         "runs ~8 arms; keep this moderate)")
    ap.add_argument("--chaos_seed", type=int, default=0,
                    help="chaos mode: fault-injection seed (same seed = "
                         "same per-stream injected-event trace)")
    ap.add_argument("--attack_commits", type=int, default=16,
                    help="attack mode: async commits per accuracy arm "
                         "(the quality-band workload runs 16)")
    ap.add_argument("--attack_ingest_clients", type=int, default=32,
                    help="attack mode: clients in the screen-overhead "
                         "ingest pair")
    ap.add_argument("--attack_backend", default="TCP",
                    choices=("TCP", "GRPC", "INPROC"),
                    help="attack mode: transport of the overhead pair")
    ap.add_argument("--attack_seed", type=int, default=0,
                    help="attack mode: adversary seed (same seed = same "
                         "byzantine set + corruption streams)")
    ap.add_argument("--serve_populations", default="10000,100000,1000000",
                    help="serve mode: comma-separated simulated client "
                         "populations (one bench row each)")
    ap.add_argument("--serve_commits", type=int, default=40,
                    help="serve mode: streaming commits per population "
                         "arm (K updates each)")
    ap.add_argument("--serve_buffer_k", type=int, default=32,
                    help="serve mode: streaming buffer capacity K")
    ap.add_argument("--serve_row_dim", type=int, default=4096,
                    help="serve mode: flat update-row width P the fold "
                         "and commit run at")
    ap.add_argument("--serve_sampler", default="stratified",
                    choices=("uniform", "reservoir", "stratified"),
                    help="serve mode: cohort sampler over the registry "
                         "(stratified = O(k)-per-draw, the spine "
                         "default; reservoir = exact-uniform one-pass)")
    ap.add_argument("--serve_arrivals", default="diurnal",
                    choices=("constant", "diurnal", "flash"),
                    help="serve mode: arrival-process family driving "
                         "the virtual clock")
    ap.add_argument("--serve_seed", type=int, default=0,
                    help="serve mode: one seed drives sampler, arrivals "
                         "and fault draws (same seed = same trace)")
    ap.add_argument("--conn_counts", default="256,1000,10000",
                    help="connections mode: comma-separated live-"
                         "connection counts (one bench row each; counts "
                         "past ~4k run the client swarm in a subprocess "
                         "so both halves fit under ulimit -n)")
    ap.add_argument("--conn_commits", type=int, default=24,
                    help="connections mode: timed commits per arm")
    ap.add_argument("--conn_buffer_k", type=int, default=32,
                    help="connections mode: streaming buffer capacity K")
    ap.add_argument("--conn_pool", type=int, default=4,
                    help="connections mode: decode-pool size")
    ap.add_argument("--conn_rate", type=float, default=2000.0,
                    help="connections mode: aggregate offered uplink "
                         "frames/sec across the swarm")
    ap.add_argument("--conn_seed", type=int, default=0,
                    help="connections mode: one seed drives the swarm "
                         "schedule and the chaos injector")
    ap.add_argument("--mh_procs", default="1,2,4",
                    help="multihost mode: comma-separated process "
                         "counts (one weak-scaling row each; per-"
                         "process work is constant — one client block "
                         "per process)")
    ap.add_argument("--mh_rounds", type=int, default=10,
                    help="multihost mode: rounds per arm (first "
                         "--mh_warmup excluded from the rate)")
    ap.add_argument("--mh_warmup", type=int, default=2,
                    help="multihost mode: warmup rounds per arm")
    ap.add_argument("--mh_clients_per_block", type=int, default=64,
                    help="multihost mode: population per block (the "
                         "id-range each process owns)")
    ap.add_argument("--mh_k_per_block", type=int, default=8,
                    help="multihost mode: sampled cohort per block per "
                         "round")
    ap.add_argument("--mh_dim", type=int, default=256,
                    help="multihost mode: LR input dim (sets the flat "
                         "carry size P that crosses hosts)")
    ap.add_argument("--mh_local_devices", type=int, default=1,
                    help="multihost mode: virtual devices per process "
                         "(the intra-host psum tier width on CPU)")
    ap.add_argument("--mh_seed", type=int, default=0,
                    help="multihost mode: workload seed (same seed = "
                         "same cohorts = the bitwise pin's premise)")
    ap.add_argument("--mh_arms", default="weak,bitwise,chaos,compress",
                    help="multihost mode: comma-subset of "
                         "{weak,bitwise,chaos,compress} — weak = the "
                         "v12 weak-scaling sweep, bitwise = the "
                         "1p-vs-2p digest pin, chaos = the v13 elastic "
                         "kill-a-rank arm (survivor goodput + "
                         "bitwise_after_death_ok), compress = the v14 "
                         "compressed+overlapped carry tier (bytes on "
                         "the wire, quality band, f32 escape-hatch "
                         "bitwise pin)")
    ap.add_argument("--mh_chaos_procs", type=int, default=3,
                    help="multihost chaos arm: elastic cluster size "
                         "(rank 1 is killed mid-run; the survivors "
                         "must finish)")
    ap.add_argument("--cluster_hosts", default="1,2,4",
                    help="cluster mode: comma-separated host counts "
                         "(one row each; a multi-target swarm stripes "
                         "its fleet across the H endpoints)")
    ap.add_argument("--cluster_connections", type=int, default=32,
                    help="cluster mode: swarm connections per host")
    ap.add_argument("--cluster_commits", type=int, default=8,
                    help="cluster mode: commit windows per arm (first "
                         "2 are warmup)")
    ap.add_argument("--cluster_buffer_k", type=int, default=32,
                    help="cluster mode: uplinks per lane per commit "
                         "window")
    ap.add_argument("--cluster_row_dim", type=int, default=256,
                    help="cluster mode: flat model row dimension")
    ap.add_argument("--cluster_rate", type=float, default=2000.0,
                    help="cluster mode: peak offered frames/sec PER "
                         "HOST — the fleet's aggregate offer scales "
                         "with the host count (weak scaling); the "
                         "diurnal profile modulates the instantaneous "
                         "rate")
    ap.add_argument("--cluster_population", type=int, default=4096,
                    help="cluster mode: client-id space, range-"
                         "partitioned across hosts")
    ap.add_argument("--cluster_ingest_pool", type=int, default=2,
                    help="cluster mode: decode-pool workers per host")
    ap.add_argument("--cluster_seed", type=int, default=0,
                    help="cluster mode: one seed drives the swarm "
                         "schedule, the arrival profile, and the chaos "
                         "injector")
    ap.add_argument("--secure_commits", type=int, default=12,
                    help="secure mode: commits per clean arm (the "
                         "byzantine arms run half — the overflow arm "
                         "pays a real deadline wait per commit)")
    ap.add_argument("--secure_cohort", type=int, default=8,
                    help="secure mode: round cohort (= buffer_k; masks "
                         "cancel over the FULL cohort)")
    ap.add_argument("--secure_seed", type=int, default=0,
                    help="secure mode: one seed drives the keyring, "
                         "the DP noise, and the byzantine set")
    ap.add_argument("--cluster_arms", default="clean",
                    help="cluster mode extra arms: add 'sparse' for "
                         "the paired dense-vs-sparse_topk uplink arm "
                         "(v17, ISSUE 19) — the fleet ships k=dim/16 "
                         "(index, value) frames and the servers opt "
                         "into the scatter-fold ingest path")
    args = ap.parse_args()
    # chip-unavailable marker (round-2 outage lesson): emit ONE JSON line
    # with an explicit error field instead of crashing, so the driver
    # artifact distinguishes "no chip" from a perf regression
    ok, detail = _probe_with_retry()
    if not ok:
        print(f"chip unavailable: {detail}", file=sys.stderr)
        print(json.dumps(_stamp({
            "metric": "fedavg_cifar10_resnet18gn_128clients_rounds_per_sec",
            "value": 0.0,
            "unit": "rounds/sec",
            "vs_baseline": 0.0,
            "mode": args.mode,
            # null, not a number: nothing ran, so neither the 1.0
            # no-uploads convention nor the 0.0 transfer-bound reading
            # applies — consumers must not fold this row into trends
            "overlap_fraction": None,
            "h2d_bytes_per_round": None,
            "async": None,
            "ingest": None,
            "chaos": None,
            "attack": None,
            "serve": None,
            "connections": None,
            "multihost": None,
            "cluster": None,
            "secure": None,
            "critical_path": None,
            "slo": None,
            "programs": None,
            "error": "chip_unavailable",
            "detail": detail,
        })))
        return

    import jax

    from fedml_tpu import obs
    from fedml_tpu.utils.profiling import repin_jax_platforms
    repin_jax_platforms()
    # FEDML_OBS_DIR enables the span tracer/flight recorder for this
    # bench run (Chrome trace + Prometheus snapshot land there); the
    # default-off path adds nothing to the timed loop
    obs.configure_from_env()
    # v11 programs block: open the profile window, and run the one-time
    # HLO flop/byte census for the torture/serve modes (their programs
    # are small — one extra AOT compile per family, amortized by the
    # compile cache).  The sync/async modes compile CHIP-sized round
    # programs, where a doubled cold compile costs real minutes — they
    # publish dispatch walls always and MFU only under an explicit
    # FEDML_OBS_CENSUS=1 opt-in.
    from fedml_tpu.obs import programs as obs_programs
    global _PROGRAMS_T0
    if args.mode in ("ingest", "chaos", "serve", "connections",
                     "cluster"):
        obs_programs.enable_census(True)
    _PROGRAMS_T0 = obs_programs.snapshot()
    if args.mode == "ingest":
        _bench_ingest(args)
        return
    if args.mode == "chaos":
        _bench_chaos(args)
        return
    if args.mode == "attack":
        _bench_attack(args)
        return
    if args.mode == "serve":
        _bench_serve(args)
        return
    if args.mode == "connections":
        _bench_connections(args)
        return
    if args.mode == "multihost":
        _bench_multihost(args)
        return
    if args.mode == "cluster":
        _bench_cluster(args)
        return
    if args.mode == "secure":
        _bench_secure(args)
        return
    import jax.numpy as jnp

    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.federated import (FederatedData, build_client_shards,
                                          build_eval_shard)
    from fedml_tpu.models import create_model
    from fedml_tpu.parallel import MeshFedAvgEngine
    from fedml_tpu.parallel.mesh import make_mesh
    from fedml_tpu.utils.config import FedConfig

    print(f"devices: {jax.devices()}", file=sys.stderr)

    cfg = FedConfig(model="resnet18_gn", dataset="cifar10",
                    client_num_in_total=N_CLIENTS,
                    client_num_per_round=N_CLIENTS,
                    epochs=1, batch_size=BATCH_SIZE, lr=0.1,
                    frequency_of_the_test=10_000)

    # synthetic CIFAR10-shaped data (real files aren't in the image; shapes
    # and FLOPs match the real workload exactly)
    rs = np.random.RandomState(0)
    n = N_CLIENTS * SAMPLES_PER_CLIENT
    x = rs.rand(n, 32, 32, 3).astype(np.float32)
    y = rs.randint(0, 10, n).astype(np.int64)
    idx = {i: np.arange(i * SAMPLES_PER_CLIENT, (i + 1) * SAMPLES_PER_CLIENT)
           for i in range(N_CLIENTS)}
    ev = build_eval_shard(x[:BATCH_SIZE], y[:BATCH_SIZE], BATCH_SIZE)
    data = FederatedData(
        train_data_num=n, test_data_num=n, train_global=ev, test_global=ev,
        client_shards=build_client_shards(x, y, idx, BATCH_SIZE),
        client_num_samples=np.full(N_CLIENTS, SAMPLES_PER_CLIENT, np.float32),
        test_client_shards=None, class_num=10, synthetic=True)

    model = create_model("resnet18_gn", output_dim=10)
    # bf16 compute / f32 masters: the MXU fast path (core/trainer.py);
    # batch_unroll=8 unrolls the 13-step batch scan (measured −2.5%:
    # L2U8 1.806 vs L2 1.851, PERF.md round-3 table)
    trainer = ClientTrainer(model, lr=cfg.lr, train_dtype=jnp.bfloat16,
                            batch_unroll=8)

    if args.mode == "async":
        _bench_async(cfg, data, trainer)
        return
    mesh = make_mesh()
    # chunk=2 + bf16 local masters: the measured v5e optimum
    # (tools/profile_bench.py L2 rows; PERF.md round-3 table)
    engine = MeshFedAvgEngine(trainer, data, cfg, mesh=mesh, chunk=2,
                              local_dtype=jnp.bfloat16)

    variables = engine.init_variables()
    server_state = engine.server_init(variables)
    # full participation: the cohort IS the whole client stack — upload it
    # once and drive the streaming round (no per-round device-side gather)
    cohort, weights = engine.stream_cohort(0)
    rng = jax.random.PRNGKey(0)

    def one_round(variables, server_state, rng):
        rng, r = jax.random.split(rng)
        variables, server_state, m = engine.round_fn_streaming(
            variables, server_state, cohort, weights, r)
        return variables, server_state, rng, m

    def force_completion(variables, m):
        """Device→host scalar fetch: the only reliable completion barrier
        on the tunnel platform (block_until_ready can return early there)."""
        jax.block_until_ready(variables)
        return float(m["train_loss"])

    for _ in range(WARMUP_ROUNDS):
        variables, server_state, rng, m = one_round(
            variables, server_state, rng)
    force_completion(variables, m)
    # overlap accounting covers the TIMED window only (the one-time
    # cohort upload above is setup): on this resident-cohort bench the
    # timed rounds do no uploads, so overlap_fraction is 1.0 by
    # definition — the field exists so streaming/block-stream bench
    # variants land in the same BENCH_*.json schema (PERF.md §"Prefetch
    # pipeline")
    engine.transfer_stats.reset()

    import contextlib
    from fedml_tpu.utils.profiling import trace
    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    trace_cm = trace(trace_dir) if trace_dir else contextlib.nullcontext()
    slo_eng = _slo_window()          # v11: judge the timed window
    with trace_cm:
        t0 = time.perf_counter()
        for _ in range(TIMED_ROUNDS):
            variables, server_state, rng, m = one_round(
                variables, server_state, rng)
        last_loss = force_completion(variables, m)
        dt = time.perf_counter() - t0

    rps = TIMED_ROUNDS / dt
    print(f"train_loss={last_loss:.4f} "
          f"{dt / TIMED_ROUNDS:.3f}s/round", file=sys.stderr)
    doc = _stamp({
        "metric": "fedavg_cifar10_resnet18gn_128clients_rounds_per_sec",
        "value": round(rps, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / ESTIMATED_REFERENCE_ROUNDS_PER_SEC, 4),
        "mode": "sync",
        "async": None,
        "ingest": None,
        "chaos": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        "overlap_fraction": round(
            engine.transfer_stats.overlap_fraction(), 4),
        # byte accounting (transfer-compression layer): mean H2D payload
        # bytes per timed round, from the engine's per-instance counter
        # (reset() above zeroed it after the one-time cohort upload) —
        # 0 on this resident path; the stack-dtype A/B lives in
        # tools/profile_bench.py exp_SD512
        "h2d_bytes_per_round": round(
            engine.transfer_stats.h2d_bytes / TIMED_ROUNDS, 1),
        # per-round transfer records (upload/wait/compute walls +
        # overlap, one dict per bracketed round): empty on this
        # resident-cohort path by design — streaming/block-stream bench
        # variants fill it, and the key keeps one schema across them
        "rounds": [
            {k: round(v, 4) for k, v in r.items()}
            for r in engine.transfer_stats.rounds],
        # v6 stage attribution (per-"round" spans on this sync path);
        # null unless the run is traced
        "critical_path": _critical_path_doc(),
        # v11: the default SLO pack over the timed window (the sync
        # bench drives no async server, so most specs read no_data and
        # the block asserts "nothing judged this run unhealthy") + the
        # per-program-family profile
        "slo": _slo_doc({"timed": _slo_close(slo_eng)}),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()                   # trace + metrics into FEDML_OBS_DIR
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# async-mode shape: concurrency 32 / buffer 8 keeps the dispatch-wave
# vmap at a quarter of the sync bench's 128-wide cohort (the async
# engine runs unchunked vmap waves, not the mesh scan) while the
# 4x concurrency/K ratio plus lognormal latencies produces genuine
# staleness — the regime the discount weights exist for.
ASYNC_CONCURRENCY = 32
ASYNC_BUFFER_K = 8
ASYNC_WARMUP_COMMITS = 2
ASYNC_TIMED_COMMITS = 12


def _bench_async(cfg, data, trainer) -> None:
    """committed-updates/sec of the buffered async scheduler on the
    bench workload, under the seeded lognormal-latency lifecycle.
    Latencies are SIMULATED (no sleeps): the wall measures compute —
    dispatch-wave training + staleness-discounted commits."""
    import jax

    from fedml_tpu import obs
    from fedml_tpu.async_ import AsyncFedAvgEngine, LifecycleConfig

    cfg.frequency_of_the_test = 1        # wall_time per commit
    lc = LifecycleConfig(latency="lognormal", latency_scale=1.0,
                         latency_sigma=0.5, heterogeneity=0.5, seed=0)
    engine = AsyncFedAvgEngine(trainer, data, cfg,
                               buffer_k=ASYNC_BUFFER_K,
                               concurrency=ASYNC_CONCURRENCY,
                               staleness="polynomial", staleness_a=0.5,
                               lifecycle_cfg=lc)
    slo_eng = _slo_window()          # v11: one arm = the whole run
    total = ASYNC_WARMUP_COMMITS + ASYNC_TIMED_COMMITS
    variables = engine.run(rounds=total)
    jax.block_until_ready(variables)
    walls = [m["wall_time"] for m in engine.metrics_history]
    dt = walls[total - 1] - walls[ASYNC_WARMUP_COMMITS - 1]
    ups = ASYNC_TIMED_COMMITS / dt
    rep = engine.async_report()
    print(f"{dt / ASYNC_TIMED_COMMITS:.3f}s/commit  "
          f"staleness p50/p95 {rep['staleness_p50']:.0f}/"
          f"{rep['staleness_p95']:.0f}", file=sys.stderr)
    doc = _stamp({
        "metric": ("fedavg_cifar10_resnet18gn_128clients_async_"
                   "committed_updates_per_sec"),
        "value": round(ups, 4),
        "unit": "commits/sec",
        # the sync baseline estimate is a per-ROUND number; an async
        # commit aggregates buffer_k of 128 clients, so cross-mode
        # ratios are not meaningful — recorded as null by design
        "vs_baseline": None,
        "mode": "async",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": {k: (round(v, 4) if isinstance(v, float) else v)
                  for k, v in rep.items()},
        "ingest": None,
        "chaos": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        # v6: commit-to-commit stage attribution from the scheduler's
        # spans (train waves / commits / eval + wait); null untraced
        "critical_path": _critical_path_doc(),
        "slo": _slo_doc({"run": _slo_close(slo_eng)}),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# ingest-mode shape: 8-deep buffer under 32 saturating clients is the
# same 4x oversubscription the async bench runs, and 30 timed commits
# (240 committed updates) keep even the slow legacy arm's wall around a
# minute on a small box.
INGEST_BUFFER_K = 8
INGEST_WARMUP_COMMITS = 5


def _bench_ingest(args) -> None:
    """Concurrent-uplink ingestion torture (ISSUE 6): N in-process
    clients saturate one transport with pre-encoded result frames while
    the server ingests and commits.  Arms: the PR-5 legacy path
    faithfully (inline decode on the recv threads, unbounded inbox,
    drained O(K·P) commit), the same path with ONLY this PR's inbox
    backpressure (queue-discipline isolation), and decode-into +
    streaming aggregation-on-arrival at each --ingest_pools size.  The
    headline is speedup_vs_legacy = best arm / legacy sustained
    committed-updates/sec — the >=2x acceptance gate."""
    from fedml_tpu import obs
    from fedml_tpu.async_.torture import run_ingest_torture

    pools = [int(p) for p in str(args.ingest_pools).split(",") if p.strip()]
    if not pools or any(p < 1 for p in pools):
        # fail BEFORE the two slow legacy arms burn their minutes; pool=0
        # is the inline FSM route, which would mislabel the A/B table
        raise SystemExit(
            f"--ingest_pools must be a comma-separated list of decode-pool "
            f"sizes >= 1, got {args.ingest_pools!r}")
    port = int(os.environ.get("BENCH_INGEST_PORT", "53300"))

    arm_no = [0]

    def run(tag, **kw):
        # fresh port per arm: the previous arm's listener may linger in
        # TIME_WAIT, and a straggler client thread could still be
        # connected to it
        arm_no[0] += 1
        rep = run_ingest_torture(
            n_clients=args.ingest_clients, backend=args.ingest_backend,
            buffer_k=INGEST_BUFFER_K, commits=args.ingest_commits,
            warmup_commits=INGEST_WARMUP_COMMITS,
            base_port=port + arm_no[0], **kw)
        print(f"{tag}: {rep['committed_updates_per_sec']:.1f} updates/s  "
              f"decode p50/p95 {rep['decode_p50_s'] * 1e3:.2f}/"
              f"{rep['decode_p95_s'] * 1e3:.2f} ms  "
              f"lock wait {rep['lock_wait_seconds']:.2f}s", file=sys.stderr)
        return rep

    legacy = run("legacy pool=0", ingest_pool=0, decode_into=False,
                 streaming=False)
    # queue-discipline isolation: the SAME decode+drain path with only
    # this PR's inbox backpressure applied, so the table separates the
    # "stop letting the heap absorb the uplinks" win from the
    # decode-into/streaming win
    bounded = run("legacy bounded-inbox", ingest_pool=0, decode_into=False,
                  streaming=False, inbox_bound=2 * args.ingest_clients)
    arms = [run(f"decode-into pool={p}", ingest_pool=p, decode_into=True,
                streaming=True) for p in pools]
    best = max(arms, key=lambda r: r["committed_updates_per_sec"])
    legacy_ups = legacy["committed_updates_per_sec"]
    doc = _stamp({
        "metric": (f"async_ingest_{args.ingest_backend.lower()}_"
                   f"{args.ingest_clients}clients_"
                   "committed_updates_per_sec"),
        "value": round(best["committed_updates_per_sec"], 4),
        "unit": "updates/sec",
        # the sync baseline estimate prices training FLOPs; the torture
        # path trains nothing — the in-schema comparison is the legacy
        # arm, so vs_baseline stays null by design
        "vs_baseline": None,
        "mode": "ingest",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        "ingest": {
            "backend": legacy["backend"],
            "n_clients": legacy["n_clients"],
            "buffer_k": legacy["buffer_k"],
            "p": legacy["p"],
            "frame_bytes": legacy["frame_bytes"],
            "commits": legacy["commits"],
            "legacy": {k: round(legacy[k], 6) for k in (
                "committed_updates_per_sec", "decode_p50_s",
                "decode_p95_s", "lock_wait_seconds")},
            "legacy_bounded_inbox": {k: round(bounded[k], 6) for k in (
                "committed_updates_per_sec", "decode_p50_s",
                "decode_p95_s", "lock_wait_seconds")},
            "arms": [{
                "ingest_pool": a["ingest_pool"],
                "committed_updates_per_sec": round(
                    a["committed_updates_per_sec"], 4),
                "decode_p50_s": round(a["decode_p50_s"], 6),
                "decode_p95_s": round(a["decode_p95_s"], 6),
                "lock_wait_seconds": round(a["lock_wait_seconds"], 4),
            } for a in arms],
            "speedup_vs_legacy": round(
                best["committed_updates_per_sec"] / legacy_ups, 2)
                if legacy_ups > 0 else None,
        },
        # v11: per-arm SLO verdicts (every ingest arm is clean traffic
        # — breaches here regress) + the program profile
        "slo": _slo_doc({
            "legacy": legacy.get("slo_arm"),
            "legacy_bounded_inbox": bounded.get("slo_arm"),
            **{f"pool_{a['ingest_pool']}": a.get("slo_arm")
               for a in arms},
        }),
        "programs": _programs_doc(),
        # v6: the BEST arm's decode/fold/commit attribution (each
        # torture run computes its own window-scoped report); null
        # untraced
        "critical_path": (
            {k: v for k, v in best["critical_path"].items()
             if k != "rounds"}
            if best.get("critical_path") else None),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# chaos-mode shape: every arm runs the reliable ingest torture (window-
# limited FMLR uplink pushers, decode-into + streaming, pool 4) so the
# curve isolates the FAULTS, not a transport change; 12 commits/arm
# keeps the ~8-arm sweep around a few minutes on a small box.
CHAOS_INGEST_POOL = 4
CHAOS_WARMUP_COMMITS = 2
CHAOS_CURVE_RATES = (0.05, 0.10, 0.20)
CHAOS_MIXED = {"drop": 0.05, "dup": 0.01, "corrupt": 0.005}


def _bench_chaos(args) -> None:
    """Goodput-vs-fault-rate curves (ISSUE 8): the concurrent-uplink
    ingest torture with the reliability envelope ON, under seeded
    wire-level fault injection (fedml_tpu/comm/chaos.py) at the
    server's receive chokepoint.  Arms: a clean reliable baseline, a
    sweep of loss (drop), duplicate and corrupt rates at 5/10/20%, and
    the acceptance-shaped "mixed" arm (5% loss + 1% dup + 0.5%
    corrupt).  Every row reports committed-updates/sec, the goodput
    ratio vs the clean arm, and the retry/dedup/quarantine/recv-death
    counters — the ≥0.5x-of-clean, zero-recv-deaths gate's raw
    numbers."""
    from fedml_tpu import obs
    from fedml_tpu.async_.torture import run_ingest_torture

    port = int(os.environ.get("BENCH_CHAOS_PORT", "53400"))
    arm_no = [0]

    def run(tag, chaos=None):
        arm_no[0] += 1
        rep = run_ingest_torture(
            n_clients=args.chaos_clients, backend=args.chaos_backend,
            buffer_k=INGEST_BUFFER_K, commits=args.chaos_commits,
            warmup_commits=CHAOS_WARMUP_COMMITS,
            ingest_pool=CHAOS_INGEST_POOL, decode_into=True,
            streaming=True, base_port=port + arm_no[0], timeout_s=600,
            reliable=True, chaos=chaos, chaos_seed=args.chaos_seed)
        print(f"{tag}: {rep['committed_updates_per_sec']:.1f} updates/s  "
              f"retries {rep['retries']:.0f}  dups suppressed "
              f"{rep['dups_suppressed']:.0f}  quarantined "
              f"{rep['quarantined']:.0f}  recv deaths "
              f"{rep['recv_thread_deaths']:.0f}", file=sys.stderr)
        return rep

    def row(rep, clean_ups, **rates):
        return {
            "drop": rates.get("drop", 0.0),
            "dup": rates.get("dup", 0.0),
            "corrupt": rates.get("corrupt", 0.0),
            "committed_updates_per_sec": round(
                rep["committed_updates_per_sec"], 4),
            "goodput_ratio": round(
                rep["committed_updates_per_sec"] / clean_ups, 4)
                if clean_ups > 0 else None,
            "retries": rep["retries"],
            "dups_suppressed": rep["dups_suppressed"],
            "quarantined": rep["quarantined"],
            "abandoned": rep["abandoned"],
            "recv_thread_deaths": rep["recv_thread_deaths"],
            "chaos_injected": rep["chaos_injected"],
        }

    slo_arms: dict = {}
    clean = run("clean reliable")
    slo_arms["clean"] = clean.get("slo_arm")
    clean_ups = clean["committed_updates_per_sec"]
    curve = []
    for key in ("drop", "dup", "corrupt"):
        for rate in CHAOS_CURVE_RATES:
            rep = run(f"{key}_{int(rate * 100)}", {key: rate})
            # "curve_" prefix: bench_diff treats these as
            # breach-by-design fault arms, never clean ones
            slo_arms[f"curve_{key}_{int(rate * 100)}"] = \
                rep.get("slo_arm")
            curve.append(row(rep, clean_ups, **{key: rate}))
    mixed = run("mixed (5% loss + 1% dup + 0.5% corrupt)",
                dict(CHAOS_MIXED))
    slo_arms["mixed"] = mixed.get("slo_arm")
    doc = _stamp({
        "metric": (f"async_chaos_{args.chaos_backend.lower()}_"
                   f"{args.chaos_clients}clients_"
                   "committed_updates_per_sec"),
        "value": round(mixed["committed_updates_per_sec"], 4),
        "unit": "updates/sec",
        # the in-schema comparison is the clean reliable arm
        "vs_baseline": None,
        "mode": "chaos",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        "chaos": {
            "backend": clean["backend"],
            "n_clients": clean["n_clients"],
            "buffer_k": clean["buffer_k"],
            "p": clean["p"],
            "frame_bytes": clean["frame_bytes"],
            "commits": clean["commits"],
            "seed": args.chaos_seed,
            "clean": row(clean, clean_ups),
            "curve": curve,
            "mixed": row(mixed, clean_ups, **CHAOS_MIXED),
            "goodput_vs_clean": round(
                mixed["committed_updates_per_sec"] / clean_ups, 4)
                if clean_ups > 0 else None,
        },
        "critical_path": (
            {k: v for k, v in mixed["critical_path"].items()
             if k != "rounds"}
            if mixed.get("critical_path") else None),
        "slo": _slo_doc(slo_arms),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# attack-mode shape (ISSUE 9): the accuracy matrix runs the SAME
# synthetic MNIST-LR async workload the quality bands calibrate
# (1000 clients, buffer K=8, concurrency 16, polynomial staleness,
# lognormal latency), so matrix rows are directly band-comparable;
# the defense arm is the band's defense config.  The overhead pair
# reruns the ingest torture with the admission screen on vs off —
# honest traffic only, so quarantines there are false positives by
# definition and the throughput ratio isolates the screen's cost.
ATTACK_FRAC = 0.2
ATTACK_BOOST = 20.0
# the MIXED arm runs the quality-band calibration shape EXACTLY
# (benchmarks/quality_bands.json async_mnist_lr_attacked_*: boost β=8,
# poison_frac 1.0) so its defended/undefended accuracies are directly
# band-comparable; the other matrix rows explore at ATTACK_BOOST
ATTACK_BAND_BOOST = 8.0
ATTACK_BAND_POISON = 1.0
ATTACK_MATRIX_MODES = ("signflip", "boost", "gaussian", "labelflip",
                       "mixed")
ATTACK_DEFENSE = dict(norm_bound=2.0, screen=True, z_max=8.0,
                      cos_min=-1.0, screen_warmup=10, buckets=4, trim_k=0)
ATTACK_OVERHEAD_COMMITS = 20


def _bench_attack(args) -> None:
    """Attack x defense accuracy/goodput matrix (ISSUE 9): every
    adversary family from fedml_tpu/async_/adversary.py against the
    admission pipeline + bucketed robust commit, on the async MNIST-LR
    quality-band workload, plus the admission-overhead ingest pair.
    Gates: the mixed defended arm stays within the clean band while
    undefended degrades, zero honest quarantines in the clean arm;
    the overhead pair's throughput_ratio prices the fused screen
    (>= 0.9x on chip, ~0.73x paired-median on the fold-bottlenecked
    2-core CI box — PERF.md "Adversarial robustness")."""
    import jax

    from fedml_tpu import obs
    from fedml_tpu.async_ import (AsyncFedAvgEngine, AttackConfig,
                                  DefenseConfig, LifecycleConfig)
    from fedml_tpu.async_.torture import run_ingest_torture
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.loaders import load_data
    from fedml_tpu.models import create_model
    from fedml_tpu.utils.config import FedConfig

    data = load_data("mnist", client_num_in_total=1000, batch_size=10,
                     synthetic_scale=0.2, seed=0)
    cfg = FedConfig(client_num_in_total=1000, client_num_per_round=16,
                    comm_round=args.attack_commits, epochs=1,
                    batch_size=10, lr=0.03, frequency_of_the_test=10_000)
    lc = LifecycleConfig(latency="lognormal", latency_scale=1.0,
                         latency_sigma=0.8, heterogeneity=0.5, seed=0)

    def arm(tag, attack_mode, defended):
        trainer = ClientTrainer(create_model("lr", output_dim=10),
                                lr=cfg.lr)
        attack = None
        if attack_mode == "mixed":
            attack = AttackConfig(mode="mixed", frac=ATTACK_FRAC,
                                  boost=ATTACK_BAND_BOOST,
                                  poison_frac=ATTACK_BAND_POISON,
                                  seed=args.attack_seed)
        elif attack_mode != "none":
            attack = AttackConfig(mode=attack_mode, frac=ATTACK_FRAC,
                                  boost=ATTACK_BOOST,
                                  seed=args.attack_seed)
        defense = (DefenseConfig(**ATTACK_DEFENSE) if defended else None)
        eng = AsyncFedAvgEngine(trainer, data, cfg, buffer_k=8,
                                concurrency=16, staleness="polynomial",
                                staleness_a=0.5, lifecycle_cfg=lc,
                                attack=attack, defense=defense)
        v = eng.run(rounds=args.attack_commits)
        acc = float(eng.evaluate(v)["test_acc"])
        rep = eng.async_report()
        attrib = eng.quarantine_attribution()
        print(f"{tag}: acc {acc:.3f}  quarantined "
              f"{rep.get('quarantined_total', 0)} "
              f"(byz {attrib['byzantine']} / honest {attrib['honest']})",
              file=sys.stderr)
        return {"attack": attack_mode, "defended": defended,
                "test_acc": round(acc, 4),
                "quarantined": rep.get("quarantined", {}),
                "quarantined_total": rep.get("quarantined_total", 0),
                "quarantined_byzantine": attrib["byzantine"],
                "quarantined_honest": attrib["honest"],
                "byzantine_clients": rep.get("byzantine_clients", 0)}

    clean = arm("clean undefended", "none", False)
    clean_def = arm("clean defended", "none", True)
    matrix = []
    for mode in ATTACK_MATRIX_MODES:
        matrix.append(arm(f"{mode} undefended", mode, False))
        matrix.append(arm(f"{mode} defended", mode, True))
    mixed_und = next(r for r in matrix
                     if r["attack"] == "mixed" and not r["defended"])
    mixed_def = next(r for r in matrix
                     if r["attack"] == "mixed" and r["defended"])

    # admission-overhead pair: honest ingest torture, screen off vs on
    port = int(os.environ.get("BENCH_ATTACK_PORT", "53500"))
    off = run_ingest_torture(
        n_clients=args.attack_ingest_clients, backend=args.attack_backend,
        buffer_k=INGEST_BUFFER_K, commits=ATTACK_OVERHEAD_COMMITS,
        warmup_commits=3, ingest_pool=4, decode_into=True, streaming=True,
        base_port=port + 1)
    on = run_ingest_torture(
        n_clients=args.attack_ingest_clients, backend=args.attack_backend,
        buffer_k=INGEST_BUFFER_K, commits=ATTACK_OVERHEAD_COMMITS,
        warmup_commits=3, ingest_pool=4, decode_into=True, streaming=True,
        base_port=port + 2,
        defense=DefenseConfig(screen=True, z_max=8.0, screen_warmup=8))
    ratio = (on["committed_updates_per_sec"]
             / off["committed_updates_per_sec"]
             if off["committed_updates_per_sec"] > 0 else None)
    print(f"overhead: screen-off {off['committed_updates_per_sec']:.1f} "
          f"-> screen-on {on['committed_updates_per_sec']:.1f} updates/s "
          f"(ratio {f'{ratio:.2f}' if ratio is not None else 'n/a'}; "
          f"chip gate >= 0.9)  false-positive "
          f"quarantines {on['admission']['quarantined_total']}",
          file=sys.stderr)

    doc = _stamp({
        "metric": "async_attack_mnist_lr_defended_acc",
        "value": mixed_def["test_acc"],
        "unit": "accuracy",
        # the in-schema comparisons are the clean and undefended arms
        "vs_baseline": None,
        "mode": "attack",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "chaos": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        "attack": {
            "workload": "async_mnist_lr (quality-band shape, K=8, "
                        "conc 16, poly a=0.5)",
            "frac": ATTACK_FRAC,
            "boost": ATTACK_BOOST,
            "seed": args.attack_seed,
            "defense": dict(ATTACK_DEFENSE),
            "clean_acc": clean["test_acc"],
            "clean_defended_acc": clean_def["test_acc"],
            "defended_acc": mixed_def["test_acc"],
            "undefended_acc": mixed_und["test_acc"],
            "false_positive_quarantines":
                clean_def["quarantined_honest"],
            "matrix": [clean, clean_def] + matrix,
            "overhead": {
                "backend": off["backend"],
                "n_clients": off["n_clients"],
                "screen_off_updates_per_sec": round(
                    off["committed_updates_per_sec"], 4),
                "screen_on_updates_per_sec": round(
                    on["committed_updates_per_sec"], 4),
                "throughput_ratio": (round(ratio, 4)
                                     if ratio is not None else None),
                "screen_on_quarantined":
                    on["admission"]["quarantined_total"],
            },
        },
        "critical_path": _critical_path_doc(),
        # v11: the overhead pair is honest traffic — its SLO arms are
        # clean; the accuracy matrix runs in-process (no comm metrics)
        "slo": _slo_doc({"overhead_screen_off": off.get("slo_arm"),
                         "overhead_screen_on": on.get("slo_arm")}),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# secure-mode shape (ISSUE 20): the clean arms share one workload
# (async MNIST-LR, full-cohort barrier, INPROC, no lifecycle latency)
# so the plain/secure pair isolates the DATA PLANE — quantize + mask +
# field fold + unmask vs flatten + f32 fold.  Byzantine arms run the
# same workload with a boost adversary at two magnitudes: one inside
# the ENFORCED quantizer bound — since the REVIEW fix that is the
# per-client cohort-headroom slice (p−1)//(2K·scale), |w·x| < 2048 at
# cohort 8 / scale 2^16, NOT the field half-range — and one past it
# (the range refusal that survives masking).  The in-field boost must
# clear that slice with margin or the arm's attackers are refused at
# quantize, never upload, and the no-deadline barrier stalls: boost 8
# keeps this workload's rows at ~55% of the bound (boost 50 is now
# correctly refused — the headroom guard catching sum-aliasing rows
# the old per-word bound let through).
SECURE_BYZ_FRAC = 0.25
SECURE_BYZ_BOOST_INFIELD = 8.0
SECURE_BYZ_BOOST_OVERFLOW = 1e9
SECURE_OVERFLOW_DEADLINE_S = 0.5


def _bench_secure(args) -> None:
    """Privacy-tax bench for the pairwise-mask data plane (ISSUE 20,
    fedml_tpu/secure/): plain vs masked committed-updates/sec on the
    live async messaging FSM plus the end-to-end private mode's
    accuracy cost, the masks-cancel bitwise protocol pin, and the
    masked-byzantine pair.  Gates (tools/bench_diff.py v18): the tax
    ratio stays above the floor, zero below-threshold commits on the
    clean arms, and the bitwise pin holds."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu import obs
    from fedml_tpu.async_ import AttackConfig
    from fedml_tpu.async_.lifecycle import run_async_messaging
    from fedml_tpu.core import mpc
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.loaders import load_data
    from fedml_tpu.models import create_model
    from fedml_tpu.secure import SecAggConfig, SecureAggregator
    from fedml_tpu.utils.config import FedConfig

    cohort = args.secure_cohort
    data = load_data("mnist", client_num_in_total=cohort, batch_size=10,
                     synthetic_scale=0.2, seed=0)

    def arm(tag, commits, secure=None, attack=None, deadline=None):
        cfg = FedConfig(client_num_in_total=cohort,
                        client_num_per_round=cohort, comm_round=commits,
                        epochs=1, batch_size=10, lr=0.03,
                        frequency_of_the_test=10_000)
        trainer = ClientTrainer(create_model("lr", output_dim=10),
                                lr=cfg.lr)
        slo_eng = _slo_window()
        t0 = time.perf_counter()
        variables, server = run_async_messaging(
            trainer, data, cfg, buffer_k=cohort, worker_num=cohort,
            total_commits=commits, secure=secure, attack=attack,
            deadline_s=deadline)
        wall = time.perf_counter() - t0
        sums = jax.jit(trainer.evaluate)(
            variables, jax.tree.map(jnp.asarray, data.test_global))
        cnt = max(float(sums["count"]), 1.0)
        row = {"arm": tag,
               "commits": server.version,
               "updates_per_sec": round(server.updates_committed / wall,
                                        4),
               "test_acc": round(float(sums["correct"]) / cnt, 4),
               "slo_arm": _slo_close(slo_eng)}
        if secure is not None:
            rep = server._secure.report()
            row.update(
                below_threshold_commits=server.secure_below_threshold,
                recovered_rounds=rep["recovered_rounds"],
                rejected_uplinks=int(
                    obs.counter("secagg_rejected_uplinks_total").value))
        print(f"{tag}: {row['updates_per_sec']:.1f} updates/s  "
              f"acc {row['test_acc']:.3f}", file=sys.stderr)
        return row

    def _sec_cfg(**kw):
        return SecAggConfig(seed=args.secure_seed, **kw)

    commits = args.secure_commits
    plain = arm("plain", commits)
    sec = arm("secure", commits, secure=_sec_cfg())
    dp = arm("secure_dp", commits,
             secure=_sec_cfg(dp_clip=3.0, dp_noise=1e-3))
    byz_kw = dict(frac=SECURE_BYZ_FRAC, seed=args.secure_seed)
    rej0 = int(obs.counter("secagg_rejected_uplinks_total").value)
    infield = arm(
        "byz_infield", max(commits // 2, 2),
        secure=_sec_cfg(),
        attack=AttackConfig(mode="boost",
                            boost=SECURE_BYZ_BOOST_INFIELD, **byz_kw))
    overflow = arm(
        "byz_overflow", max(commits // 2, 2),
        secure=_sec_cfg(),
        attack=AttackConfig(mode="boost",
                            boost=SECURE_BYZ_BOOST_OVERFLOW, **byz_kw),
        deadline=SECURE_OVERFLOW_DEADLINE_S)
    # the counter is process-global: attribute the deltas per arm
    overflow["rejected_uplinks"] -= infield["rejected_uplinks"]
    infield["rejected_uplinks"] -= rej0

    tax = (sec["updates_per_sec"] / plain["updates_per_sec"]
           if plain["updates_per_sec"] > 0 else None)
    print(f"privacy tax: plain {plain['updates_per_sec']:.1f} -> "
          f"masked {sec['updates_per_sec']:.1f} updates/s "
          f"(ratio {f'{tax:.2f}' if tax is not None else 'n/a'})",
          file=sys.stderr)

    # masks-cancel protocol pin, pure integers outside the FSM: a
    # full-cohort masked field sum must equal the plain fixed-point
    # sum BITWISE — masks cancel exactly or not at all
    pin_cfg = _sec_cfg()
    pin_dim, pin_ids = 64, list(range(1, 6))
    pin = SecureAggregator(pin_cfg, pin_ids, pin_dim)
    rs = np.random.RandomState(args.secure_seed + 5)
    p = pin_cfg.prime
    expected = np.zeros(pin_dim + 1, np.int64)
    for c in pin_ids:
        pin.escrow(c)
        flat = rs.randn(pin_dim) * 0.1
        w = float(rs.randint(1, 50))
        q = np.empty(pin_dim + 1, np.int64)
        q[:pin_dim] = mpc.quantize(flat * w, pin_cfg.scale, p)
        q[pin_dim] = mpc.quantize(np.array([w]), pin_cfg.scale, p)[0]
        expected = (expected + q) % p
        pin.fold(c, pin.client_row(c, 0, flat, w))
    words, _included = pin.field_sum(0, pin.arrived)
    masks_cancel = bool(np.array_equal(np.asarray(words) % p, expected))
    print(f"masks cancel bitwise: {masks_cancel}", file=sys.stderr)

    # uplink bytes, measured on REAL encoded frames (the INPROC runs
    # above never serialize): one plain-path uplink (f32 pytree +
    # plaintext sample count) vs one masked uplink (u32 field words,
    # dim+1 — the weight rides as the masked trailing word) through
    # MessageCodec.encode, framed exactly as lifecycle.py ships them
    from fedml_tpu.async_.staleness import flat_dim
    from fedml_tpu.comm.message import Message, MessageCodec
    bytes_vars = ClientTrainer(create_model("lr", output_dim=10),
                               lr=0.03).init(
        jax.random.PRNGKey(0), jnp.asarray(data.client_shards["x"][0, 0]))
    dim = flat_dim(bytes_vars)
    m_plain = Message(4, 1, 0)
    m_plain.add_params("model_params",
                       jax.tree.map(np.asarray, bytes_vars))
    m_plain.add_params("num_samples", 50.0)
    m_plain.add_params("version", 0)
    plain_bytes = len(MessageCodec.encode(m_plain))
    m_sec = Message(4, 1, 0)
    m_sec.add_params("model_params",
                     rs.randint(0, p, dim + 1).astype(np.uint32))
    m_sec.add_params("num_samples", 1.0)
    m_sec.add_params("secagg", {"round": 0})
    m_sec.add_params("version", 0)
    m_sec.set_wire_transport("model_params", "secagg",
                             scale=pin_cfg.scale, p=p)
    sec_bytes = len(MessageCodec.encode(m_sec))
    print(f"uplink frame: plain {plain_bytes} B -> masked {sec_bytes} B "
          f"(dim {dim}; masked words are incompressible by design)",
          file=sys.stderr)

    doc = _stamp({
        "metric": "secure_agg_mnist_lr_privacy_tax_ratio",
        "value": round(tax, 4) if tax is not None else None,
        "unit": "ratio",
        "vs_baseline": None,
        "mode": "secure",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "chaos": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        "secure": {
            "workload": f"async_mnist_lr (INPROC, cohort {cohort}, "
                        "full-cohort barrier, no lifecycle latency)",
            "cohort": cohort,
            "threshold": pin_cfg.resolve_threshold(cohort),
            "scale": pin_cfg.scale,
            "seed": args.secure_seed,
            "privacy_tax_ratio": (round(tax, 4)
                                  if tax is not None else None),
            "plain_updates_per_sec": plain["updates_per_sec"],
            "secure_updates_per_sec": sec["updates_per_sec"],
            "plain_uplink_bytes": plain_bytes,
            "secure_uplink_bytes": sec_bytes,
            "uplink_bytes_ratio": round(sec_bytes / plain_bytes, 4),
            "flat_dim": dim,
            "plain_acc": plain["test_acc"],
            "secure_acc": sec["test_acc"],
            "dp_acc": dp["test_acc"],
            "acc_delta_secure_vs_plain": round(
                sec["test_acc"] - plain["test_acc"], 4),
            "masks_cancel_bitwise_ok": masks_cancel,
            "below_threshold_commits_clean": (
                sec["below_threshold_commits"]
                + dp["below_threshold_commits"]),
            "byzantine": {
                "frac": SECURE_BYZ_FRAC,
                # admission screening reads plaintext rows and is
                # BLINDED under masks: the in-field boost commits
                # unimpeded (its damage shows in test_acc); the only
                # surviving enforcement is the client-side quantizer
                # range refusal, which the overflow boost trips
                "infield": infield,
                "overflow": overflow,
            },
            "arms": [plain, sec, dp, infield, overflow],
        },
        "critical_path": _critical_path_doc(),
        "slo": _slo_doc({r["arm"]: r.pop("slo_arm")
                         for r in (plain, sec, dp, infield, overflow)}),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# serve-mode shape (ISSUE 10): one virtual-time serve-loop arm per
# simulated population, same buffer/arrival/sampler config across arms,
# so the table isolates POPULATION — the north star's heavy-traffic
# axis.  The sub-linear gate is the registry's allocated bytes per
# client (<= ~100 B; 29 B at the current field set), asserted per arm.
SERVE_WARMUP_COMMITS = 4
SERVE_BYTES_PER_CLIENT_GATE = 100.0


def _bench_serve(args) -> None:
    """Million-client serving-spine bench (ISSUE 10, fedml_tpu/scale/):
    sustained committed-updates/sec and server memory versus simulated
    client population.  Each arm drives the REAL PR-6 streaming
    buffer/commit through the sharded registry + streaming cohort
    sampler under a seeded arrival process in virtual time; client
    compute is out of scope (pre-generated update rows), so the wall
    prices the SERVER round hot path.  Gates: registry bytes/client
    <= ~100 at every population (sub-linear memory), updates/sec
    sustained (the 1M arm within 2x of the 10k arm on a healthy
    box)."""
    from fedml_tpu import obs
    from fedml_tpu.scale import ArrivalConfig, run_serve_sim

    pops = sorted(int(p) for p in str(args.serve_populations).split(",")
                  if p.strip())
    if not pops or pops[0] < 1:
        raise SystemExit(
            f"--serve_populations must be a comma-separated list of "
            f"positive client counts, got {args.serve_populations!r}")
    # sorted above: the headline row and sustain_ratio_vs_smallest
    # assume rows[-1] is the LARGEST population
    arrival = ArrivalConfig(mode=args.serve_arrivals, rate=2000.0,
                            period_s=600.0, amplitude=0.8,
                            flash_at_s=5.0, flash_duration_s=10.0,
                            flash_boost=5.0, seed=args.serve_seed)
    rows = []
    slo_arms: dict = {}
    for pop in pops:
        slo_eng = _slo_window()      # v11: one arm per population
        rep = run_serve_sim(
            pop, commits=args.serve_commits,
            warmup_commits=SERVE_WARMUP_COMMITS,
            buffer_k=args.serve_buffer_k, row_dim=args.serve_row_dim,
            sampler_mode=args.serve_sampler, arrival=arrival,
            dropout_prob=0.02, banned_frac=0.01, seed=args.serve_seed)
        slo_arms[f"pop_{pop}"] = _slo_close(slo_eng)
        rep["sublinear_ok"] = bool(
            rep["registry_bytes_per_client"] <= SERVE_BYTES_PER_CLIENT_GATE)
        print(f"serve pop={pop}: "
              f"{rep['committed_updates_per_sec']:.0f} updates/s  "
              f"registry {rep['registry_bytes'] / 1e6:.1f} MB "
              f"({rep['registry_bytes_per_client']:.1f} B/client)  "
              f"rss {rep['rss_bytes'] / 1e6:.0f} MB  virtual "
              f"{rep['virtual_time_s']:.1f}s", file=sys.stderr)
        rows.append(rep)
    head = rows[-1]            # the largest population is the headline
    doc = _stamp({
        "metric": (f"serve_spine_{head['population']}clients_"
                   "committed_updates_per_sec"),
        "value": round(head["committed_updates_per_sec"], 4),
        "unit": "updates/sec",
        # the in-schema comparison is across the population arms
        "vs_baseline": None,
        "mode": "serve",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "chaos": None,
        "attack": None,
        "connections": None,
        "multihost": None,
        "cluster": None,
        "serve": {
            "buffer_k": args.serve_buffer_k,
            "row_dim": args.serve_row_dim,
            "sampler_mode": args.serve_sampler,
            "arrival_mode": args.serve_arrivals,
            "commits": args.serve_commits,
            "seed": args.serve_seed,
            "bytes_per_client_gate": SERVE_BYTES_PER_CLIENT_GATE,
            "populations": [{
                "population": r["population"],
                "committed_updates_per_sec": round(
                    r["committed_updates_per_sec"], 4),
                "registry_bytes": r["registry_bytes"],
                "registry_bytes_per_client": round(
                    r["registry_bytes_per_client"], 2),
                "registry_shards_allocated":
                    r["registry_shards_allocated"],
                "sampler_peak_scratch_bytes":
                    r["sampler_peak_scratch_bytes"],
                "rss_bytes": r["rss_bytes"],
                "virtual_time_s": round(r["virtual_time_s"], 3),
                "mean_arrival_rate": round(r["mean_arrival_rate"], 2),
                "crashed": r["crashed"],
                "banned": r["banned"],
                "sublinear_ok": r["sublinear_ok"],
            } for r in rows],
            "sublinear_ok": all(r["sublinear_ok"] for r in rows),
            "sustain_ratio_vs_smallest": round(
                head["committed_updates_per_sec"]
                / rows[0]["committed_updates_per_sec"], 4)
                if rows[0]["committed_updates_per_sec"] > 0 else None,
        },
        "critical_path": _critical_path_doc(),
        "slo": _slo_doc(slo_arms),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# connections-mode shape (ISSUE 11): every arm runs the SAME reactor
# config, buffer, pool and offered rate, so the table isolates the
# live-connection count and the overload scenario.  The mixed-chaos
# rates mirror the PR-8 acceptance shape; the storm arm adds the
# connection storm (every SYN at once) + reconnect churn on top of the
# same chaos — the acceptance arm of the >= 0.5x-of-clean gate.
CONN_WARMUP_COMMITS = 3
CONN_CHAOS = {"drop": 0.05, "dup": 0.01, "corrupt": 0.005}
CONN_CHURN_LIFETIME_S = 5.0


def _bench_connections(args) -> None:
    """Live-connection reactor bench (ISSUE 11, fedml_tpu/comm/
    reactor.py + connswarm.py): N live sockets against the selector
    reactor transport — a swarm keeps every connection open with paced
    FMLR-enveloped uplinks while the server reassembles, dedups, acks
    and commits.  Arms per count: clean, mixed-chaos (5% loss + 1% dup
    + 0.5% corrupt at the receive chokepoint) and storm (the same
    chaos + a connection storm + seeded reconnect churn).  Gates:
    storm sustains >= 0.5x clean committed-updates/sec, zero recv-
    thread deaths, zero leaked FDs, every shed/evicted uplink
    accounted."""
    from fedml_tpu import obs
    from fedml_tpu.async_.torture import run_connection_torture

    counts = sorted(int(c) for c in str(args.conn_counts).split(",")
                    if c.strip())
    if not counts or counts[0] < 1:
        raise SystemExit(
            f"--conn_counts must be a comma-separated list of positive "
            f"connection counts, got {args.conn_counts!r}")
    port = int(os.environ.get("BENCH_CONN_PORT", "53700"))
    arm_no = [0]

    def run(tag, n, **kw):
        arm_no[0] += 1
        rep = run_connection_torture(
            n_connections=n, buffer_k=args.conn_buffer_k,
            commits=args.conn_commits, warmup_commits=CONN_WARMUP_COMMITS,
            ingest_pool=args.conn_pool, offered_rate=args.conn_rate,
            base_port=port + arm_no[0], timeout_s=900,
            seed=args.conn_seed, chaos_seed=args.conn_seed, **kw)
        ev = rep["evicted"]
        print(f"{tag}: {rep['committed_updates_per_sec']:.1f} updates/s  "
              f"admission p95 {rep['admission_p95_s'] * 1e3:.1f} ms  "
              f"peak {rep['open_connections_peak']} conns  evicted "
              f"stall/rate/shed {ev['stall']:.0f}/{ev['rate']:.0f}/"
              f"{ev['shed']:.0f}  shed {rep['uplinks_shed']:.0f}  "
              f"fd leak {rep['fd_leaked']}  recv deaths "
              f"{rep['recv_thread_deaths']:.0f}", file=sys.stderr)
        return rep

    def arm_doc(rep):
        return {
            "committed_updates_per_sec": round(
                rep["committed_updates_per_sec"], 4),
            "admission_p50_s": round(rep["admission_p50_s"], 6),
            "admission_p95_s": round(rep["admission_p95_s"], 6),
            "loop_lag_p95_s": round(rep["loop_lag_p95_s"], 6),
            "open_connections_peak": rep["open_connections_peak"],
            "evicted": rep["evicted"],
            "uplinks_shed": rep["uplinks_shed"],
            "connections_drained": rep["connections_drained"],
            "recv_thread_deaths": rep["recv_thread_deaths"],
            "dups_suppressed": rep["dups_suppressed"],
            "quarantined": rep["quarantined"],
            "fd_leaked": rep["fd_leaked"],
            "chaos_injected": rep["chaos_injected"],
            "swarm": rep["swarm"],
        }

    rows = []
    slo_arms: dict = {}
    for n in counts:
        clean = run(f"n={n} clean", n)
        chaosr = run(f"n={n} chaos", n, chaos=dict(CONN_CHAOS))
        storm = run(f"n={n} storm", n, chaos=dict(CONN_CHAOS),
                    storm=True, churn_lifetime_s=CONN_CHURN_LIFETIME_S)
        slo_arms[f"n{n}_clean"] = clean.get("slo_arm")
        slo_arms[f"n{n}_chaos"] = chaosr.get("slo_arm")
        slo_arms[f"n{n}_storm"] = storm.get("slo_arm")
        clean_ups = clean["committed_updates_per_sec"]
        rows.append({
            "n_connections": n,
            "clean": arm_doc(clean),
            "chaos": arm_doc(chaosr),
            "storm": arm_doc(storm),
            "storm_goodput_ratio": round(
                storm["committed_updates_per_sec"] / clean_ups, 4)
                if clean_ups > 0 else None,
        })
    head = rows[-1]
    doc = _stamp({
        "metric": (f"reactor_{head['n_connections']}conns_storm_"
                   "committed_updates_per_sec"),
        "value": head["storm"]["committed_updates_per_sec"],
        "unit": "updates/sec",
        # the in-schema comparison is the same count's clean arm
        "vs_baseline": None,
        "mode": "connections",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "chaos": None,
        "attack": None,
        "serve": None,
        "connections": {
            "buffer_k": args.conn_buffer_k,
            "ingest_pool": args.conn_pool,
            "offered_rate": args.conn_rate,
            "commits": args.conn_commits,
            "seed": args.conn_seed,
            "chaos_rates": dict(CONN_CHAOS),
            "churn_lifetime_s": CONN_CHURN_LIFETIME_S,
            "rows": rows,
            "storm_goodput_ratio": head["storm_goodput_ratio"],
        },
        "cluster": None,
        "secure": None,
        "critical_path": _critical_path_doc(),
        "slo": _slo_doc(slo_arms),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


# multihost-mode shape (ISSUE 13): weak scaling — per-process work is
# CONSTANT (one client block per process: mh_clients_per_block
# population, mh_k_per_block sampled per round), so the ideal curve is
# flat rounds/sec while total clients/round grows with the process
# count.  On the 2-core box 2+ processes oversubscribe the cores and
# the carry rides loopback TCP, so >= 0.5x at 2 processes is the
# documented GIL/gloo floor; the chip gate rides exp_POD (chip queue
# step 15) where each process owns real chips and the carry rides DCN.
MH_BITWISE_ROUNDS = 3


def _bench_multihost(args) -> None:
    """Weak-scaling sweep of the two-level multihost runtime: one
    spawned cluster per process count, each rank reporting rounds/sec
    and carry-allreduce bytes (fedml_tpu/parallel/mh_worker.py), plus
    the 1-vs-2-process same-block-partition bitwise commit pin."""
    import tempfile

    from fedml_tpu import obs
    from fedml_tpu.parallel.multihost import (MultihostLaunchError,
                                              spawn_cluster_report)

    procs_list = sorted({int(p) for p in str(args.mh_procs).split(",")
                         if p.strip()})
    if not procs_list or procs_list[0] < 1:
        raise SystemExit(f"--mh_procs must be positive process counts, "
                         f"got {args.mh_procs!r}")
    if args.mh_rounds <= args.mh_warmup:
        raise SystemExit(f"--mh_rounds ({args.mh_rounds}) must exceed "
                         f"--mh_warmup ({args.mh_warmup})")
    arms = {a.strip() for a in str(args.mh_arms).split(",") if a.strip()}
    bad_arms = arms - {"weak", "bitwise", "chaos", "compress", "sparse"}
    if bad_arms or not arms:
        raise SystemExit(f"--mh_arms must be a non-empty subset of "
                         f"weak,bitwise,chaos,compress,sparse; got "
                         f"{args.mh_arms!r}")
    if args.mh_chaos_procs < 2:
        raise SystemExit(f"--mh_chaos_procs must be >= 2 (someone has "
                         f"to die AND someone has to survive), got "
                         f"{args.mh_chaos_procs}")

    def run_arm(procs: int, n_blocks: int, rounds: int, modes: list,
                extra_cfg: Optional[dict] = None, elastic: bool = False,
                expect_ranks: Optional[set] = None) -> tuple:
        """Spawn one cluster; returns ({rank: worker JSON doc},
        per-rank outcome report from spawn_cluster_report)."""
        cfg = {
            "clients": args.mh_clients_per_block * n_blocks,
            "spc": 24, "dim": args.mh_dim, "classes": 10,
            "k_per_round": args.mh_k_per_block * n_blocks,
            "n_blocks": n_blocks, "rounds": rounds,
            "warmup": args.mh_warmup, "seed": args.mh_seed,
            "modes": modes, "local_devices": args.mh_local_devices,
            **(extra_cfg or {}),
        }
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(cfg, f)
            path = f.name
        try:
            outs, report = spawn_cluster_report(
                [sys.executable, "-m", "fedml_tpu.parallel.mh_worker",
                 path], procs, timeout_s=900.0, elastic=elastic)
        finally:
            os.unlink(path)
        docs = {}
        for out in outs:
            for line in out.splitlines():
                if line.startswith("{"):
                    d = json.loads(line)
                    docs[d["rank"]] = d
        expect = (set(range(procs)) if expect_ranks is None
                  else expect_ranks)
        if not expect <= set(docs):
            raise MultihostLaunchError(
                f"rank(s) {sorted(expect - set(docs))} never reported "
                f"({len(docs)}/{procs} docs); per-rank: "
                f"{report['ranks']}")
        return docs, report

    slo_eng = _slo_window()
    rows = []
    deaths_total = 0
    for n in (procs_list if "weak" in arms else []):
        try:
            docs, _rep = run_arm(n, n, args.mh_rounds, ["streaming"])
        except MultihostLaunchError as e:
            print(f"multihost arm procs={n} FAILED: {e}",
                  file=sys.stderr)
            deaths_total += 1
            rows.append({"procs": n, "n_blocks": n, "error": str(e),
                         "process_deaths": 1})
            continue
        d0 = docs[0]
        agree = all(docs[r]["digests"] == d0["digests"]
                    for r in docs)
        row = {
            "procs": n,
            "n_blocks": n,
            "clients_per_round": args.mh_k_per_block * n,
            "population": args.mh_clients_per_block * n,
            "rounds_per_sec": round(d0["rounds_per_sec"], 4),
            "round_wall_p50_s": round(
                d0["per_mode"]["streaming"]["round_wall_p50_s"], 5),
            "carry_allreduce_bytes_per_round": round(
                max(docs[r]["carry_allreduce_bytes_per_round"]
                    for r in docs), 1),
            "ranks_agree": bool(agree),
            "process_deaths": 0,
        }
        print(f"multihost procs={n}: "
              f"{row['rounds_per_sec']:.3f} rounds/s  carry "
              f"{row['carry_allreduce_bytes_per_round']:.0f} B/round  "
              f"agree={agree}", file=sys.stderr)
        rows.append(row)

    ok_rows = {r["procs"]: r for r in rows if "error" not in r}
    base = ok_rows.get(procs_list[0])

    def _eff(n: int):
        r = ok_rows.get(n)
        if (base is None or r is None
                or base["rounds_per_sec"] <= 0):
            return None
        return round(r["rounds_per_sec"] / base["rounds_per_sec"], 4)

    # the bitwise pin arm: SAME block partition (n_blocks=2) at 1 and
    # 2 processes, both residency modes — the commit digests must be
    # byte-identical (the anchor that lets the weak-scaling numbers be
    # trusted as the same computation)
    bitwise_ok = None
    if "bitwise" in arms:
        try:
            one, _ = run_arm(1, 2, MH_BITWISE_ROUNDS,
                             ["streaming", "resident"])
            two, _ = run_arm(2, 2, MH_BITWISE_ROUNDS,
                             ["streaming", "resident"])
            bitwise_ok = bool(
                one[0]["digests"] == two[0]["digests"]
                == two[1]["digests"])
            print(f"multihost bitwise 1p-vs-2p pin: "
                  f"{'OK' if bitwise_ok else 'MISMATCH'} "
                  f"({one[0]['digests']})", file=sys.stderr)
        except MultihostLaunchError as e:
            print(f"multihost bitwise arm FAILED: {e}", file=sys.stderr)
            deaths_total += 1
            bitwise_ok = False

    # v13 elastic chaos arm (ISSUE 14): a clean ELASTIC N-process run
    # vs the same run with rank 1 seeded-killed mid-run.  The killed
    # run must (a) COMPLETE on the survivors (elastic launch policy +
    # view change + block re-adoption), (b) commit byte-identical
    # models to the clean elastic run (the [seed, round, block] purity
    # argument, measured not assumed), (c) keep survivor goodput
    # >= 0.5x clean, with zero survivor deaths.  Fail-fast stays the
    # default everywhere else in this mode — the weak/bitwise arms
    # above run the non-elastic runtime unchanged.
    chaos = None
    straggler = None
    if "chaos" in arms:
        cp = args.mh_chaos_procs
        # the killed arm pays ONE detection stall (~hb_timeout) at the
        # view change — a real deployment amortizes it over hours, so
        # the arm runs 2x the weak-scaling rounds (>= 20) to price the
        # steady survivor state, not the transient; the transient
        # itself is reported separately as view_change_latency_s
        chaos_rounds = max(20, 2 * args.mh_rounds)
        base_cfg = {"elastic": True, "hb_timeout_s": 1.0,
                    "channel_timeout_s": 120.0}
        try:
            clean_docs, _ = run_arm(
                cp, cp, chaos_rounds, ["streaming", "resident"],
                extra_cfg=base_cfg, elastic=True)
            survivors = set(range(cp)) - {1}
            killed_docs, killed_rep = run_arm(
                cp, cp, chaos_rounds, ["streaming", "resident"],
                extra_cfg={**base_cfg, "die_rank": 1,
                           "die_at_round": 1},
                elastic=True, expect_ranks=survivors)
            d0 = killed_docs[0]
            srep = d0["per_mode"]["streaming"]
            clean_rps = clean_docs[0]["rounds_per_sec"]
            killed_rps = d0["rounds_per_sec"]
            survivor_deaths = sum(
                1 for r, info in killed_rep["ranks"].items()
                if int(r) != 1 and info["rc"] != 0)
            bitwise_after_death = all(
                killed_docs[r]["digests"]
                == clean_docs[0]["digests"]
                for r in survivors)
            chaos = {
                "procs": cp,
                "rounds": chaos_rounds,
                "clean_rounds_per_sec": round(clean_rps, 4),
                "killed_rounds_per_sec": round(killed_rps, 4),
                "survivor_goodput_ratio": (
                    round(killed_rps / clean_rps, 4)
                    if clean_rps > 0 else None),
                "view_changes": srep.get("view_changes", 0),
                "view_change_latency_s": round(
                    srep.get("view_change_latency_s", 0.0), 5),
                "epoch_final": srep.get("epoch", 0),
                "survivor_deaths": survivor_deaths,
                "killed_rank_outcome":
                    killed_rep["ranks"][1]["outcome"],
                "bitwise_after_death_ok": bool(bitwise_after_death),
                # asserted only when a non-elastic arm actually ran
                # this invocation (the weak/bitwise arms use the
                # fail-fast launch policy); --mh_arms chaos alone
                # exercises nothing about the default -> null
                "elastic_fail_fast_default_ok": (
                    True if arms & {"weak", "bitwise"} else None),
            }
            print(f"multihost elastic chaos: clean "
                  f"{clean_rps:.3f} -> killed {killed_rps:.3f} "
                  f"rounds/s (ratio "
                  f"{chaos['survivor_goodput_ratio']}), "
                  f"{chaos['view_changes']} view change(s) @ "
                  f"{chaos['view_change_latency_s']*1e3:.1f} ms, "
                  f"bitwise_after_death_ok="
                  f"{chaos['bitwise_after_death_ok']}",
                  file=sys.stderr)
            # v15 straggler block (ISSUE 17): rank 0's always-on
            # barrier ledger + cluster SLO verdicts, from the SAME
            # chaos clusters — no extra spawns.  The killed arm must
            # breach cluster_no_rank_deaths AND name rank 1 dead in
            # the attribution; the clean arm's cluster pack must be
            # green (loopback barrier waits are µs-ms, far under the
            # 2.5 s p95 ceiling).
            c_sl = clean_docs[0].get("straggler") or {}
            k_sl = killed_docs[0].get("straggler") or {}
            c_slo = clean_docs[0].get("cluster_slo") or {}
            k_slo = killed_docs[0].get("cluster_slo") or {}
            k_attr = k_slo.get("attribution") or {}
            straggler = {
                "clean_barriers": c_sl.get("barriers", 0),
                "killed_barriers": k_sl.get("barriers", 0),
                "clean_gating_counts": c_sl.get(
                    "gating_counts", {}),
                "killed_gating_counts": k_sl.get(
                    "gating_counts", {}),
                "top_gating_rank": k_sl.get("top_gating_rank"),
                "worst_gate_margin_s": k_sl.get(
                    "worst_gate_margin_s"),
                "per_rank_wait_s": k_sl.get("per_rank_wait_s", {}),
                # tail of the ledger — each entry names its
                # round_gating_rank and per-rank waits_s
                "recent": (k_sl.get("recent") or [])[-4:],
                "cluster_clean_breaches": len(
                    c_slo.get("breached") or []),
                "cluster_killed_breached": sorted(
                    k_slo.get("breached") or []),
                "straggler_attribution_ok": bool(
                    k_sl.get("barriers", 0) > 0
                    and "1" in (k_attr.get("dead_ranks") or [])
                    and "cluster_no_rank_deaths"
                    in (k_slo.get("breached") or [])),
            }
            print(f"multihost straggler ledger: clean "
                  f"{straggler['clean_barriers']} / killed "
                  f"{straggler['killed_barriers']} barriers, "
                  f"top_gating_rank="
                  f"{straggler['top_gating_rank']}, "
                  f"clean breaches "
                  f"{straggler['cluster_clean_breaches']}, "
                  f"attribution_ok="
                  f"{straggler['straggler_attribution_ok']}",
                  file=sys.stderr)
        except MultihostLaunchError as e:
            print(f"multihost elastic chaos arm FAILED: {e}",
                  file=sys.stderr)
            deaths_total += 1
            chaos = {"error": str(e), "survivor_deaths": None,
                     "bitwise_after_death_ok": False}

    # v14 compress arm (ISSUE 16): price the compressed + overlapped
    # carry tier against the f32 serial baseline at the SAME block
    # partition (2 processes, 2 blocks).  Four spawned clusters:
    #   f32 serial   — the PR-13 wire bytes and digest baseline
    #   f32 +overlap — the escape hatch MUST stay byte-identical to
    #                  serial (overlap reorders nothing: frames
    #                  concatenate in global block order)
    #   int8 / int8_ef +overlap — the compressed rows; wire bytes are
    #                  the CHANNEL's per-round delta (measured on the
    #                  wire), accuracy rides eval at rank 0
    compress = None
    if "compress" in arms:
        def _wire_b(docs):
            return max(docs[r]["carry_wire_sent_bytes_per_round"]
                       for r in docs)

        try:
            ev = {"eval": True}
            f32_docs, _ = run_arm(2, 2, args.mh_rounds, ["streaming"],
                                  extra_cfg=ev)
            f32_ov_docs, _ = run_arm(
                2, 2, args.mh_rounds, ["streaming"],
                extra_cfg={**ev, "carry_codec": "f32",
                           "overlap_exchange": True})
            escape_ok = all(
                f32_ov_docs[r]["digests"] == f32_docs[0]["digests"]
                for r in f32_ov_docs)
            f32_rps = f32_docs[0]["rounds_per_sec"]
            f32_wire = _wire_b(f32_docs)
            f32_acc = f32_docs[0].get("eval", {}).get("streaming")
            codec_rows = []
            for codec in ("int8", "int8_ef"):
                docs, _ = run_arm(
                    2, 2, args.mh_rounds, ["streaming"],
                    extra_cfg={**ev, "carry_codec": codec,
                               "overlap_exchange": True})
                d0 = docs[0]
                wire = _wire_b(docs)
                rps = d0["rounds_per_sec"]
                acc = d0.get("eval", {}).get("streaming")
                reduction = (round(f32_wire / wire, 4)
                             if wire > 0 else None)
                crow = {
                    "codec": codec,
                    "rounds_per_sec": round(rps, 4),
                    "carry_wire_bytes_per_round": round(wire, 1),
                    "carry_payload_bytes_per_round": round(
                        d0["carry_payload_bytes_per_round"], 1),
                    "carry_raw_bytes_per_round": round(
                        d0["carry_raw_bytes_per_round"], 1),
                    "carry_compression_ratio": round(
                        d0["carry_compression_ratio"], 4),
                    "wire_reduction_vs_f32": reduction,
                    "overlap_fraction": round(
                        d0["overlap_fraction"], 4),
                    "ranks_agree": all(
                        docs[r]["digests"] == d0["digests"]
                        for r in docs),
                    "eval_acc": (round(acc, 4)
                                 if acc is not None else None),
                    "acc_delta_vs_f32": (
                        round(abs(acc - f32_acc), 4)
                        if acc is not None and f32_acc is not None
                        else None),
                    "efficiency_at_constant_bytes": (
                        round((rps / f32_rps) * reduction, 4)
                        if f32_rps > 0 and reduction else None),
                }
                codec_rows.append(crow)
                print(f"multihost compress {codec}: "
                      f"{crow['carry_wire_bytes_per_round']:.0f} "
                      f"B/round on the wire "
                      f"({crow['wire_reduction_vs_f32']}x vs f32), "
                      f"overlap {crow['overlap_fraction']}, "
                      f"acc_delta {crow['acc_delta_vs_f32']}",
                      file=sys.stderr)
            compress = {
                "procs": 2,
                "rounds": args.mh_rounds,
                "f32_rounds_per_sec": round(f32_rps, 4),
                "f32_wire_bytes_per_round": round(f32_wire, 1),
                "f32_eval_acc": (round(f32_acc, 4)
                                 if f32_acc is not None else None),
                "f32_overlap_fraction": round(
                    f32_ov_docs[0]["overlap_fraction"], 4),
                "bitwise_f32_escape_ok": bool(escape_ok),
                "codecs": codec_rows,
            }
            print(f"multihost f32 escape hatch under overlap: "
                  f"{'OK' if escape_ok else 'MISMATCH'} (overlap "
                  f"fraction "
                  f"{compress['f32_overlap_fraction']})",
                  file=sys.stderr)
        except MultihostLaunchError as e:
            print(f"multihost compress arm FAILED: {e}",
                  file=sys.stderr)
            deaths_total += 1
            compress = {"error": str(e),
                        "bitwise_f32_escape_ok": False}

    # v17 sparse arm (ISSUE 19): same paired 2-process protocol as the
    # compress arm, but the codec rows are the SPARSE flavors (topk,
    # topk_ef; fixed k = dim/16 per block).  The wire bytes are the
    # channel's measured per-round delta, so wire_reduction_vs_f32 is
    # the honest bytes-on-the-wire ratio the ISSUE-19 >= 6x gate rides
    # on (bench_diff v17).  f32 stays the escape hatch: its bitwise
    # pin is re-asserted here under overlap so a sparse-era regression
    # in the fold path can't hide behind the compress arm being off.
    sparse = None
    if "sparse" in arms:
        def _wire_sb(docs):
            return max(docs[r]["carry_wire_sent_bytes_per_round"]
                       for r in docs)

        try:
            # topk_ef's reconstruction mirror needs ~topk_ratio rounds
            # of warm-up before every coordinate has shipped once —
            # judging convergence at the 10-round default would
            # measure the transient, not the codec, so the arm floors
            # its round count well past the warm-up (the chaos arm's
            # round-floor precedent)
            sp_rounds = max(8 * 16, args.mh_rounds)
            ev = {"eval": True}
            f32_docs, _ = run_arm(2, 2, sp_rounds, ["streaming"],
                                  extra_cfg=ev)
            f32_ov_docs, _ = run_arm(
                2, 2, sp_rounds, ["streaming"],
                extra_cfg={**ev, "carry_codec": "f32",
                           "overlap_exchange": True})
            escape_ok = all(
                f32_ov_docs[r]["digests"] == f32_docs[0]["digests"]
                for r in f32_ov_docs)
            f32_rps = f32_docs[0]["rounds_per_sec"]
            f32_wire = _wire_sb(f32_docs)
            f32_acc = f32_docs[0].get("eval", {}).get("streaming")
            codec_rows = []
            for codec in ("topk", "topk_ef"):
                docs, _ = run_arm(
                    2, 2, sp_rounds, ["streaming"],
                    extra_cfg={**ev, "carry_codec": codec,
                               "overlap_exchange": True})
                d0 = docs[0]
                wire = _wire_sb(docs)
                rps = d0["rounds_per_sec"]
                acc = d0.get("eval", {}).get("streaming")
                reduction = (round(f32_wire / wire, 4)
                             if wire > 0 else None)
                crow = {
                    "codec": codec,
                    "rounds_per_sec": round(rps, 4),
                    "carry_wire_bytes_per_round": round(wire, 1),
                    "carry_payload_bytes_per_round": round(
                        d0["carry_payload_bytes_per_round"], 1),
                    "carry_raw_bytes_per_round": round(
                        d0["carry_raw_bytes_per_round"], 1),
                    "carry_compression_ratio": round(
                        d0["carry_compression_ratio"], 4),
                    "wire_reduction_vs_f32": reduction,
                    "overlap_fraction": round(
                        d0["overlap_fraction"], 4),
                    "ranks_agree": all(
                        docs[r]["digests"] == d0["digests"]
                        for r in docs),
                    "eval_acc": (round(acc, 4)
                                 if acc is not None else None),
                    "acc_delta_vs_f32": (
                        round(abs(acc - f32_acc), 4)
                        if acc is not None and f32_acc is not None
                        else None),
                    "efficiency_at_constant_bytes": (
                        round((rps / f32_rps) * reduction, 4)
                        if f32_rps > 0 and reduction else None),
                }
                codec_rows.append(crow)
                print(f"multihost sparse {codec}: "
                      f"{crow['carry_wire_bytes_per_round']:.0f} "
                      f"B/round on the wire "
                      f"({crow['wire_reduction_vs_f32']}x vs f32), "
                      f"overlap {crow['overlap_fraction']}, "
                      f"acc_delta {crow['acc_delta_vs_f32']}",
                      file=sys.stderr)
            sparse = {
                "procs": 2,
                "rounds": sp_rounds,
                "topk_ratio": 16,
                "f32_rounds_per_sec": round(f32_rps, 4),
                "f32_wire_bytes_per_round": round(f32_wire, 1),
                "f32_eval_acc": (round(f32_acc, 4)
                                 if f32_acc is not None else None),
                "f32_overlap_fraction": round(
                    f32_ov_docs[0]["overlap_fraction"], 4),
                "bitwise_f32_escape_ok": bool(escape_ok),
                "codecs": codec_rows,
            }
            print(f"multihost f32 escape hatch under overlap "
                  f"(sparse arm): "
                  f"{'OK' if escape_ok else 'MISMATCH'} (overlap "
                  f"fraction "
                  f"{sparse['f32_overlap_fraction']})",
                  file=sys.stderr)
        except MultihostLaunchError as e:
            print(f"multihost sparse arm FAILED: {e}",
                  file=sys.stderr)
            deaths_total += 1
            sparse = {"error": str(e),
                      "bitwise_f32_escape_ok": False}

    head = (rows[-1] if rows and "error" not in rows[-1] else
            (base or (rows[-1] if rows else {})))
    doc = _stamp({
        "metric": "multihost_weak_scaling_rounds_per_sec",
        "value": round(head.get("rounds_per_sec", 0.0), 4),
        "unit": "rounds/sec",
        "vs_baseline": None,
        "mode": "multihost",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "chaos": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": {
            "rows": rows,
            "weak_efficiency_2p": _eff(2),
            "weak_efficiency_4p": _eff(4),
            "bitwise_2proc_ok": bitwise_ok,
            "chaos": chaos,
            "straggler": straggler,
            "compress": compress,
            "sparse": sparse,
            "process_deaths": deaths_total,
            "k_per_block": args.mh_k_per_block,
            "clients_per_block": args.mh_clients_per_block,
            "dim": args.mh_dim,
            "local_devices": args.mh_local_devices,
            "rounds": args.mh_rounds,
            "warmup": args.mh_warmup,
            "seed": args.mh_seed,
        },
        "cluster": None,
        "secure": None,
        "critical_path": _critical_path_doc(),
        "slo": _slo_doc({"sweep": _slo_close(slo_eng)}),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


CLUSTER_WARMUP_COMMITS = 2
CLUSTER_GOODPUT_FLOOR = 0.5


def _bench_cluster(args) -> None:
    """Fused serving cluster bench (ISSUE 18, fedml_tpu/scale/
    cluster.py): H spawned hosts each bind a reactor endpoint and
    serve live-socket uplinks into their registry-shard lanes, folding
    lane partials cross-host through the ElasticChannel at every
    commit barrier; ONE connswarm fleet (subprocess, own fd budget)
    stripes its connections across the H endpoints, pacing uplinks
    along the PR-10 diurnal profile.  Rows sweep host counts —
    cluster committed-updates/sec, p95 admission (max over ranks), and
    ranks_agree (the live-ingest cross-rank digest pin).  The
    chaos_everything arm composes EVERY fault layer at once:
    connection storm + reconnect churn + seeded wire faults + rank 1
    killed mid-run — survivors must keep >= 0.5x the clean row's
    goodput, agree bitwise after the death, lose no recv threads, and
    account every shed/evicted/dropped uplink."""
    import dataclasses
    import tempfile

    import numpy as np

    from fedml_tpu import obs
    from fedml_tpu.async_.torture import _swarm_subprocess
    from fedml_tpu.comm.connswarm import SwarmConfig
    from fedml_tpu.parallel.multihost import (MultihostLaunchError,
                                              free_port,
                                              spawn_cluster_report)
    from fedml_tpu.scale.arrivals import ArrivalConfig
    from fedml_tpu.scale.cluster import make_uplink_frame

    hosts_list = sorted(int(h) for h in str(args.cluster_hosts).split(",")
                        if h.strip())
    if not hosts_list or hosts_list[0] < 1:
        raise SystemExit(
            f"--cluster_hosts must be a comma-separated list of "
            f"positive host counts, got {args.cluster_hosts!r}")
    if args.cluster_commits <= CLUSTER_WARMUP_COMMITS:
        raise SystemExit(
            f"--cluster_commits ({args.cluster_commits}) must exceed "
            f"the warmup ({CLUSTER_WARMUP_COMMITS})")
    cluster_arms = {a.strip()
                    for a in str(args.cluster_arms).split(",")
                    if a.strip()}
    bad_cluster_arms = cluster_arms - {"clean", "sparse"}
    if bad_cluster_arms:
        raise SystemExit(
            f"--cluster_arms must be a subset of clean,sparse; got "
            f"{args.cluster_arms!r}")
    rng = np.random.default_rng(args.cluster_seed)
    uplink_row = rng.standard_normal(
        args.cluster_row_dim).astype(np.float32)
    frame = make_uplink_frame(uplink_row, sender=1, weight=1.0,
                              version=0)

    def run_arm(hosts, *, tag, storm=False, chaos=None, die_at=None,
                expect_ranks=None, commits=None, uplink_frame=None,
                sparse_uplink=False):
        ports = [free_port() for _ in range(hosts)]
        # weak scaling: --cluster_rate is PER HOST, so the fleet's
        # aggregate offer grows with the host count (each row asks
        # "did adding hosts add committed throughput").  The flash
        # profile bursts ABOVE that (the push-notification stampede),
        # it does not scale it down: offered_rate is the profile's
        # PEAK, so the storm arm's peak is boost x the sustained rate
        offered = args.cluster_rate * hosts * (3.0 if storm else 1.0)
        sc = {"population": args.cluster_population,
              "commits": int(commits or args.cluster_commits),
              "warmup_commits": CLUSTER_WARMUP_COMMITS,
              "buffer_k": args.cluster_buffer_k,
              "row_dim": args.cluster_row_dim,
              "connections": args.cluster_connections,
              "ingest_pool": args.cluster_ingest_pool,
              "window_deadline_s": 5.0, "timeout_s": 600.0,
              "ports": ports}
        if sparse_uplink:
            sc["sparse_uplink"] = True
        if chaos:
            sc["chaos"] = dict(chaos)
            sc["chaos_seed"] = args.cluster_seed
        if die_at is not None:
            sc["die_rank"] = 1
            sc["die_at_commit"] = die_at
        cfg = {"serve_cluster": sc, "channel_timeout_s": 300.0,
               "hb_timeout_s": 1.0, "hb_interval_s": 0.25}
        arrival = dataclasses.asdict(ArrivalConfig(
            mode="flash" if storm else "diurnal",
            rate=args.cluster_rate, period_s=30.0, amplitude=0.5,
            flash_at_s=2.0, flash_duration_s=5.0, flash_boost=3.0,
            seed=args.cluster_seed))
        swarm_cfg = SwarmConfig(
            n_connections=hosts * args.cluster_connections,
            offered_rate=offered, storm=storm,
            churn_lifetime_s=(CONN_CHURN_LIFETIME_S if storm else 0.0),
            duration_s=600.0, seed=args.cluster_seed,
            targets=[["127.0.0.1", p] for p in ports],
            arrival=arrival, burst_cap_s=0.05)
        # swarm first: the fleet retries refused connects until the
        # workers' reactors bind, so startup order is not a race
        sw_finish = _swarm_subprocess(
            swarm_cfg, frame if uplink_frame is None else uplink_frame)
        path = None
        try:
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".json", delete=False) as f:
                json.dump(cfg, f)
                path = f.name
            outs, rep = spawn_cluster_report(
                [sys.executable, "-m", "fedml_tpu.parallel.mh_worker",
                 path], hosts, timeout_s=900.0, elastic=(hosts > 1))
        finally:
            sw = sw_finish()
            if path:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        docs = {}
        for r, out in enumerate(outs):
            for line in out.splitlines():
                if line.startswith("{"):
                    docs[r] = json.loads(line)["serve_cluster"]
        expect = (set(expect_ranks) if expect_ranks is not None
                  else set(range(hosts)))
        if not expect <= set(docs):
            raise MultihostLaunchError(
                f"cluster arm {tag!r}: missing rank report(s) "
                f"{sorted(expect - set(docs))} "
                f"(ranks: {rep['ranks']})")
        r0 = docs[min(docs)]
        p95_ms = max(d["admission_p95_s"] for d in docs.values()) * 1e3
        print(f"{tag}: {r0['cluster_updates_per_sec']:.1f} cluster "
              f"updates/s  p95 admission {p95_ms:.1f} ms  swarm sent "
              f"{sw.get('frames_sent', 0)} frames "
              f"({sw.get('connects', 0)} connects)", file=sys.stderr)
        return docs, rep, sw

    def steady_rate(doc, skip):
        """Sustained committed-updates/sec over the tail of the
        per-commit walls/wsums ledger — at least the last half of the
        commits, and never earlier than `skip`.  The early commits are
        regime transients, excluded by construction: the startup
        backlog drain (frames that landed while jit warmed up replay
        at decode speed, not at the offered pace) and, in the chaos
        arm, the kill + heartbeat-eviction window — a one-time stall
        that must not masquerade as steady-state goodput loss."""
        n = len(doc["commit_walls_s"])
        skip = max(int(skip), n // 2)
        walls = doc["commit_walls_s"][skip:]
        wsums = doc["commit_wsums"][skip:]
        tw = sum(walls)
        return (sum(wsums) / tw) if tw > 0 else 0.0

    def arm_doc(docs, sw, steady_skip=CLUSTER_WARMUP_COMMITS):
        digests = [d["committed_digest"] for d in docs.values()]
        return {
            "cluster_updates_per_sec": round(
                docs[min(docs)]["cluster_updates_per_sec"], 4),
            "steady_updates_per_sec": round(
                steady_rate(docs[min(docs)], steady_skip), 4),
            "admission_p50_s": round(max(
                d["admission_p50_s"] for d in docs.values()), 6),
            "admission_p95_s": round(max(
                d["admission_p95_s"] for d in docs.values()), 6),
            "ranks_agree": len(set(digests)) == 1,
            "committed_updates": int(sum(
                d["committed_updates"] for d in docs.values())),
            "commits": max(d["commits"] for d in docs.values()),
            "evicted": {k: sum(d["evicted"][k] for d in docs.values())
                        for k in next(iter(docs.values()))["evicted"]},
            "uplinks_shed": sum(d["uplinks_shed"]
                                for d in docs.values()),
            "shed_reasons": {
                k: sum(d["shed_reasons"][k] for d in docs.values())
                for k in next(iter(docs.values()))["shed_reasons"]},
            "lane_overflow_dropped": sum(
                d["lane_overflow_dropped"] for d in docs.values()),
            "deadline_windows": sum(d["deadline_windows"]
                                    for d in docs.values()),
            "recv_thread_deaths": sum(d["recv_thread_deaths"]
                                      for d in docs.values()),
            "quarantined": sum(d["quarantined"] for d in docs.values()),
            "open_connections_peak": sum(
                d["open_connections_peak"] for d in docs.values()),
            "registry_bytes": sum(d["registry_bytes"]
                                  for d in docs.values()),
            "swarm": {"frames_sent": sw.get("frames_sent"),
                      "connects": sw.get("connects"),
                      "refused": sw.get("refused"),
                      "per_target": sw.get("per_target")},
        }

    rows = []
    slo_arms: dict = {}
    clean_by_hosts: dict = {}
    for hosts in hosts_list:
        docs, _rep, sw = run_arm(hosts, tag=f"hosts={hosts} clean")
        clean_by_hosts[hosts] = docs
        slo_arms[f"h{hosts}_clean"] = docs[min(docs)].get("slo_arm")
        row = {"hosts": hosts,
               "connections": hosts * args.cluster_connections,
               **arm_doc(docs, sw)}
        rows.append(row)

    # the chaos-everything arm: storm + churn + wire faults + rank
    # kill, all in the same run, at the widest clean host count >= 2
    chaos_arm = None
    hmax = max(hosts_list)
    if hmax >= 2:
        # more commits than the clean rows: the one-time eviction
        # stall (heartbeat timeout + view change) must amortize over
        # the post-kill steady state, same shape as the multihost
        # chaos arm's round count
        chaos_commits = max(12, 2 * args.cluster_commits)
        die_at = CLUSTER_WARMUP_COMMITS + 1
        survivors = set(range(hmax)) - {1}
        docs, rep, sw = run_arm(
            hmax, tag=f"hosts={hmax} chaos-everything", storm=True,
            chaos=dict(CONN_CHAOS), die_at=die_at,
            expect_ranks=survivors, commits=chaos_commits)
        sdocs = {r: docs[r] for r in survivors if r in docs}
        digests = [d["committed_digest"] for d in sdocs.values()]
        # goodput on the STEADY rates: clean tail vs the survivors'
        # post-eviction tail (commit die_at absorbs the heartbeat
        # timeout + view change; the floor judges the regime after it)
        clean_ups = steady_rate(
            clean_by_hosts[hmax][min(clean_by_hosts[hmax])],
            CLUSTER_WARMUP_COMMITS)
        killed_ups = steady_rate(sdocs[min(sdocs)], die_at + 1)
        slo_arms[f"h{hmax}_chaos_everything"] = \
            sdocs[min(sdocs)].get("slo_arm")
        chaos_arm = {
            "hosts": hmax,
            "killed_rank": 1,
            "die_at_commit": die_at,
            "survivor_goodput_ratio": round(
                killed_ups / clean_ups, 4) if clean_ups > 0 else None,
            "bitwise_after_death_ok": len(set(digests)) == 1,
            "survivor_deaths": sum(
                1 for r, st in rep["ranks"].items()
                if int(r) != 1 and st["rc"] != 0),
            **arm_doc(sdocs, sw, steady_skip=die_at + 1),
        }
        print(f"chaos-everything: survivor goodput "
              f"{chaos_arm['survivor_goodput_ratio']}x  bitwise "
              f"{chaos_arm['bitwise_after_death_ok']}  sheds "
              f"{chaos_arm['uplinks_shed']:.0f}", file=sys.stderr)

    # v17 sparse uplink arm (ISSUE 19): the paired dense-vs-sparse
    # run at the widest clean host count.  Same offered rate, same
    # population, same connections — the ONLY change is the wire: the
    # fleet ships sparse_topk v2 frames (k = dim/16 pairs) and the
    # servers opt their lanes into the scatter-fold ingest path
    # (sparse_uplink=True).  throughput_ratio_vs_dense rides the
    # ISSUE-19 >= 0.9x gate in bench_diff; uplink_reduction_vs_dense
    # is honest len(frame) bytes including the envelope.  The
    # digests_equal pin replays a <=k-sparse row through the sparse
    # codec in-process — sparse_topk ships exact f32 (index, value)
    # pairs, so a row with <= k nonzeros must decode bitwise-equal
    # (truncation only bites when MORE than k coordinates are live;
    # that lossy case is priced by the multihost sparse arm's
    # acc_delta, not pinned here).
    sparse_arm = None
    if "sparse" in cluster_arms:
        from fedml_tpu.comm.message import MessageCodec
        k = max(1, args.cluster_row_dim // 16)
        sp_row = np.zeros(args.cluster_row_dim, np.float32)
        sp_idx = rng.choice(args.cluster_row_dim, size=k,
                            replace=False)
        sp_row[sp_idx] = rng.standard_normal(k).astype(np.float32)
        replay = MessageCodec.decode(make_uplink_frame(
            sp_row, sender=1, weight=1.0, version=0,
            transport="sparse_topk"))
        replay_row = np.asarray(replay.get("model_params")["w"])
        digests_equal = bool(
            replay_row.dtype == np.float32
            and np.array_equal(
                replay_row.view(np.uint32),
                sp_row.view(np.uint32)))
        sparse_frame = make_uplink_frame(
            uplink_row, sender=1, weight=1.0, version=0,
            transport="sparse_topk")
        docs, _rep, sw = run_arm(
            hmax, tag=f"hosts={hmax} sparse",
            uplink_frame=sparse_frame, sparse_uplink=True)
        dense_docs = clean_by_hosts[hmax]
        dense_ups = steady_rate(dense_docs[min(dense_docs)],
                                CLUSTER_WARMUP_COMMITS)
        sparse_ups = steady_rate(docs[min(docs)],
                                 CLUSTER_WARMUP_COMMITS)
        slo_arms[f"h{hmax}_sparse"] = docs[min(docs)].get("slo_arm")
        sparse_arm = {
            "hosts": hmax,
            "topk_ratio": 16,
            "k": k,
            "uplink_bytes_per_update": len(sparse_frame),
            "dense_uplink_bytes_per_update": len(frame),
            "uplink_reduction_vs_dense": round(
                len(frame) / len(sparse_frame), 4),
            "throughput_ratio_vs_dense": (
                round(sparse_ups / dense_ups, 4)
                if dense_ups > 0 else None),
            "digests_equal": digests_equal,
            **arm_doc(docs, sw),
        }
        print(f"sparse uplink: {sparse_arm['uplink_bytes_per_update']}"
              f" B/update "
              f"({sparse_arm['uplink_reduction_vs_dense']}x vs dense "
              f"{len(frame)} B), throughput ratio "
              f"{sparse_arm['throughput_ratio_vs_dense']}x, "
              f"k-sparse replay "
              f"{'EXACT' if digests_equal else 'MISMATCH'}",
              file=sys.stderr)

    head = rows[-1]
    doc = _stamp({
        "metric": (f"cluster_{head['hosts']}hosts_"
                   "committed_updates_per_sec"),
        "value": head["cluster_updates_per_sec"],
        "unit": "updates/sec",
        "vs_baseline": None,
        "mode": "cluster",
        "overlap_fraction": None,
        "h2d_bytes_per_round": None,
        "rounds": [],
        "async": None,
        "ingest": None,
        "chaos": None,
        "attack": None,
        "serve": None,
        "connections": None,
        "multihost": None,
        "secure": None,
        "cluster": {
            "rows": rows,
            "chaos_everything": chaos_arm,
            "sparse": sparse_arm,
            "goodput_floor": CLUSTER_GOODPUT_FLOOR,
            "commits": args.cluster_commits,
            "buffer_k": args.cluster_buffer_k,
            "row_dim": args.cluster_row_dim,
            "population": args.cluster_population,
            "connections_per_host": args.cluster_connections,
            "offered_rate": args.cluster_rate,
            "ingest_pool": args.cluster_ingest_pool,
            "chaos_rates": dict(CONN_CHAOS),
            "seed": args.cluster_seed,
        },
        "critical_path": _critical_path_doc(),
        "slo": _slo_doc(slo_arms),
        "programs": _programs_doc(),
    })
    if obs.enabled():
        obs.export()
        doc["obs"] = obs.rollup()
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
