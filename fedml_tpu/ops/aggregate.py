"""Fused federated-aggregation pallas kernels.

Two ops, both forward-only (server aggregation is never differentiated
through):

* `weighted_mean_pallas(stacked, w)` — sample-weighted mean over the
  client axis: Σᵢ wᵢ·xᵢ / Σᵢ wᵢ.  Replaces the reference's CPU
  dict-of-tensors loop (FedAVGAggregator.py:73-81).  One [1,C]×[C,T]
  MXU dot per tile.
* `robust_weighted_mean_pallas(stacked, w, global_tree, tau)` — the
  Byzantine-robust pipeline (norm-difference clipping,
  robust_aggregation.py:38-49) fused into two passes over the stack:
  pass 1 accumulates per-client ‖xᵢ−g‖², pass 2 applies the clip factor
  inside the weighted reduction:  g + Σᵢ ŵᵢ·min(1, τ/‖dᵢ‖)·(xᵢ−g).
  Without fusion this is 4+ HBM round-trips over [C,N]; fused it is 2.

Layout: client pytrees are flattened to one [C, N] matrix (N padded to
the 128-lane tile), so every leaf rides the same kernel and the tiling is
always aligned.  On non-TPU backends the kernels run in pallas interpret
mode (tests), selected automatically.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:                                   # pltpu import fails on cpu-only jax
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except Exception:                      # pragma: no cover
    pltpu = None
    _VMEM = _SMEM = None

Pytree = Any
TILE = 512                             # lanes per grid step (4×128)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# pytree <-> [C, N] matrix
# ---------------------------------------------------------------------------

def flatten_stacked_tree(stacked: Pytree):
    """[C, ...] leaves → float32 [C, N] (N padded to TILE) + unflatten spec.

    Donation-safe: builds one fresh [C, N] buffer and never aliases the
    input leaves into the returned spec, so callers may donate `stacked`
    at their jit boundary (the mesh engines' block steps donate their
    whole block inputs — parallel/engine.py); a single-leaf tree skips
    the concatenate (reshape only), letting XLA alias a donated f32
    input straight into the flat buffer."""
    leaves, treedef = jax.tree.flatten(stacked)
    C = leaves[0].shape[0]
    if len(leaves) == 1:
        flat = leaves[0].reshape(C, -1).astype(jnp.float32)
    else:
        flat = jnp.concatenate(
            [l.reshape(C, -1).astype(jnp.float32) for l in leaves], axis=1)
    n = flat.shape[1]
    pad = (-n) % TILE
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    shapes = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]
    return flat, (treedef, shapes, n)


def unflatten_to_tree(vec: jax.Array, spec) -> Pytree:
    """[N] → pytree with the per-leaf shapes of the stacked input (minus the
    client axis)."""
    treedef, leaves, n = spec
    vec = vec[:n]
    out, off = [], 0
    for l in leaves:
        shape = l.shape[1:]
        size = 1
        for s in shape:
            size *= s
        out.append(vec[off:off + size].reshape(shape).astype(l.dtype))
        off += size
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# kernel 1: weighted mean
# ---------------------------------------------------------------------------

def _wmean_kernel(w_ref, x_ref, inv_ref, o_ref):
    # [1,C] @ [C,T] on the MXU, scaled by 1/Σw from SMEM
    o_ref[:] = jnp.dot(w_ref[:], x_ref[:],
                       preferred_element_type=jnp.float32) * inv_ref[0, 0]


def _wmean_flat(flat: jax.Array, w: jax.Array, interpret: bool) -> jax.Array:
    C, N = flat.shape
    inv = (1.0 / jnp.maximum(jnp.sum(w), 1e-12)).reshape(1, 1)
    out = pl.pallas_call(
        _wmean_kernel,
        grid=(N // TILE,),
        in_specs=[
            pl.BlockSpec((1, C), lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec((C, TILE), lambda i: (0, i), memory_space=_VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=_SMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE), lambda i: (0, i),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((1, N), jnp.float32),
        interpret=interpret,
    )(w.astype(jnp.float32).reshape(1, C), flat, inv)
    return out[0]


def weighted_mean_pallas(stacked: Pytree, weights: jax.Array,
                         interpret: bool | None = None) -> Pytree:
    """Drop-in for core.pytree.tree_weighted_mean, fused over all leaves."""
    if interpret is None:
        interpret = _interpret_default()
    flat, spec = flatten_stacked_tree(stacked)
    return unflatten_to_tree(_wmean_flat(flat, weights, interpret), spec)


# ---------------------------------------------------------------------------
# kernel 2: fused robust (norm-clip) aggregation
# ---------------------------------------------------------------------------

def _sqnorm_kernel(x_ref, g_ref, o_ref):
    # accumulate per-client Σ (x−g)² across the tile grid (grid on TPU is
    # sequential, so the running += into the same output block is sound)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)
    d = x_ref[:] - g_ref[:]
    o_ref[:] += jnp.sum(d * d, axis=1, keepdims=True)


def _clip_agg_kernel(cf_ref, x_ref, g_ref, o_ref):
    # out = g + Σ_c cf_c·(x_c − g):   cf already folds ŵ_c·min(1, τ/‖d_c‖)
    d = x_ref[:] - g_ref[:]
    o_ref[:] = g_ref[:] + jnp.dot(cf_ref[:], d,
                                  preferred_element_type=jnp.float32)


def robust_weighted_mean_pallas(stacked: Pytree, weights: jax.Array,
                                global_tree: Pytree, norm_bound: float,
                                interpret: bool | None = None) -> Pytree:
    """Fused  g + Σᵢ ŵᵢ·clipᵢ·(xᵢ−g),  ŵ = w/Σw,
    clipᵢ = min(1, τ/‖xᵢ−g‖) — exactly norm_diff_clip + weighted mean
    (reference clips each client before averaging,
    FedAvgRobustAggregator.py:176-185)."""
    if interpret is None:
        interpret = _interpret_default()
    flat, spec = flatten_stacked_tree(stacked)
    C, N = flat.shape
    gflat, _ = flatten_stacked_tree(
        jax.tree.map(lambda x: x[None], global_tree))

    sq = pl.pallas_call(
        _sqnorm_kernel,
        grid=(N // TILE,),
        in_specs=[
            pl.BlockSpec((C, TILE), lambda i: (0, i), memory_space=_VMEM),
            pl.BlockSpec((1, TILE), lambda i: (0, i), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((C, 1), lambda i: (0, 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((C, 1), jnp.float32),
        interpret=interpret,
    )(flat, gflat)

    # the clip factor is the ONE shared definition (core/pytree.clip_scale
    # — same 1e-24-floored sqrt), so this fused path, norm_diff_clip and
    # the flat-row admission/DP clip cannot drift (ISSUE-9 dedupe)
    from fedml_tpu.core.pytree import clip_scale
    clip = clip_scale(sq[:, 0], norm_bound)
    w = weights.astype(jnp.float32)
    cf = (w / jnp.maximum(jnp.sum(w), 1e-12)) * clip

    out = pl.pallas_call(
        _clip_agg_kernel,
        grid=(N // TILE,),
        in_specs=[
            pl.BlockSpec((1, C), lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec((C, TILE), lambda i: (0, i), memory_space=_VMEM),
            pl.BlockSpec((1, TILE), lambda i: (0, i), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE), lambda i: (0, i),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((1, N), jnp.float32),
        # the output rides the gflat buffer: same [1, N] f32 shape, gflat
        # is dead after this call (the sq pass above already consumed
        # it), and each grid step reads its g tile into VMEM before the
        # o tile stores back — one less HBM allocation per aggregation
        input_output_aliases={2: 0},
        interpret=interpret,
    )(cf.reshape(1, C), flat, gflat)
    return unflatten_to_tree(out[0], spec)
