"""Pallas TPU kernels for the server-side aggregation hot path.

The reference's server aggregation is a Python loop over state_dict keys on
CPU (FedAVGAggregator.py:59-88); XLA already turns our tree-level weighted
mean into fused HBM-bandwidth kernels, and these pallas kernels go one step
further: the entire cohort aggregation — including the robust norm-clip
pipeline — runs as a single pass over the stacked client weights in VMEM
tiles, with the reduction on the MXU.
"""
from fedml_tpu.ops.aggregate import (flatten_stacked_tree,
                                     robust_weighted_mean_pallas,
                                     unflatten_to_tree,
                                     weighted_mean_pallas)

__all__ = ["weighted_mean_pallas", "robust_weighted_mean_pallas",
           "flatten_stacked_tree", "unflatten_to_tree"]
