"""Fused GroupNorm — pallas forward + custom-VJP backward.

GroupNorm is the normalization of the flagship ResNet-18-GN (the
reference's fed_cifar100 model, cv/resnet_gn.py + group_normalization.py)
and ~40% of the bench step's fwd+bwd wall-clock.  The fused layout:

  forward : ONE pass over x → (y, mean, rstd)      [stats in f32]
  backward: ONE pass over (x, dy) → dx; the small dγ/dβ channel
            reductions run as one fused XLA reduction outside the kernel.

Mosaic cannot split the minor (lane) dimension in-kernel, so instead of
reshaping [B, S·C] → [B, S, G, C/G] the kernels select each group with an
iota mask over the flattened feature axis (G unrolled VPU passes over
VMEM-resident data — no extra HBM traffic), and γ/β arrive pre-tiled to
the feature axis from XLA.  Layout requirement: trailing-channel arrays
with (H·W·C) a multiple of 128, C divisible by `num_groups`, and batch a
multiple of BLOCK_N; anything else — and any non-TPU backend — takes the
pure-jnp reference path, which is the numerical spec the tests compare
against.

MEASURED OUTCOME (v5e-1, bs 4096 ResNet-18-GN train step): the hand
kernel loses to XLA — 262 ms/step fused vs 177 ms/step with plain
nn.GroupNorm.  XLA already fuses GN's elementwise tail into the
surrounding relu/conv producers/consumers, and the group-select masks
cost G extra VPU passes over the block.  The models therefore keep
nn.GroupNorm by default; this op remains available (and tested for
value/grad parity) as the building block for cases XLA fuses poorly —
e.g. GN followed by host-visible stats, or very large C where the
mask passes amortize.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:                      # pragma: no cover
    pltpu = None
    _VMEM = None

BLOCK_N = 8      # sublane granularity: blocks must be multiples of 8
FTILE = 8192     # in-kernel chunk (VMEM temporaries stay ~1 MB)


def _use_pallas(shape, num_groups) -> bool:
    if jax.default_backend() != "tpu":
        return False
    if len(shape) < 2:
        return False
    feat = 1
    for s in shape[1:]:
        feat *= s
    C = shape[-1]
    if C % num_groups or shape[0] % BLOCK_N or feat % 128:
        return False
    if feat <= FTILE:
        return True
    # chunked path needs C-aligned full tiles
    return feat % FTILE == 0 and FTILE % C == 0


# ---------------------------------------------------------------------------
# reference (spec) path — plain jnp, used off-TPU / unaligned shapes
# ---------------------------------------------------------------------------

def _gn_reference(x, gamma, beta, num_groups, eps):
    N, C = x.shape[0], x.shape[-1]
    xf = x.astype(jnp.float32).reshape(N, -1, num_groups, C // num_groups)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=(1, 3), keepdims=True)
    xhat = ((xf - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    return (xhat * gamma.astype(jnp.float32)
            + beta.astype(jnp.float32)).astype(x.dtype)


def _stats_reference(x, num_groups, eps):
    N, C = x.shape[0], x.shape[-1]
    xf = x.astype(jnp.float32).reshape(N, -1, num_groups, C // num_groups)
    mean = xf.mean(axis=(1, 3))
    var = ((xf - mean[:, None, :, None]) ** 2).mean(axis=(1, 3))
    return mean, jax.lax.rsqrt(var + eps)


# ---------------------------------------------------------------------------
# pallas kernels (x flattened to [B, F], F = spatial·C)
# ---------------------------------------------------------------------------

def _chunk_layout(F, C):
    ftile = min(F, FTILE)
    return ftile, F // ftile


def _group_onehots(ftile, C, G):
    """[G, ftile] one-hot masks (as f32) selecting each group's lanes —
    identical for every chunk because ftile % C == 0."""
    f_idx = jax.lax.broadcasted_iota(jnp.int32, (1, ftile), 1)
    grp = (f_idx % C) // (C // G)
    return [(grp == g).astype(jnp.float32) for g in range(G)]


def _fwd_kernel(x_ref, gt_ref, bt_ref, y_ref, mean_ref, rstd_ref,
                *, G, C, eps):
    B, F = x_ref.shape
    ftile, n_chunks = _chunk_layout(F, C)
    onehots = _group_onehots(ftile, C, G)
    m = jnp.float32(F // G)
    # pass 1 over VMEM-resident chunks: per-group Σx → mean
    s = [jnp.zeros((B, 1), jnp.float32) for _ in range(G)]
    for t in range(n_chunks):
        xc = x_ref[:, pl.ds(t * ftile, ftile)].astype(jnp.float32)
        for g, oh in enumerate(onehots):
            s[g] = s[g] + jnp.sum(xc * oh, axis=1, keepdims=True)
    mean = jnp.concatenate(s, axis=1) / m
    # pass 2: Σ(x−μ)² — two-pass variance, matching the reference spec
    # (the one-pass E[x²]−μ² form cancels catastrophically for
    # large-mean inputs); chunks are VMEM reads, so the extra pass is
    # compute-only, not HBM traffic
    v = [jnp.zeros((B, 1), jnp.float32) for _ in range(G)]
    for t in range(n_chunks):
        xc = x_ref[:, pl.ds(t * ftile, ftile)].astype(jnp.float32)
        for g, oh in enumerate(onehots):
            d = (xc - mean[:, g][:, None]) * oh
            v[g] = v[g] + jnp.sum(d * d, axis=1, keepdims=True)
    var = jnp.concatenate(v, axis=1) / m
    rstd = jax.lax.rsqrt(var + eps)
    mean_ref[:] = mean
    rstd_ref[:] = rstd
    # pass 3: normalize chunk-by-chunk
    for t in range(n_chunks):
        xc = x_ref[:, pl.ds(t * ftile, ftile)].astype(jnp.float32)
        mean_f = jnp.zeros((B, ftile), jnp.float32)
        rstd_f = jnp.zeros((B, ftile), jnp.float32)
        for g, oh in enumerate(onehots):
            mean_f += mean[:, g][:, None] * oh
            rstd_f += rstd[:, g][:, None] * oh
        yc = (xc - mean_f) * rstd_f * gt_ref[:, pl.ds(t * ftile, ftile)] \
            + bt_ref[:, pl.ds(t * ftile, ftile)]
        y_ref[:, pl.ds(t * ftile, ftile)] = yc.astype(y_ref.dtype)


def _bwd_kernel(x_ref, dy_ref, gt_ref, mean_ref, rstd_ref, dx_ref,
                *, G, C, eps):
    B, F = x_ref.shape
    ftile, n_chunks = _chunk_layout(F, C)
    onehots = _group_onehots(ftile, C, G)
    m = jnp.float32(F // G)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    # pass 1: per-group Σdx̂, Σdx̂·x̂
    s1l = [jnp.zeros((B, 1), jnp.float32) for _ in range(G)]
    s2l = [jnp.zeros((B, 1), jnp.float32) for _ in range(G)]
    for t in range(n_chunks):
        sl = pl.ds(t * ftile, ftile)
        xc = x_ref[:, sl].astype(jnp.float32)
        dxh = dy_ref[:, sl].astype(jnp.float32) * gt_ref[:, sl]
        for g, oh in enumerate(onehots):
            xh = (xc - mean[:, g][:, None]) * rstd[:, g][:, None]
            s1l[g] = s1l[g] + jnp.sum(dxh * oh, axis=1, keepdims=True)
            s2l[g] = s2l[g] + jnp.sum(dxh * xh * oh, axis=1, keepdims=True)
    s1 = jnp.concatenate(s1l, axis=1)
    s2 = jnp.concatenate(s2l, axis=1)
    # pass 2: dx
    for t in range(n_chunks):
        sl = pl.ds(t * ftile, ftile)
        xc = x_ref[:, sl].astype(jnp.float32)
        dxh = dy_ref[:, sl].astype(jnp.float32) * gt_ref[:, sl]
        mean_f = jnp.zeros((B, ftile), jnp.float32)
        rstd_f = jnp.zeros((B, ftile), jnp.float32)
        s1_f = jnp.zeros((B, ftile), jnp.float32)
        s2_f = jnp.zeros((B, ftile), jnp.float32)
        for g, oh in enumerate(onehots):
            mean_f += mean[:, g][:, None] * oh
            rstd_f += rstd[:, g][:, None] * oh
            s1_f += s1[:, g][:, None] * oh
            s2_f += s2[:, g][:, None] * oh
        xh = (xc - mean_f) * rstd_f
        dxc = (dxh - (s1_f + xh * s2_f) / m) * rstd_f
        dx_ref[:, sl] = dxc.astype(dx_ref.dtype)


def _flat(x):
    N = x.shape[0]
    F = 1
    for s in x.shape[1:]:
        F *= s
    return x.reshape(N, F), N, F


def _tile_feat(v, F):
    """[C] → [1, F] channel-tiled, computed in XLA (cheap, fused)."""
    C = v.shape[0]
    return jnp.broadcast_to(v.astype(jnp.float32)[None, :],
                            (F // C, C)).reshape(1, F)


def _pallas_fwd(x, gamma, beta, num_groups, eps):
    xf, N, F = _flat(x)
    C = x.shape[-1]
    BN = BLOCK_N
    kern = functools.partial(_fwd_kernel, G=num_groups, C=C, eps=eps)
    blk = lambda i: (i, 0)
    row = lambda i: (0, 0)
    y, mean, rstd = pl.pallas_call(
        kern,
        grid=(N // BN,),
        in_specs=[
            pl.BlockSpec((BN, F), blk, memory_space=_VMEM),
            pl.BlockSpec((1, F), row, memory_space=_VMEM),
            pl.BlockSpec((1, F), row, memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BN, F), blk, memory_space=_VMEM),
            pl.BlockSpec((BN, num_groups), blk, memory_space=_VMEM),
            pl.BlockSpec((BN, num_groups), blk, memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, F), x.dtype),
            jax.ShapeDtypeStruct((N, num_groups), jnp.float32),
            jax.ShapeDtypeStruct((N, num_groups), jnp.float32),
        ],
    )(xf, _tile_feat(gamma, F), _tile_feat(beta, F))
    return y.reshape(x.shape), mean, rstd


def _pallas_dx(x, dy, gamma, mean, rstd, num_groups, eps):
    xf, N, F = _flat(x)
    dyf, _, _ = _flat(dy)
    C = x.shape[-1]
    BN = BLOCK_N
    kern = functools.partial(_bwd_kernel, G=num_groups, C=C, eps=eps)
    blk = lambda i: (i, 0)
    dx = pl.pallas_call(
        kern,
        grid=(N // BN,),
        in_specs=[
            pl.BlockSpec((BN, F), blk, memory_space=_VMEM),
            pl.BlockSpec((BN, F), blk, memory_space=_VMEM),
            pl.BlockSpec((1, F), lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec((BN, num_groups), blk, memory_space=_VMEM),
            pl.BlockSpec((BN, num_groups), blk, memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((BN, F), blk, memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((N, F), x.dtype),
    )(xf, dyf, _tile_feat(gamma, F), mean, rstd)
    return dx.reshape(x.shape)


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def group_norm(x, gamma, beta, num_groups: int = 8, eps: float = 1e-5):
    """y = GN(x)·γ + β over trailing-channel layout (groups split C)."""
    if _use_pallas(x.shape, num_groups):
        y, _, _ = _pallas_fwd(x, gamma, beta, num_groups, eps)
        return y
    return _gn_reference(x, gamma, beta, num_groups, eps)


def _gn_fwd(x, gamma, beta, num_groups, eps):
    if _use_pallas(x.shape, num_groups):
        y, mean, rstd = _pallas_fwd(x, gamma, beta, num_groups, eps)
    else:
        y = _gn_reference(x, gamma, beta, num_groups, eps)
        mean, rstd = _stats_reference(x, num_groups, eps)
    return y, (x, gamma, mean, rstd)


def _channel_grads(x, dy, mean, rstd, num_groups):
    """dγ/dβ: one fused XLA reduction over (x, dy) — cheap relative to the
    activation-sized dx pass, and XLA fuses the two sums."""
    N, C = x.shape[0], x.shape[-1]
    G, Cg = num_groups, C // num_groups
    xg = x.astype(jnp.float32).reshape(N, -1, G, Cg)
    xhat = (xg - mean[:, None, :, None]) * rstd[:, None, :, None]
    dyg = dy.astype(jnp.float32).reshape(N, -1, G, Cg)
    dg = jnp.sum(dyg * xhat, axis=(0, 1)).reshape(C)
    db = jnp.sum(dyg, axis=(0, 1)).reshape(C)
    return dg, db


def _gn_bwd(num_groups, eps, res, dy):
    x, gamma, mean, rstd = res
    if _use_pallas(x.shape, num_groups):
        dx = _pallas_dx(x, dy, gamma, mean, rstd, num_groups, eps)
    else:
        # reference dx (same math as _bwd_kernel)
        shape = x.shape
        N, C = shape[0], shape[-1]
        G, Cg = num_groups, C // num_groups
        m = 1
        for s in shape[1:-1]:
            m *= s
        m *= Cg
        xg = x.astype(jnp.float32).reshape(N, -1, G, Cg)
        xhat = (xg - mean[:, None, :, None]) * rstd[:, None, :, None]
        dyg = dy.astype(jnp.float32).reshape(N, -1, G, Cg)
        dxhat = dyg * gamma.astype(jnp.float32).reshape(1, 1, G, Cg)
        s1 = jnp.sum(dxhat, axis=(1, 3))
        s2 = jnp.sum(dxhat * xhat, axis=(1, 3))
        dx = ((dxhat - (s1[:, None, :, None] + xhat * s2[:, None, :, None])
               / m) * rstd[:, None, :, None]).reshape(shape).astype(x.dtype)
    dg, db = _channel_grads(x, dy, mean, rstd, num_groups)
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


group_norm.defvjp(_gn_fwd, _gn_bwd)


_fused_gn_cls = None


def _get_fused_gn_cls():
    """Build the flax module class ONCE (flax import deferred; a fresh
    class per construction would defeat jit caches keyed on module type)."""
    global _fused_gn_cls
    if _fused_gn_cls is None:
        import flax.linen as nn

        class _FusedGN(nn.Module):
            num_groups: int = 8
            epsilon: float = 1e-5

            @nn.compact
            def __call__(self, x):
                C = x.shape[-1]
                scale = self.param("scale", nn.initializers.ones, (C,))
                bias = self.param("bias", nn.initializers.zeros, (C,))
                return group_norm(x, scale, bias, self.num_groups,
                                  self.epsilon)

        _fused_gn_cls = _FusedGN
    return _fused_gn_cls


def FusedGroupNorm(num_groups: int = 8, epsilon: float = 1e-5, name=None):
    """flax-compatible GroupNorm module backed by the fused kernels.
    Parameter names/shapes match nn.GroupNorm ("scale", "bias" of [C]), so
    checkpoints are interchangeable with the plain-XLA module."""
    return _get_fused_gn_cls()(num_groups=num_groups, epsilon=epsilon,
                               name=name)
