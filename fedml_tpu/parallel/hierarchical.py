"""Hierarchical FL on a 2-D (silo × clients) mesh.

Reference: fedml_api/standalone/hierarchical_fl/{trainer,group,client}.py —
clients → groups run `group_comm_round` inner FedAvg rounds, groups → global
average every `global_comm_round` (trainer.py:44-69, group.py:24-46).

TPU-native, the two aggregation tiers map onto the two mesh axes:

    inner round:  psum over the "clients" axis only   → per-silo model (ICI)
    outer round:  psum over the "silo" axis           → global model   (DCN)

so a full global round — G inner rounds on every silo plus the cross-silo
reduction — is ONE SPMD program; per-silo models never leave HBM.

Invariant kept from the reference CI (CI-script-fedavg.sh:51-59): with full
batch, E=1, full participation and one inner round, the result equals plain
FedAvg (and hence centralized) regardless of the client→silo grouping,
because Σ_g (W_g/W)·(Σ_i w_i v_i / W_g) = Σ_i (w_i/W) v_i.
"""
from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.parallel.engine import (cast_local, chunked_weighted_train,
                                       flatten_stack_x, restore_chunk_x,
                                       default_chunk)
from fedml_tpu.parallel.mesh import (CLIENT_AXIS, SILO_AXIS, make_mesh_2d,
                                     pvary_tree)
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


class MeshHierarchicalEngine(FedAvgEngine):
    """Two-tier FedAvg over a (silo, clients) mesh.

    Clients are assigned to silos contiguously: silo g owns client ids
    [g*C/S, (g+1)*C/S).  Each global round runs `group_comm_round` inner
    rounds; inner cohorts are sampled per silo with the reference's seeded
    numpy semantics (round-deterministic)."""

    def __init__(self, trainer: ClientTrainer, data: FederatedData,
                 cfg: FedConfig, n_silos: int = 2,
                 group_comm_round: int = 1,
                 mesh: Optional[Mesh] = None, donate: bool = True,
                 chunk: Optional[int] = None, local_dtype=None,
                 flat_stack: bool = True):
        if chunk is not None and chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = (chunk if chunk is not None
                      else default_chunk(local_dtype))
        self.local_dtype = local_dtype   # bf16 local masters (engine.py)
        # flat image-cohort storage + per-chunk restore, same rationale
        # and helpers as MeshFedAvgEngine (engine.py flat_stack)
        self.flat_stack = flat_stack
        self._x_image_shape = None
        self.mesh = mesh if mesh is not None else make_mesh_2d(n_silos)
        self.n_silos = self.mesh.shape[SILO_AXIS]
        self.per_silo_shards = self.mesh.shape[CLIENT_AXIS]
        self.group_comm_round = group_comm_round
        super().__init__(trainer, data, cfg, donate=donate)
        C = data.client_num
        assert C % self.n_silos == 0, (
            f"{C} clients cannot split into {self.n_silos} silos")
        self.clients_per_silo = C // self.n_silos
        self._stack = None
        self._stack_w = None
        from fedml_tpu.obs import programs as obs_programs
        self.program_family = "hierarchical"
        self.round_fn = obs_programs.instrument(
            self.program_family,
            jax.jit(self._global_round,
                    donate_argnums=(0, 1) if donate else ()))

    # -- data layout: [S, C/S, B, bs, ...] sharded (silo, clients) ----------
    def _device_stack(self):
        if self._stack is None:
            S, Cs = self.n_silos, self.clients_per_silo
            sh = NamedSharding(self.mesh, P(SILO_AXIS, CLIENT_AXIS))
            # pad the per-silo client dim to a multiple of the client-axis size
            pad = (-Cs) % self.per_silo_shards
            def up(a):
                a = np.asarray(a)
                a = a.reshape((S, Cs) + a.shape[1:])
                if pad:
                    z = np.zeros((S, pad) + a.shape[2:], a.dtype)
                    a = np.concatenate([a, z], axis=1)
                return jax.device_put(a, sh)
            shards = dict(self.data.client_shards)
            if self.flat_stack:
                shards, image_shape = flatten_stack_x(shards)
                if image_shape is not None:
                    self._x_image_shape = image_shape
            self._stack = {k: up(v) for k, v in shards.items()}
            w = np.asarray(self.data.client_num_samples, np.float32)
            self._stack_w = up(w)
            self._cs_padded = Cs + pad
        return self._stack, self._stack_w

    # -- sampling: per-silo cohort ids for every inner round ----------------
    def sample_inner_rounds(self, global_round: int):
        """ids[g_round, silo, K_pad] (silo-local indices) + wmask like it.
        Reference seed discipline: np.random.seed(round) per sampling call
        (group.py / fedavg_api.py:83-91)."""
        K = min(self.cfg.client_num_per_round, self.clients_per_silo)
        Kp = K + ((-K) % self.per_silo_shards)
        G = self.group_comm_round
        ids = np.zeros((G, self.n_silos, Kp), np.int32)
        wmask = np.zeros((G, self.n_silos, Kp), np.float32)
        for g in range(G):
            rs = np.random.RandomState(global_round * self.group_comm_round + g)
            for s in range(self.n_silos):
                if K == self.clients_per_silo:
                    pick = np.arange(K)
                else:
                    pick = rs.choice(self.clients_per_silo, K, replace=False)
                ids[g, s, :K] = pick
                wmask[g, s, :K] = 1.0
        return jnp.asarray(ids), jnp.asarray(wmask)

    # -- the global round program -------------------------------------------
    def _global_round(self, variables, server_state, stack, stack_w, ids,
                      wmask, rng):
        mesh = self.mesh
        trainer, epochs = self.trainer, self.cfg.epochs
        G = self.group_comm_round
        sc = P(SILO_AXIS, CLIENT_AXIS)

        def shard_body(variables, stack, stack_w, ids, wmask, rngs):
            # local shapes: stack [1, c_loc, B, bs, ...], ids [G, 1, k_loc]
            # silo-local gather, hoisted OUT of the inner-round scan (XLA
            # does not hoist collectives from scan bodies): all_gather this
            # silo's client shards along the client axis once; data volume
            # per silo is small (C/S clients) and the gather rides ICI.
            full = jax.tree.map(
                lambda a: jax.lax.all_gather(a[0], CLIENT_AXIS, tiled=True),
                stack)
            w_full = jax.lax.all_gather(stack_w[0], CLIENT_AXIS, tiled=True)

            def inner_round(vars_g, inp):
                ids_g, wm_g, rng_g = inp          # [1,k_loc], [1,k_loc], [2]
                idx = ids_g[0]
                cohort = jax.tree.map(lambda a: jnp.take(a, idx, axis=0), full)
                weights = jnp.take(w_full, idx) * wm_g[0]
                crngs = jax.random.split(rng_g, idx.shape[0])
                # per-client training varies over the client axis too
                vars_g = pvary_tree(vars_g, CLIENT_AXIS)
                # bf16 local masters: silo/global masters stay f32, only
                # the per-client step chain runs reduced (engine.py)
                local_vars = cast_local(vars_g, self.local_dtype)
                # chunked inner loop (same HBM-bounding scan as the flat
                # engine, parallel/engine.py::chunked_weighted_train)
                num, den, lsum = chunked_weighted_train(
                    trainer, local_vars, cohort, weights, crngs, epochs,
                    vary_axes=(SILO_AXIS, CLIENT_AXIS),
                    chunk_cap=self.chunk,
                    restore_x=lambda cs: restore_chunk_x(
                        self._x_image_shape, cs))
                num = jax.lax.psum(num, CLIENT_AXIS)        # ICI tier
                den = jax.lax.psum(den, CLIENT_AXIS)
                silo_vars = jax.tree.map(
                    lambda s, ref: (s / den).astype(ref.dtype), num, vars_g)
                loss = jax.lax.psum(lsum, CLIENT_AXIS) / den
                return silo_vars, (loss, den)

            inner_rngs = jax.random.split(rngs, G)
            # the scan carries the *per-silo* model (replicated within a
            # silo, distinct across silos); mark the initial carry as
            # silo-varying so the carry type is stable across iterations
            vars0 = pvary_tree(variables, SILO_AXIS)
            silo_vars, (losses, dens) = jax.lax.scan(
                inner_round, vars0, (ids, wmask, inner_rngs))
            # outer tier: sample-weighted cross-silo average (DCN psum)
            W_g = dens[-1]
            num = jax.tree.map(
                lambda v: jax.lax.psum(v.astype(jnp.float32) * W_g,
                                       SILO_AXIS), silo_vars)
            W = jax.lax.psum(W_g, SILO_AXIS)
            new_vars = jax.tree.map(
                lambda s, ref: (s / W).astype(ref.dtype), num, variables)
            loss = jax.lax.psum(losses[-1] * W_g, SILO_AXIS) / W
            return new_vars, loss

        new_variables, train_loss = jax.shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), sc, sc, P(None, SILO_AXIS, CLIENT_AXIS),
                      P(None, SILO_AXIS, CLIENT_AXIS), P()),
            out_specs=(P(), P()))(
                variables, stack, stack_w, ids, wmask, rng)
        return new_variables, server_state, {"train_loss": train_loss}

    # the base FedAvgEngine.run drives the loop through these hooks
    def _prepare_variables(self, variables: Pytree) -> Pytree:
        from fedml_tpu.parallel.mesh import replicated_sharding
        return jax.device_put(variables, replicated_sharding(self.mesh))

    def _round_args(self, round_idx: int) -> tuple:
        stack, stack_w = self._device_stack()
        ids, wmask = self.sample_inner_rounds(round_idx)
        return (stack, stack_w, ids, wmask)
