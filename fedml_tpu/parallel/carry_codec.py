"""Carry wire codecs — the compressed inter-host tier (ISSUE 16).

The two-level aggregation tier ships each host's P-sized flat f32 carry
partials across the DCN at every commit barrier.  These codecs trade
that 4 B/param for ~1 B/param on the wire:

* ``f32`` — the identity codec and the DEFAULT: bytes on the wire are
  exactly ``vec.tobytes()`` as in the PR-13/14 runners, so every bitwise
  anchor (1p-vs-2p, bitwise-under-death) holds on this path unchanged.
  This is the escape hatch — any compressed-tier bug is debugged by
  flipping back to ``f32`` and re-running the pins.
* ``int8`` — per-chunk int8/affine fixed-point reusing the comm-layer
  v2 wire discipline (comm.message.affine_int8_*): each CHUNK-sized
  slice of the carry stores an f32 (min, scale) pair then 1 B/element.
  ~3.9x fewer bytes at ``chunk >> 2`` with quantization error bounded
  by scale/2 = (chunk range)/510 per element.
* ``int8_ef`` — int8/affine plus per-block error-feedback residuals:
  the quantization error of round r is added back into round r+1's
  carry before encoding, so the SUM over rounds converges to the true
  sum (single-round error bound, not O(rounds)).  The residual
  accumulator is runner state — it rides ``state_dict()`` /
  ``load_state_dict()`` and checkpoints through orbax as
  ``extra_state`` so crash-resume continues the same error trajectory.
* ``topk`` — fixed-k magnitude sparsification (ISSUE 19): only the
  k = max(1, dim // ratio) largest-|value| entries ship, as k u32
  indices + k f32 values.  ~(4*dim)/(8 + 8k) fewer bytes (7.5x at the
  default ratio 16) but LOSSY — the dropped mass is gone.  The shipped
  values are exact f32 (no quantization), so any vector with <= k
  nonzeros round-trips bitwise.
* ``topk_ef`` — top-k DELTA encoding against a replicated
  reconstruction mirror: the carry is a SNAPSHOT stream (each round's
  vector is a weighted model sum, not an increment), so unlike
  ``int8_ef`` the error feedback must live in the *difference* domain.
  The encoder ships the top-k of ``vec - rec`` where ``rec`` is the
  receiver's integrated reconstruction, and EVERY rank (including the
  encoder itself) advances ``rec`` by integrating the identical
  allgathered bytes — the mirror is replicated by construction, never
  synchronized.  Unsent coordinates have ``|vec - rec|`` below the
  round's selection threshold, so the reconstruction error is bounded
  by a SINGLE round's truncation threshold at every round (feeding the
  raw snapshot through a stream-EF residual instead would accumulate
  the full unselected model mass every round and diverge).

Wire layout (int8 flavors), per block:

    u32 dim ‖ f32 min[n_chunks] ‖ f32 scale[n_chunks] ‖ int8 q[dim]

Wire layout (topk flavors), per block:

    u32 dim ‖ u32 k ‖ u32 idx[k] ‖ f32 val[k]

k is a pure function of dim (fixed ratio), so the equal-length-bytes
contract the HostChannel allgather requires holds by construction.
Top-k selection runs as a jitted ``lax.top_k`` over |vec| (cached per
(dim, k) — no per-element Python); the residual update is a vectorized
scatter against the round-tripped values.  Sparse codecs expose
``sparse = True`` plus ``decode_pairs()`` so the runner fold can
scatter-add (index, value) pairs straight into the flat f32 carry
without densifying per block.

The payload size is a pure function of (dim, chunk) — load-bearing:
``ElasticChannel`` requires uniform item payloads to split collective
blobs, so a codec MUST produce equal-length bytes for equal-length
vectors (``encoded_nbytes`` is the contract).  The header (min, scale)
values are stored as f32 and the encoder quantizes against the
f32-ROUNDED values, so every rank's dequant prologue reconstructs
bit-identical f32 carries from the same wire bytes.

Decoding is deterministic f64 math on every host, so the global fold
over decoded partials commits replicated results — the compressed tier
changes accuracy (inside the committed quality bands), never replica
agreement.
"""
from __future__ import annotations

import struct

import numpy as np

from fedml_tpu.comm.message import affine_int8_decode, affine_int8_encode

CARRY_CODECS = ("f32", "int8", "int8_ef", "topk", "topk_ef")

# ~16 KiB of f32 per (min, scale) pair: coarse enough to amortize the
# 8 B header, fine enough that one outlier only poisons its own chunk
DEFAULT_CHUNK = 4096

# ship 1-in-16 entries by default: 8 B/kept-entry -> 7.5x fewer wire
# bytes than f32 at dim >> 1, comfortably past the ISSUE-19 6x gate
DEFAULT_TOPK_RATIO = 16


class CarryCodec:
    """Identity f32 codec — the default bitwise escape hatch.

    ``encode`` must stay byte-identical to ``vec.tobytes()`` of a
    little-endian f32 vector: the PR-13/14 bitwise anchors pin the
    runner behavior built on exactly those bytes.
    """

    name = "f32"

    def __init__(self, chunk: int = DEFAULT_CHUNK):
        self.chunk = int(chunk)
        if self.chunk <= 0:
            raise ValueError(f"carry chunk must be positive, got {chunk}")

    def encoded_nbytes(self, dim: int) -> int:
        return 4 * int(dim)

    def encode(self, block: int, vec: np.ndarray) -> bytes:
        return np.ascontiguousarray(vec, dtype="<f4").tobytes()

    def decode(self, buf: bytes) -> np.ndarray:
        return np.frombuffer(buf, dtype="<f4")

    def retain_blocks(self, blocks) -> None:
        """Keep per-block codec state only for `blocks` (elastic
        ownership changes) — stateless codecs have nothing to do."""

    # residual state (empty for stateless codecs) — the runner
    # checkpoints this dict as orbax extra_state
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        if state:
            raise ValueError(f"codec {self.name!r} carries no state, "
                             f"got keys {sorted(state)}")


class Int8CarryCodec(CarryCodec):
    """Per-chunk int8/affine fixed-point (the v2 wire discipline)."""

    name = "int8"

    def _n_chunks(self, dim: int) -> int:
        return -(-int(dim) // self.chunk)

    def encoded_nbytes(self, dim: int) -> int:
        return 4 + 8 * self._n_chunks(dim) + int(dim)

    def _qparams(self, vec: np.ndarray):
        """Per-chunk f32 (min, scale) + the per-element f64 broadcasts
        the affine math runs against.  reduceat handles the ragged tail
        chunk exactly; scales that round to 0.0 in f32 (degenerate or
        subnormal range) fall back to 1.0 so encode/decode stay finite."""
        dim = vec.size
        idx = np.arange(0, dim, self.chunk)
        mn32 = np.minimum.reduceat(vec, idx).astype(np.float32)
        mx = np.maximum.reduceat(vec, idx).astype(np.float64)
        sc32 = ((mx - mn32.astype(np.float64)) / 255.0).astype(np.float32)
        sc32[sc32 == 0] = np.float32(1.0)
        per_mn = np.repeat(mn32.astype(np.float64), self.chunk)[:dim]
        per_sc = np.repeat(sc32.astype(np.float64), self.chunk)[:dim]
        return mn32, sc32, per_mn, per_sc

    def _encode_vec(self, block: int, vec: np.ndarray) -> bytes:
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        if vec.size and not np.all(np.isfinite(vec)):
            raise ValueError(
                f"non-finite carry for block {block}: the int8 tier "
                f"cannot represent it — rerun with --carry_codec f32 "
                f"(the escape hatch) to debug the divergence")
        mn32, sc32, per_mn, per_sc = self._qparams(vec)
        q = affine_int8_encode(vec, per_mn, per_sc)
        return (struct.pack("<I", vec.size) + mn32.tobytes()
                + sc32.tobytes() + q.tobytes())

    def encode(self, block: int, vec: np.ndarray) -> bytes:
        return self._encode_vec(block, vec)

    def decode(self, buf: bytes) -> np.ndarray:
        (dim,) = struct.unpack_from("<I", buf, 0)
        nc = self._n_chunks(dim)
        if len(buf) != self.encoded_nbytes(dim):
            raise ValueError(
                f"carry payload is {len(buf)} B but dim={dim} chunk="
                f"{self.chunk} encodes to {self.encoded_nbytes(dim)} B "
                f"— mixed-codec cluster?")
        mn32 = np.frombuffer(buf, dtype="<f4", count=nc, offset=4)
        sc32 = np.frombuffer(buf, dtype="<f4", count=nc, offset=4 + 4 * nc)
        q = np.frombuffer(buf, dtype=np.int8, count=dim, offset=4 + 8 * nc)
        per_mn = np.repeat(mn32.astype(np.float64), self.chunk)[:dim]
        per_sc = np.repeat(sc32.astype(np.float64), self.chunk)[:dim]
        return affine_int8_decode(q, per_mn, per_sc, np.float32)


class _BlockResidualState:
    """Per-block f64 error-feedback residual state shared by the
    stateful (`*_ef`) codecs: elastic retention, checkpoint dict."""

    _residual: dict

    def retain_blocks(self, blocks) -> None:
        """Forget residuals for blocks this rank no longer owns
        (elastic re-partition): a re-adopting rank starts that block's
        residual at zero — only the compression-error trajectory
        shifts, never replica agreement (every rank decodes the same
        wire bytes)."""
        keep = {int(b) for b in blocks}
        for b in list(self._residual):
            if b not in keep:
                del self._residual[b]

    def state_dict(self) -> dict:
        return {"residual": {str(b): np.asarray(v, dtype=np.float64)
                             for b, v in sorted(self._residual.items())}}

    def load_state_dict(self, state: dict) -> None:
        if not state:
            self._residual = {}
            return
        res = state.get("residual", state)
        self._residual = {int(b): np.asarray(v, dtype=np.float64)
                          for b, v in res.items()}


class Int8EFCarryCodec(_BlockResidualState, Int8CarryCodec):
    """int8/affine with per-block error-feedback residuals: encode
    ships q(vec + residual[block]) and keeps the new quantization error
    for the next round, so the summed carry over rounds tracks the true
    sum within a single round's quantization error."""

    name = "int8_ef"

    def __init__(self, chunk: int = DEFAULT_CHUNK):
        super().__init__(chunk)
        self._residual: dict[int, np.ndarray] = {}

    def encode(self, block: int, vec: np.ndarray) -> bytes:
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        res = self._residual.get(block)
        if res is not None and res.size != vec.size:
            res = None                 # block re-partitioned; start clean
        # f64 carry+residual so the fed-back error does not itself round
        fed = (vec.astype(np.float64)
               + (res if res is not None else 0.0))
        buf = self._encode_vec(block, fed.astype(np.float32))
        self._residual[block] = fed - self.decode(buf).astype(np.float64)
        return buf


def _topk_select(dim: int, k: int):
    """Jitted fixed-k magnitude selection, cached per (dim, k): one
    `lax.top_k` over |vec| then a gather — no per-element Python.
    Imported lazily so the codec module stays importable without a
    working jax runtime (the fold/decode side is pure numpy)."""
    fn = _topk_select._cache.get((dim, k))
    if fn is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def sel(vec):
            _, idx = jax.lax.top_k(jnp.abs(vec), k)
            return idx.astype(jnp.uint32), jnp.take(vec, idx)

        fn = _topk_select._cache[(dim, k)] = sel
    return fn


_topk_select._cache = {}


class TopKCarryCodec(CarryCodec):
    """Fixed-k magnitude top-k sparsification (LOSSY without the `_ef`
    residual flavor — the dropped (dim - k) mass never ships)."""

    name = "topk"
    sparse = True

    def __init__(self, chunk: int = DEFAULT_CHUNK,
                 topk_ratio: int = DEFAULT_TOPK_RATIO):
        super().__init__(chunk)
        self.topk_ratio = int(topk_ratio)
        if self.topk_ratio <= 0:
            raise ValueError(
                f"topk ratio must be positive, got {topk_ratio}")

    def k_for(self, dim: int) -> int:
        """k is a pure function of dim — the equal-length-bytes
        contract the HostChannel allgather splits by."""
        dim = int(dim)
        return 0 if dim == 0 else max(1, dim // self.topk_ratio)

    def encoded_nbytes(self, dim: int) -> int:
        return 8 + 8 * self.k_for(dim)

    def _encode_vec(self, block: int, vec: np.ndarray) -> bytes:
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        if vec.size and not np.all(np.isfinite(vec)):
            raise ValueError(
                f"non-finite carry for block {block}: NaN poisons the "
                f"top-k magnitude ordering — rerun with --carry_codec "
                f"f32 (the escape hatch) to debug the divergence")
        k = self.k_for(vec.size)
        if k == 0:
            return struct.pack("<II", 0, 0)
        idx, vals = _topk_select(vec.size, k)(vec)
        return (struct.pack("<II", vec.size, k)
                + np.ascontiguousarray(idx, dtype="<u4").tobytes()
                + np.ascontiguousarray(vals, dtype="<f4").tobytes())

    def encode(self, block: int, vec: np.ndarray) -> bytes:
        return self._encode_vec(block, vec)

    def decode_pairs(self, buf: bytes):
        """(dim, idx u32[k], vals f32[k]) without densifying — the
        runner fold scatter-adds these straight into the flat carry."""
        dim, k = struct.unpack_from("<II", buf, 0)
        if len(buf) != self.encoded_nbytes(dim):
            raise ValueError(
                f"carry payload is {len(buf)} B but dim={dim} ratio="
                f"{self.topk_ratio} encodes to {self.encoded_nbytes(dim)}"
                f" B — mixed-codec cluster?")
        idx = np.frombuffer(buf, dtype="<u4", count=k, offset=8)
        vals = np.frombuffer(buf, dtype="<f4", count=k, offset=8 + 4 * k)
        return dim, idx, vals

    def decode(self, buf: bytes) -> np.ndarray:
        dim, idx, vals = self.decode_pairs(buf)
        arr = np.zeros(dim, dtype=np.float32)
        arr[idx] = vals                # top_k indices are unique
        return arr


class TopKEFCarryCodec(_BlockResidualState, TopKCarryCodec):
    """top-k DELTA encoding with a replicated reconstruction mirror:
    ``encode`` ships the k largest-|.| entries of ``vec - rec`` (exact
    f32 values), ``integrate`` scatter-adds a block's wire pairs into
    that block's ``rec`` and returns the reconstruction.  Every rank
    integrates the identical allgathered bytes for EVERY block — the
    encoder included — so the mirror agrees bitwise across the cluster
    without ever being synchronized, and a block adopted by a new
    owner (elastic view change) continues from the very mirror the new
    owner already holds.  Unsent coordinates have ``|vec - rec|``
    below the round's selection threshold: the reconstruction error is
    bounded by a single round's truncation threshold (the stream-EF
    discipline of ``int8_ef`` would instead re-accumulate the whole
    unselected snapshot mass every round — the carry is a weighted
    model SUM, not an increment — and diverge)."""

    name = "topk_ef"

    def __init__(self, chunk: int = DEFAULT_CHUNK,
                 topk_ratio: int = DEFAULT_TOPK_RATIO):
        super().__init__(chunk, topk_ratio)
        # block -> f32 reconstruction mirror (the "residual" state key
        # is kept for the checkpoint extra_state convention: here the
        # state IS the reconstruction, error = vec - rec implicitly)
        self._residual: dict[int, np.ndarray] = {}

    def _rec(self, block: int, dim: int) -> np.ndarray:
        rec = self._residual.get(block)
        if rec is None or rec.size != dim:
            # unseen or re-partitioned block: the mirror restarts at
            # zero ON EVERY RANK at once (all ranks see the same block
            # partition), so agreement holds through the reset
            rec = np.zeros(dim, dtype=np.float32)
            self._residual[block] = rec
        return rec

    def encode(self, block: int, vec: np.ndarray) -> bytes:
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        # NO state update here: the mirror advances only in
        # integrate(), on the allgathered bytes, identically on every
        # rank — the encoder's own integrate() of its own frame is
        # what keeps its mirror honest
        return self._encode_vec(block, vec - self._rec(block, vec.size))

    def integrate(self, block: int, buf: bytes) -> np.ndarray:
        """Advance block's reconstruction by one wire frame and return
        it (f32, the runner fold's input).  Scatter-add is well-defined
        — top-k indices are unique — and pure f32, so every rank's
        mirror stays byte-identical given identical wire bytes."""
        dim, idx, vals = self.decode_pairs(buf)
        rec = self._rec(block, dim)
        rec[idx] += vals
        return rec

    def retain_blocks(self, blocks) -> None:
        """Keep EVERY block's mirror (override of the encoder-state
        convention): rec is replicated DECODE state — every rank
        integrates every block — so an ownership change must not drop
        it; the new owner encodes deltas against the same mirror the
        old owner's frames built."""

    def state_dict(self) -> dict:
        return {"residual": {str(b): np.asarray(v, dtype=np.float32)
                             for b, v in sorted(self._residual.items())}}

    def load_state_dict(self, state: dict) -> None:
        if not state:
            self._residual = {}
            return
        res = state.get("residual", state)
        self._residual = {int(b): np.ascontiguousarray(v, np.float32)
                          for b, v in res.items()}


def make_carry_codec(name: str, *, chunk: int = DEFAULT_CHUNK,
                     topk_ratio: int = DEFAULT_TOPK_RATIO) -> CarryCodec:
    """Codec by CLI name (``--carry_codec f32|int8|int8_ef|topk|topk_ef``)."""
    try:
        cls = {"f32": CarryCodec, "int8": Int8CarryCodec,
               "int8_ef": Int8EFCarryCodec, "topk": TopKCarryCodec,
               "topk_ef": TopKEFCarryCodec}[name]
    except KeyError:
        raise ValueError(f"unknown carry codec {name!r}; "
                         f"expected one of {CARRY_CODECS}") from None
    if name in ("topk", "topk_ef"):
        return cls(chunk=chunk, topk_ratio=topk_ratio)
    return cls(chunk=chunk)
