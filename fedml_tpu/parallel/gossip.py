"""Decentralized gossip (DSGD) over a mesh ring — serverless FL.

Reference: fedml_api/distributed/decentralized_framework/ (neighbor
wait-and-advance over a TopologyManager ring) and
fedml_api/standalone/decentralized/client_dsgd.py (DSGD mixing).  The
reference moves models between worker processes with MPI point-to-point
sends; here every mesh device owns one worker's model and the neighbor
exchange is `lax.ppermute` over the ring — the gossip step

    v_i ← w_self·v_i + w_nbr·(v_{i-1} + v_{i+1})

is two ICI shifts, no host involvement (SURVEY.md §2.5: 'neighbor exchange
= lax.ppermute over mesh ring').
"""
from __future__ import annotations

import logging
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.parallel.mesh import make_mesh
from fedml_tpu.utils.config import FedConfig

log = logging.getLogger(__name__)
Pytree = Any


class MeshGossipEngine(FedAvgEngine):
    """One worker model per mesh shard; ring-gossip mixing each round.

    `neighbor_weight` follows the reference's row-normalized symmetric ring
    (SymmetricTopologyManager.generate_topology,
    symmetric_topology_manager.py:21-52): with 2 neighbors each row is
    [w_nbr, w_self, w_nbr]."""

    def __init__(self, trainer: ClientTrainer, data: FederatedData,
                 cfg: FedConfig, mesh: Optional[Mesh] = None,
                 self_weight: float = 1.0 / 3.0, donate: bool = True,
                 flat_stack: bool = True):
        # flat image-stack storage + per-worker restore, same rationale
        # and helpers as MeshFedAvgEngine (engine.py flat_stack) — the
        # gossip stack is the FULL client data, device-resident, so the
        # padded-relayout cost it avoids is at its largest here
        self.flat_stack = flat_stack
        self._x_image_shape = None
        self.mesh = mesh if mesh is not None else make_mesh()
        if len(self.mesh.axis_names) != 1:
            raise ValueError("gossip requires a 1-D (ring) mesh; got axes "
                             f"{self.mesh.axis_names}")
        self.n_shards = int(np.prod(list(self.mesh.shape.values())))
        self.self_weight = self_weight
        super().__init__(trainer, data, cfg, donate=donate)
        # every client is a gossip worker (client_dsgd.py); workers are laid
        # out contiguously over the mesh ring in blocks of C/n_shards
        self.n_workers = data.client_num
        assert self.n_workers % self.n_shards == 0, (
            f"{self.n_workers} workers over {self.n_shards} shards")
        self._stack = None
        self._stack_w = None
        from fedml_tpu.obs import programs as obs_programs
        self.program_family = "gossip"
        self.round_fn = obs_programs.instrument(
            self.program_family,
            jax.jit(self._gossip_round,
                    donate_argnums=(0,) if donate else ()))

    def _device_stack(self):
        if self._stack is None:
            sh = NamedSharding(self.mesh, P(self.mesh.axis_names))
            shards = dict(self.data.client_shards)
            if self.flat_stack:
                from fedml_tpu.parallel.engine import flatten_stack_x
                shards, image_shape = flatten_stack_x(shards)
                if image_shape is not None:
                    self._x_image_shape = image_shape
            self._stack = {k: jax.device_put(np.asarray(v), sh)
                           for k, v in shards.items()}
            self._stack_w = jax.device_put(
                np.asarray(self.data.client_num_samples, np.float32), sh)
        return self._stack, self._stack_w

    def init_worker_variables(self, rng: Optional[jax.Array] = None):
        """[W, ...] stacked worker models, one per shard (all equal at init)."""
        v = self.init_variables(rng)
        stacked = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (self.n_workers,) + a.shape),
            v)
        sh = NamedSharding(self.mesh, P(self.mesh.axis_names))
        return jax.tree.map(lambda a: jax.device_put(a, sh), stacked)

    def _gossip_round(self, worker_vars, stack, stack_w, rng):
        mesh, axes = self.mesh, self.mesh.axis_names
        trainer, epochs = self.trainer, self.cfg.epochs
        W = self.n_workers
        w_self = self.self_weight
        w_nbr = (1.0 - w_self) / 2.0
        sc = P(axes)

        img = self._x_image_shape

        def shard_body(worker_vars, cohort, weights, rngs):
            # this shard's workers: [w_loc, ...]; each trains on its clients
            def one(vars_i, shard, crng):
                from fedml_tpu.parallel.engine import restore_shard_x
                shard = restore_shard_x(img, shard)  # flat_stack
                v, loss, _ = trainer.local_train(vars_i, shard, crng, epochs)
                return v, loss

            vs, losses = jax.vmap(one)(worker_vars, cohort, rngs)
            # ring gossip: shift the whole local block both ways. Within the
            # block the neighbor is a jnp.roll; across block edges the
            # wrap-around element comes from the adjacent device (ppermute).
            n_sh = jax.lax.axis_size(axes[0])
            perm_fwd = [(i, (i + 1) % n_sh) for i in range(n_sh)]
            perm_bwd = [(i, (i - 1) % n_sh) for i in range(n_sh)]

            def mix(x):
                left = jnp.roll(x, 1, axis=0)
                right = jnp.roll(x, -1, axis=0)
                if n_sh > 1:
                    # fix the wrapped entries with cross-device edges
                    from_prev = jax.lax.ppermute(x[-1], axes[0], perm_fwd)
                    from_next = jax.lax.ppermute(x[0], axes[0], perm_bwd)
                    left = left.at[0].set(from_prev)
                    right = right.at[-1].set(from_next)
                return w_self * x + w_nbr * (left + right)

            mixed = jax.tree.map(
                lambda x: mix(x.astype(jnp.float32)).astype(x.dtype), vs)
            den = jax.lax.psum(jnp.sum(weights), axes)
            loss = jax.lax.psum(jnp.sum(losses * weights), axes) / den
            return mixed, loss

        stack_rngs = jax.random.split(rng, W)
        new_vars, train_loss = jax.shard_map(
            shard_body, mesh=mesh, in_specs=(sc, sc, sc, sc),
            out_specs=(sc, P()))(worker_vars, stack, stack_w, stack_rngs)
        return new_vars, {"train_loss": train_loss}

    def _local_eval_transform(self, shard: dict) -> dict:
        """evaluate_local(split="train") reuses the resident gossip
        stack, which stores x FLAT under flat_stack (shared restore
        guard — restore_flat_eval_shard; ADVICE r4)."""
        from fedml_tpu.parallel.engine import restore_flat_eval_shard
        return restore_flat_eval_shard(self._x_image_shape, shard)

    def consensus_variables(self, worker_vars):
        """Uniform average of all worker models (for evaluation)."""
        return jax.tree.map(lambda a: jnp.mean(a.astype(jnp.float32),
                                               axis=0).astype(a.dtype),
                            worker_vars)

    def run(self, rounds: Optional[int] = None) -> Pytree:
        cfg = self.cfg
        worker_vars = self.init_worker_variables()
        rng = jax.random.PRNGKey(cfg.seed + 1)
        rounds = rounds if rounds is not None else cfg.comm_round
        stack, stack_w = self._device_stack()
        for round_idx in range(rounds):
            t0 = time.time()
            rng, round_rng = jax.random.split(rng)
            worker_vars, m = self.round_fn(worker_vars, stack, stack_w,
                                           round_rng)
            if (round_idx % cfg.frequency_of_the_test == 0
                    or round_idx == rounds - 1):
                stats = self.evaluate(self.consensus_variables(worker_vars))
                stats.update(round=round_idx,
                             train_loss=float(m["train_loss"]),
                             round_time=time.time() - t0)
                self.metrics_history.append(stats)
                log.info("gossip round %d: %s", round_idx, stats)
        return worker_vars
