"""Double-buffered host→device prefetch pipeline (streaming engines).

The streaming and block-stream rounds are transfer-bound at large
cohorts (VERDICT r5: the 4096-client block-streamed round ran exactly at
measured tunnel bandwidth): each client block is gathered, cast, and
uploaded, and only then does the round loop dispatch compute on it.
`jax.device_put` and jit dispatch are asynchronous, but the HOST side of
an upload — the `np.take` gather over the client stack, the stack_dtype
cast, the flat_stack reshape — runs on the dispatching thread and
serializes with the round loop.  `Prefetcher` moves production to a
background thread with a depth-bounded handoff: while the device trains
on block k, the host prepares and uploads block k+1.  At the default
depth=2 this is classic double buffering — the item the consumer holds
plus one in flight — so device data memory keeps the same
O(2·block bytes) bound the synchronous loop had (pinned by
tests/test_parallel_stream.py's live-bytes tests).

`InlineFetcher` is the `--no_prefetch` escape hatch: the identical
iteration contract with production inlined into `get()` — strictly
synchronous gather→upload→compute, kept for bitwise comparison against
the pipelined path (tests/test_prefetch.py) and for debugging.

`AsyncValue` is the one-shot variant the per-round streaming path uses:
round r+1's whole-cohort gather+upload runs on a background thread
while round r computes.

Thread-safety: jax dispatch (device_put included) is thread-safe; the
producer thread touches only host numpy data and enqueue-side jax
calls.  Every upload lands in the engine's TransferOverlapStats
(utils/profiling.py) from whichever thread runs it — walls AND payload
bytes (`add_h2d_bytes`, the transfer-compression accounting: the engine
counts each host buffer it hands to device_put, so uint8/bf16 stacks
report their real H2D reduction per round) — and consumer-side blocking
waits are recorded so overlap_fraction is measurable.

The pipeline is dtype-agnostic by construction: a uint8-quantized block
(stack_dtype=uint8) rides the same produce()/get() contract at 1/4 the
f32 bytes, which shrinks exactly the upload wall this double buffer
exists to hide.
"""
from __future__ import annotations

import contextlib
import logging
import queue
import threading
from typing import Any, Callable, Optional, Sequence

from fedml_tpu.utils.profiling import TransferOverlapStats

log = logging.getLogger(__name__)
_SENTINEL = object()


class Prefetcher:
    """Run `produce(item)` for each work item on a background thread,
    delivering results in order via `get()`, with at most `depth`
    results materialized at once (the one the consumer last took plus
    `depth-1` queued/in-flight).  A producer exception is re-raised
    from the next `get()`.  `close()` (also via context manager exit)
    always stops the worker, joins it, and drops undelivered results —
    an aborted round can never leak a worker thread or hand a stale
    uploaded buffer to the next round."""

    def __init__(self, produce: Callable[[Any], Any], items: Sequence,
                 depth: int = 2, stats: Optional[TransferOverlapStats] = None,
                 name: str = "h2d-prefetch"):
        if depth < 2:
            raise ValueError(f"depth must be >= 2 (double buffer), got "
                             f"{depth}")
        self._produce = produce
        self._items = list(items)
        self._stats = stats
        self._q: queue.Queue = queue.Queue()
        # permits = how far the producer may run ahead of the consumer;
        # acquired before each produce, released on each get
        self._slots = threading.Semaphore(depth - 1)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._work, name=name,
                                        daemon=True)
        self._thread.start()

    def _work(self) -> None:
        try:
            for item in self._items:
                self._slots.acquire()
                if self._stop.is_set():
                    return
                out = self._produce(item)
                if self._stop.is_set():
                    # closed mid-produce (close()'s join may even have
                    # timed out on the slow-tunnel path): DROP the
                    # result — enqueueing it would park a stale
                    # uploaded block past the drain, breaking the
                    # O(2·block) bound for the next round
                    return
                self._q.put(out)
        except BaseException as e:          # surfaced from get()
            self._err = e
            self._q.put(_SENTINEL)

    def get(self):
        """Next result, blocking until the worker has produced it (the
        block recorded as wait_wall in `stats`)."""
        wait = (self._stats.waiting() if self._stats is not None
                else contextlib.nullcontext())
        with wait:
            while True:
                try:
                    out = self._q.get(timeout=5.0)
                    break
                except queue.Empty:
                    if not self._thread.is_alive():
                        # the worker may have put its final result and
                        # exited between the timeout and the liveness
                        # check — drain once more before declaring it
                        # dead (on the slow-tunnel path every block
                        # takes multiple timeout cycles)
                        try:
                            out = self._q.get_nowait()
                            break
                        except queue.Empty:
                            raise RuntimeError(
                                "prefetch worker died without a result"
                            ) from self._err
        if out is _SENTINEL:
            raise self._err
        self._slots.release()
        return out

    def close(self) -> None:
        """Stop the worker, join it, drop undelivered buffers."""
        self._stop.set()
        # unblock a worker parked in acquire (twice is enough: it checks
        # _stop right after acquiring and never re-acquires before that)
        self._slots.release()
        self._slots.release()
        self._thread.join(timeout=60.0)
        if self._thread.is_alive():
            # a single block upload can exceed the join timeout on the
            # slow-tunnel platform; the worker will see _stop after its
            # produce returns and drop the result (never enqueue it)
            log.warning("prefetch worker still mid-upload after close() "
                        "join timeout; it will discard its result")
        while True:                         # drop queued results
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class InlineFetcher:
    """The --no_prefetch path: `get()` runs `produce(item)` inline —
    the strictly synchronous upload→compute ordering.  Same contract as
    Prefetcher so the round loops are knob-agnostic.  The inline
    produce IS consumer blocking, so it is recorded as wait_wall: the
    synchronous path correctly reports overlap_fraction ≈ 0 (nothing
    hidden), not a vacuous 1.0."""

    def __init__(self, produce: Callable[[Any], Any], items: Sequence,
                 depth: int = 2, stats: Optional[TransferOverlapStats] = None,
                 name: str = "h2d-inline"):
        self._produce = produce
        self._it = iter(list(items))
        self._stats = stats

    def get(self):
        item = next(self._it)
        wait = (self._stats.waiting() if self._stats is not None
                else contextlib.nullcontext())
        with wait:
            return self._produce(item)

    def close(self) -> None:
        pass

    def __enter__(self) -> "InlineFetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncValue:
    """One value computed on a background thread — the streaming path's
    next-round cohort gather+upload.  `result()` joins and re-raises;
    recorded as a consumer wait in `stats` when the value is not ready
    yet."""

    def __init__(self, fn: Callable, *args,
                 stats: Optional[TransferOverlapStats] = None,
                 name: str = "h2d-prefetch-round"):
        self._out = None
        self._err: Optional[BaseException] = None
        self._stats = stats

        def work():
            try:
                self._out = fn(*args)
            except BaseException as e:
                self._err = e

        self._thread = threading.Thread(target=work, name=name, daemon=True)
        self._thread.start()

    def result(self):
        if self._thread.is_alive() and self._stats is not None:
            with self._stats.waiting():
                self._thread.join()
        else:
            self._thread.join()
        if self._err is not None:
            raise self._err
        return self._out
