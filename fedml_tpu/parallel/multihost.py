"""Multi-host (DCN) runtime — bootstrap, host channel, and the
two-level round loop (ISSUE 13).

The reference scales across machines with `mpirun -np N -hostfile ...`
(run_fedavg_distributed_pytorch.sh:16-35) — one OS process per client rank
over MPI.  TPU-native, multi-host is one SPMD program: every host runs the
same code, `jax.distributed.initialize` wires the hosts into a single
runtime, and `jax.devices()` becomes the global chip list.  The engines in
parallel/ are already global-view (shard_map over a Mesh, device_put with
NamedShardings), so they run unchanged on a multi-host mesh — XLA routes
in-slice collectives over ICI and cross-slice traffic over DCN.

ISSUE 13 adds the runnable-today runtime on top of that seam, following
the MLPerf pod recipe (arXiv:1909.09756 — per-host input pipelines,
hierarchical gradient reduction) mapped onto FedML's hierarchical
aggregation (arXiv:2007.13518):

* `MultihostContext` / `spawn_cluster` / `tools/launch_multihost.py` —
  a multi-process launcher: N OS processes wired by env
  (`FEDML_MH_RANK/WORLD/COORD`), optionally joined into one jax runtime
  via `init_multihost` (`FEDML_MH_JAX_COORD`; on TPU pods this is what
  makes the local chips visible).
* `HostChannel` — the DCN tier executed for real: a tiny TCP
  coordinator (rank 0) carrying the P-sized flat f32 carry between
  hosts.  On the CPU dev box this stands in for gloo/DCN; it needs NO
  backend collective support, which is what makes the runtime runnable
  on jaxlib builds whose CPU backend lacks cross-process computations
  (the 0.4.x line — see tests/test_multihost_spmd.py's version gate on
  the in-program gloo path).  Every wait is BOUNDED: a dead or hung
  rank raises `DeadRankError` NAMING the rank instead of hanging the
  cluster.
* `MultihostRunner` — the two-level round loop: intra-host psum over
  the flat f32 carry on the LOCAL mesh (the engine's new
  `{family}_twolevel` partial program, ICI tier), then an inter-host
  allreduce of the P-sized per-block partials over the HostChannel
  (DCN tier), then a replicated commit (`twolevel_commit` program) on
  every host.

Bitwise anchor (the pin that anchors this subsystem, like the reactor
and async ones): the reduction tree is a function of the BLOCK
PARTITION, not the process count.  The cohort is sampled per block
from fixed population ranges (`BlockCohortSampler`, rng streams keyed
[seed, round, block]), each block's partial is one compiled program on
a same-shaped local mesh, and every host folds ALL block partials in
global block order.  Any process count that tiles the same blocks
therefore commits bitwise-identically — `n_blocks=2` at 1 process and
at 2 processes produce the same bits (tests/test_multihost_spmd.py).
This is STRONGER than an in-program psum can promise (a topology
change reorders XLA's reduction ring).

Mesh layout guidance (the scaling-book recipe): put the axis with the
heaviest collective traffic (the client/cohort axis — its psum moves the
whole model) INSIDE a slice so it rides ICI; put the hierarchical silo
axis across slices so only the second-tier reduction crosses DCN —
`make_hierarchical_host_mesh` encodes exactly that on top of
mesh.make_mesh_2d.

IMPORTANT: init_multihost() must run before ANY jax call that initializes
the XLA backend (so: first thing in main) — jax.distributed.initialize
refuses to run afterwards.

Streaming/prefetch note (parallel/prefetch.py): the streaming and
block-stream paths' background upload thread is PER PROCESS, and every
process runs the same round loop, so the prefetchers issue their
`jax.device_put(..., NamedSharding)` calls in the same order on every
host — each process materializes only its addressable shards, and the
upload/compute overlap composes across hosts (each host hides its own
gather+DMA behind its chips' compute).  The block-streamed
order-statistic defenses remain single-process (enforced at engine
construction): their host [K, P] offload needs every client shard
addressable.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pickle
import select
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from fedml_tpu import obs
from fedml_tpu.obs import cluster as _cluster
from fedml_tpu.parallel.mesh import CLIENT_AXIS, make_mesh, make_mesh_2d

log = logging.getLogger(__name__)

ENV_RANK = "FEDML_MH_RANK"
ENV_WORLD = "FEDML_MH_WORLD"
ENV_COORD = "FEDML_MH_COORD"           # host:port of the HostChannel
ENV_JAX_COORD = "FEDML_MH_JAX_COORD"   # host:port for jax.distributed


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   required: bool = False) -> None:
    """Join this host into the global runtime (idempotent).

    With no arguments, relies on the cluster's auto-detection (TPU pods
    expose the coordinator via metadata) and degrades gracefully to
    single-process mode on a dev box.  With EXPLICIT arguments — or
    required=True (the CLI's --multihost sets it) — a failure raises:
    silently training independent single-host replicas would corrupt the
    run.  Replaces the reference's mpirun/hostfile bootstrap."""
    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:              # older jax: no is_initialized
        pass
    explicit = (required or coordinator_address is not None
                or num_processes is not None or process_id is not None)
    try:
        # CPU cross-process collectives need a transport; without one the
        # global mesh forms but the first psum fails.  Current jaxlib
        # defaults the option to "gloo" (test_multihost_spmd runs over
        # it); this fallback covers builds whose default is unset/"none".
        # It must happen BEFORE initialize, and without probing the
        # platform — that would initialize the backend, which
        # jax.distributed.initialize forbids (see module docstring) — so
        # the option is set whenever it is not already configured (it
        # only affects the cpu backend; TPU pods use ICI/DCN natively).
        # getattr's default covers the older-jaxlib option-absent case
        # (cur = "absent" skips the update); a FAILING update on a jaxlib
        # that HAS the option is a real configuration error and must not
        # be swallowed — deferring it to the first cross-process psum
        # yields a much worse message
        cur = getattr(jax.config,
                      "jax_cpu_collectives_implementation", "absent")
        if cur in (None, "", "none"):
            # unset/disabled only (this jaxlib's default is already
            # "gloo"): an operator's explicit transport choice (env
            # JAX_CPU_COLLECTIVES_IMPLEMENTATION=mpi or a prior
            # config.update) must win
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        log.info("multihost: process %d/%d, %d global devices",
                 jax.process_index(), jax.process_count(),
                 len(jax.devices()))
    except Exception as e:
        if explicit:
            raise RuntimeError(
                f"multi-host initialization failed for coordinator "
                f"{coordinator_address!r}: {e}") from e
        log.info("multihost init skipped (%s); single-process mode", e)


def make_global_mesh(axis_name: str = CLIENT_AXIS) -> Mesh:
    """1-D mesh over ALL chips of ALL hosts — the cohort axis spans the
    pod; psum rides ICI within a slice and DCN across."""
    return make_mesh(axis_name=axis_name)


def make_local_mesh(axis_name: str = CLIENT_AXIS) -> Mesh:
    """1-D mesh over THIS process's chips only — the intra-host tier of
    the two-level aggregation (MultihostRunner requires a local-only
    mesh: its cross-host traffic is the HostChannel carry exchange, not
    in-program collectives)."""
    return make_mesh(axis_name=axis_name, devices=jax.local_devices())


def make_hierarchical_host_mesh(silos: Optional[int] = None) -> Mesh:
    """2-D (silo × clients) mesh with one silo per host by default: the
    inner FedAvg psum stays on each host's ICI, only the per-silo means
    cross DCN — the two-tier reduction of hierarchical FL mapped onto the
    physical network (SURVEY.md §2.5 'hierarchical aggregation').

    VIRTUAL-SILO semantics (single process, silos>1): with only one
    process there is no host boundary to place the silo tier on — the
    requested silo rows are carved out of THIS host's devices, so the
    "DCN tier" is simulated on local links.  That is the intended
    dev/test topology (the virtual-CPU oracles in
    tests/multihost_case.py rely on it), but it measures NOTHING about
    cross-host cost — a loud warning says so, because on a real pod the
    same call with one process per host is the genuine two-tier layout
    and silently accepting the single-process shape has masked
    misconfigured launches (ISSUE 13 satellite)."""
    devs = jax.devices()
    procs = max(jax.process_count(), 1)
    silos = silos or procs
    if len(devs) % silos != 0:
        raise ValueError(f"{len(devs)} devices not divisible into "
                         f"{silos} silos")
    if procs == 1 and silos > 1:
        log.warning(
            "make_hierarchical_host_mesh: building %d VIRTUAL silos on a "
            "single process — every silo row shares this host's devices, "
            "so the cross-silo tier rides local links, not DCN.  This is "
            "the dev/test topology (virtual-CPU oracles); on a pod, "
            "launch one process per host so the silo tier really crosses "
            "hosts.", silos)
    # global device order is NOT guaranteed host-contiguous; sort by
    # process so each silo row really sits on one host's ICI
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    return make_mesh_2d(n_silos=silos, devices=devs)


# ---------------------------------------------------------------------------
# process context + cluster spawning
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MultihostContext:
    """One process's place in the launched cluster (env-carried so any
    entry point — cli, bench worker, test worker — resolves the same
    way)."""
    rank: int
    world: int
    coordinator: str                    # "host:port" of the HostChannel
    jax_coordinator: Optional[str] = None   # jax.distributed, when wired

    @classmethod
    def from_env(cls) -> Optional["MultihostContext"]:
        if ENV_RANK not in os.environ or ENV_WORLD not in os.environ:
            return None
        world = int(os.environ[ENV_WORLD])
        rank = int(os.environ[ENV_RANK])
        if not 0 <= rank < world:
            raise ValueError(f"{ENV_RANK}={rank} outside world "
                             f"{world}")
        return cls(rank=rank, world=world,
                   coordinator=os.environ.get(ENV_COORD,
                                              "localhost:0"),
                   jax_coordinator=os.environ.get(ENV_JAX_COORD))

    @classmethod
    def single(cls) -> "MultihostContext":
        return cls(rank=0, world=1, coordinator="localhost:0")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


class MultihostLaunchError(RuntimeError):
    """A launched rank failed/hung; the message names it."""


def _rank_outcome(rc: Optional[int], policy_killed: bool = False) -> str:
    """One rank's exit, human-named: clean/nonzero exit codes and the
    SIGNAL name for signal deaths — SIGKILL (the chaos injection / OOM
    shape) reads differently from SIGSEGV (a real crash) and from a
    plain nonzero exit (a named Python error)."""
    if rc is None:
        return "still running"
    if rc == 0:
        return "ok"
    if rc < 0:
        try:
            name = signal.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        suffix = (" by launcher cleanup after the first failure"
                  if policy_killed else "")
        return f"killed by {name}{suffix}"
    return f"exit rc={rc}"


def spawn_cluster(cmd: list[str], procs: int, *,
                  env: Optional[dict] = None,
                  timeout_s: float = 600.0,
                  jax_distributed: bool = False,
                  echo: bool = False,
                  coordinator_host: str = "localhost",
                  elastic: bool = False,
                  respawn: bool = False,
                  kill_grace_s: float = 5.0) -> list[str]:
    """Fork `procs` copies of `cmd` wired as one multihost cluster (env
    FEDML_MH_RANK/WORLD/COORD [+ FEDML_MH_JAX_COORD with
    jax_distributed]); returns each rank's stdout, rank-ordered.

    Failure policy (fail-fast, the default): the first rank to exit
    nonzero kills the rest and raises MultihostLaunchError NAMING that
    rank (with its stderr tail) plus a per-rank outcome summary — exit
    code or signal name for EVERY rank, so a chaos-killed rank
    (SIGKILL) is distinguishable from the collateral channel-EOF deaths
    it causes.  A deadline overrun kills everything and names the ranks
    still running.

    Elastic policy (`elastic=True`, ISSUE 14): a dead rank does NOT
    take the survivors down — the cluster runs to completion and only a
    rank-0 (coordinator) failure or the deadline raises.  With
    `respawn=True` a dead nonzero rank > 0 is relaunched ONCE with
    FEDML_MH_REJOIN=1 in its env, so the worker re-enters the cluster
    through the elastic rejoin handshake (ElasticChannel) — the
    process-level chaos/recovery loop, launcher-driven.

    `echo` streams child stderr line-prefixed (`[rank i]`)."""
    outs, _report = spawn_cluster_report(
        cmd, procs, env=env, timeout_s=timeout_s,
        jax_distributed=jax_distributed, echo=echo,
        coordinator_host=coordinator_host, elastic=elastic,
        respawn=respawn, kill_grace_s=kill_grace_s)
    return outs


def spawn_cluster_report(cmd: list[str], procs: int, *,
                         env: Optional[dict] = None,
                         timeout_s: float = 600.0,
                         jax_distributed: bool = False,
                         echo: bool = False,
                         coordinator_host: str = "localhost",
                         elastic: bool = False,
                         respawn: bool = False,
                         kill_grace_s: float = 5.0
                         ) -> tuple[list[str], dict]:
    """spawn_cluster plus a per-rank outcome report: ({rank stdouts},
    {"ranks": {r: {"rc", "outcome", "respawned", "incarnations"}},
    "first_failed": r|None}) — the bench's chaos arm reads survivor
    deaths and the respawn count from here instead of re-parsing
    stderr."""
    if procs < 1:
        raise ValueError(f"procs must be >= 1, got {procs}")
    if not cmd:
        raise ValueError("empty worker command")
    if respawn and not elastic:
        raise ValueError("respawn=True needs elastic=True (a fail-fast "
                         "cluster kills the survivors the rejoiner "
                         "would rejoin)")
    coord = f"{coordinator_host}:{free_port()}"
    base_env = {**os.environ, **(env or {}),
                ENV_WORLD: str(procs), ENV_COORD: coord}
    base_env.pop("FEDML_MH_REJOIN", None)
    if jax_distributed:
        base_env[ENV_JAX_COORD] = f"{coordinator_host}:{free_port()}"

    # per-rank incarnation tables (respawn appends a second incarnation)
    incarnations: list[list[subprocess.Popen]] = [[] for _ in range(procs)]
    bufs: dict[tuple[int, int], tuple[list, list]] = {}
    drains: list[threading.Thread] = []
    policy_killed: set[int] = set()

    def _drain(rank: int, gen: int, p: subprocess.Popen):
        buf_out: list = []
        buf_err: list = []
        bufs[(rank, gen)] = (buf_out, buf_err)

        def _pump(stream, buf, is_err):
            for line in stream:
                buf.append(line)
                if echo and is_err:
                    # stderr streams live (progress/tracebacks); stdout
                    # is returned buffered so machine-readable lines
                    # stay contiguous per rank
                    print(f"[rank {rank}] {line}", end="",
                          file=sys.stderr, flush=True)
        t_err = threading.Thread(target=_pump,
                                 args=(p.stderr, buf_err, True))
        t_err.start()
        _pump(p.stdout, buf_out, False)
        t_err.join()

    def _launch(rank: int, rejoin: bool = False):
        e = dict(base_env)
        e[ENV_RANK] = str(rank)
        if rejoin:
            e["FEDML_MH_REJOIN"] = "1"
        p = subprocess.Popen(cmd, env=e, text=True,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE)
        gen = len(incarnations[rank])
        incarnations[rank].append(p)
        t = threading.Thread(target=_drain, args=(rank, gen, p))
        t.start()
        drains.append(t)
        return p

    for r in range(procs):
        _launch(r)

    def _cur(rank: int) -> subprocess.Popen:
        return incarnations[rank][-1]

    def _summary() -> str:
        rows = []
        for r in range(procs):
            tags = [_rank_outcome(p.poll(), r in policy_killed)
                    for p in incarnations[r]]
            rows.append(f"rank {r}: " + " -> respawned: ".join(tags))
        return "; ".join(rows)

    def _err_tail(rank: int) -> str:
        chunks = [("".join(bufs.get((rank, g), ([], []))[1]))
                  for g in range(len(incarnations[rank]))]
        return "".join(chunks)[-3000:]

    deadline = time.monotonic() + timeout_s
    first_failed: Optional[int] = None
    handled_deaths: set[tuple[int, int]] = set()
    respawned: set[int] = set()
    try:
        while True:
            live = [r for r in range(procs) if _cur(r).poll() is None]
            for r in range(procs):
                for g, p in enumerate(incarnations[r]):
                    if (p.poll() is not None and p.returncode != 0
                            and (r, g) not in handled_deaths):
                        handled_deaths.add((r, g))
                        if first_failed is None:
                            first_failed = r
                        if (elastic and respawn and r != 0
                                and r not in respawned):
                            respawned.add(r)
                            log.warning(
                                "elastic launch: rank %d died (%s); "
                                "respawning once with FEDML_MH_REJOIN=1",
                                r, _rank_outcome(p.returncode))
                            _launch(r, rejoin=True)
            if elastic:
                # survivors outlive a dead peer; only the coordinator's
                # death (or the deadline) is cluster-fatal
                if (_cur(0).poll() is not None
                        and _cur(0).returncode != 0):
                    break
                if all(_cur(r).poll() is not None
                       for r in range(procs)):
                    break
            else:
                failed = [r for r in range(procs)
                          if _cur(r).poll() is not None
                          and _cur(r).returncode != 0]
                if failed or not live:
                    break
            if time.monotonic() > deadline:
                for r in live:
                    policy_killed.add(r)
                    _cur(r).kill()
                for r in live:   # reap: the summary must show the
                    try:         # kill outcome, not "still running"
                        _cur(r).wait(timeout=10)
                    except Exception:
                        pass
                raise MultihostLaunchError(
                    f"multihost launch timed out after {timeout_s:.0f}s: "
                    f"rank(s) {live} still running (of {procs})\n"
                    f"per-rank: {_summary()}")
            time.sleep(0.05)
        if any(p.returncode not in (0, None)
               for ps in incarnations for p in ps):
            # give survivors a short grace (a dead peer's channel EOF
            # usually fails them promptly with their OWN named error;
            # elastic survivors already ran to completion), then kill
            grace = time.monotonic() + kill_grace_s
            while (time.monotonic() < grace
                   and any(_cur(r).poll() is None
                           for r in range(procs))):
                time.sleep(0.05)
            killed_now = []
            for r in range(procs):
                if _cur(r).poll() is None:
                    policy_killed.add(r)
                    _cur(r).kill()
                    killed_now.append(r)
            for r in killed_now:
                # reap before the report/summary reads returncode —
                # an unreaped kill would show rc=None "still running"
                try:
                    _cur(r).wait(timeout=10)
                except Exception:
                    pass
    finally:
        for t in drains:
            t.join()
    report = {
        "first_failed": first_failed,
        "ranks": {
            r: {"rc": _cur(r).returncode,
                "outcome": _rank_outcome(_cur(r).returncode,
                                         r in policy_killed),
                "respawned": r in respawned,
                "incarnations": len(incarnations[r]),
                "all_rcs": [p.returncode for p in incarnations[r]]}
            for r in range(procs)},
    }
    bad = [r for r in range(procs) if _cur(r).returncode != 0]
    fatal = bad and (not elastic or 0 in bad)
    if fatal:
        # blame the FIRST rank observed failing (the injected/original
        # fault), not a survivor that died of the resulting channel
        # EOF; the per-rank summary names EVERY rank's exit/signal
        i = first_failed if first_failed in bad else bad[0]
        raise MultihostLaunchError(
            f"multihost rank {i}/{procs} failed first "
            f"(rc={_cur(i).returncode}; {len(bad)}/{procs} ranks "
            f"failed):\nper-rank: {_summary()}\n{_err_tail(i)}")
    outs = ["".join("".join(bufs.get((r, g), ([], []))[0])
                    for g in range(len(incarnations[r])))
            for r in range(procs)]
    return outs, report


# ---------------------------------------------------------------------------
# HostChannel — the DCN tier, executed for real
# ---------------------------------------------------------------------------

class DeadRankError(RuntimeError):
    """A peer rank died or stalled past the bounded channel timeout; the
    message names it (the crash-of-one-process acceptance case)."""


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


def _dial_with_backoff(host: str, port: int, deadline: float, what: str,
                       *, initial_s: float = 0.05,
                       cap_s: float = 1.0) -> socket.socket:
    """Deadline-bounded TCP dial with exponential backoff — THE connect
    path for every transient dial in this module (worker->coordinator
    data/heartbeat/rejoin links).  A coordinator mid-accept-setup, or
    restarting in elastic mode, refuses connects transiently; retrying
    with growing sleeps (initial_s doubling to cap_s) inside the
    caller's deadline turns that window into latency instead of a
    launch failure.  Final failure raises DeadRankError NAMING `what`
    and the last OS error."""
    delay = initial_s
    last: Optional[Exception] = None
    while True:
        budget = deadline - time.monotonic()
        if budget <= 0:
            raise DeadRankError(
                f"{what}: could not connect to {host}:{port} before its "
                f"deadline (last error: "
                f"{type(last).__name__ if last is not None else 'none'}:"
                f" {last})") from last
        try:
            return socket.create_connection(
                (host, port), timeout=min(5.0, max(0.1, budget)))
        except OSError as e:
            last = e
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2.0, cap_s)


def _export_channel_byte_counters(rank: int, bytes_sent: int,
                                  bytes_received: int) -> None:
    """Publish a channel's cumulative byte counters as obs metrics
    (called at round boundaries — the counters themselves stay cheap
    plain ints on the hot path).  Shared by HostChannel and
    ElasticChannel so the delta-inc accounting can never diverge."""
    r = str(rank)
    sent = obs.counter("multihost_bytes_sent_total", rank=r)
    recv = obs.counter("multihost_bytes_received_total", rank=r)
    sent.inc(max(0.0, bytes_sent - sent.value))
    recv.inc(max(0.0, bytes_received - recv.value))


def _account_carry(raw: int, wire: int) -> None:
    """Carry-codec compression accounting (ISSUE 16), mirroring the
    comm layer's MessageCodec._account: raw = the f32 bytes of the
    carry partials this rank encoded, wire = the encoded payload it
    shipped; the gauge is the cumulative raw/wire quotient."""
    c_raw = obs.counter("multihost_carry_raw_bytes_total")
    c_wire = obs.counter("multihost_carry_compressed_bytes_total")
    c_raw.inc(raw)
    c_wire.inc(wire)
    if c_wire.value > 0:
        obs.gauge("multihost_carry_compression_ratio").set(
            c_raw.value / c_wire.value)


class _GatherHandle:
    """In-flight state of ONE pipelined carry gather (ISSUE 16): rank 0
    carries the background frame collector, workers the chained
    frame-push tail; gather_finish() consumes it.  One handle per
    collective — never reused."""

    __slots__ = ("n_frames", "deadline", "seq", "own", "pending",
                 "collector", "pushed", "aborted")

    def __init__(self, n_frames: int, deadline: float, seq: int):
        self.n_frames = int(n_frames)
        self.deadline = float(deadline)
        self.seq = int(seq)
        self.own: list[bytes] = []
        self.pending = None
        self.collector = None
        self.pushed = 0
        self.aborted = False


class _ContribHandle:
    """In-flight early contributions of one elastic exchange (ISSUE
    16): workers chain per-block contrib sends (the coordinator's
    multi-contrib protocol already accepts them), rank 0 stashes its
    own blocks locally; ElasticChannel.exchange(pending=...) drains the
    handle.  Stale handles are harmless — the coordinator drops
    contribs whose round header does not match the round in flight."""

    __slots__ = ("round_idx", "blocks", "stash", "pending")

    def __init__(self, round_idx: int):
        self.round_idx = int(round_idx)
        self.blocks: list[int] = []
        self.stash: dict[int, bytes] = {}
        self.pending = None


class HostChannel:
    """Small-payload allgather/barrier between the cluster's processes —
    the inter-host (DCN) tier of the two-level aggregation, carrying the
    P-sized flat f32 carry partials.

    Star topology: rank 0 coordinates (gathers every rank's payload,
    broadcasts the rank-ordered list).  Deliberately NOT a ring: the
    payloads are O(P) model-carry vectors, tiny next to the cohort data
    that never crosses processes, and a star gives every failure a
    single observer that can NAME the dead rank.  All waits are bounded
    (`timeout_s`): a dead peer raises DeadRankError naming it instead
    of hanging the round loop (the PR-8 crash lesson, applied to the
    cluster tier).  Byte/time accounting lands in
    multihost_bytes_sent/received_total and multihost_allgather_seconds
    (the bench's carry-allreduce bytes read)."""

    def __init__(self, ctx: MultihostContext, *,
                 timeout_s: float = 120.0,
                 connect_timeout_s: float = 60.0):
        self.ctx = ctx
        self.timeout_s = float(timeout_s)
        self.bytes_sent = 0
        self.bytes_received = 0
        self._mark = (0, 0)
        self._seq = 0
        # the runner stamps the round in flight here so the barrier
        # ledger (ISSUE 17) can attribute gather waits to a round
        self.round_hint: Optional[int] = None
        self._peers: dict[int, socket.socket] = {}
        self._sock: Optional[socket.socket] = None
        self._listener: Optional[socket.socket] = None
        _cluster.set_role(ctx.rank, ctx.world)
        if ctx.world <= 1:
            return
        host, port = ctx.coordinator.rsplit(":", 1)
        port = int(port)
        if ctx.rank == 0:
            self._listener = socket.create_server((host, port))
            self._listener.settimeout(connect_timeout_s)
            deadline = time.monotonic() + connect_timeout_s

            def _setup_dead(reason: str):
                missing = sorted(set(range(1, ctx.world))
                                 - set(self._peers))
                for s in self._peers.values():
                    s.close()
                self._listener.close()
                raise DeadRankError(
                    f"multihost channel setup: rank(s) {missing} "
                    f"{reason} within {connect_timeout_s:.0f}s")

            while len(self._peers) < ctx.world - 1:
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    conn = None
                if conn is None or time.monotonic() > deadline:
                    _setup_dead("never connected")
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # accepted sockets are BLOCKING regardless of the
                # listener's timeout — bound the rank handshake too, or
                # a connected-but-stalled peer hangs setup unboundedly
                conn.settimeout(max(0.001, deadline - time.monotonic()))
                try:
                    (r,) = struct.unpack("<I", _recv_exact(conn, 4))
                except (socket.timeout, ConnectionError, OSError):
                    conn.close()
                    _setup_dead("connected but never sent a rank "
                                "handshake")
                self._peers[r] = conn
        else:
            # deadline-bounded exponential-backoff dial: the accept
            # window on rank 0 opens asynchronously with this process's
            # start, so first-connect refusals are expected, not fatal
            self._sock = _dial_with_backoff(
                host, port, time.monotonic() + connect_timeout_s,
                f"multihost channel setup: rank {ctx.rank} dialing the "
                f"rank-0 coordinator at {ctx.coordinator}")
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._sock.sendall(struct.pack("<I", ctx.rank))

    # -- collective ops ------------------------------------------------------
    def allgather(self, payload: bytes,
                  timeout_s: Optional[float] = None) -> list[bytes]:
        """Every rank contributes `payload`; every rank receives the
        rank-ordered list.  Bounded: a silent rank raises DeadRankError
        naming it."""
        t0 = time.perf_counter()
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        self._seq += 1
        ctx = self.ctx
        if ctx.world <= 1:
            return [payload]
        deadline = time.monotonic() + timeout
        try:
            if ctx.rank == 0:
                # barrier ledger (ISSUE 17): rank 0 is the star's single
                # observer — its own arrival is the loop open, each
                # peer's is its frame landing.  Piggybacked metric
                # sidecars are stripped BEFORE the broadcast, so every
                # rank folds the identical payload bytes.
                arrivals = {0: time.monotonic()}
                parts: list[Optional[bytes]] = [None] * ctx.world
                parts[0] = payload
                for r in sorted(self._peers):
                    sock = self._peers[r]
                    sock.settimeout(max(0.001,
                                        deadline - time.monotonic()))
                    try:
                        parts[r] = _recv_frame(sock)
                    except (socket.timeout, ConnectionError, OSError) as e:
                        missing = sorted(r2 for r2 in range(1, ctx.world)
                                         if parts[r2] is None)
                        raise DeadRankError(
                            f"multihost allgather #{self._seq}: no "
                            f"payload from rank(s) {missing} within "
                            f"{timeout:.0f}s ({type(e).__name__}: "
                            f"process dead or hung)") from e
                    arrivals[r] = time.monotonic()
                    self.bytes_received += len(parts[r])
                    parts[r], side = _cluster.split_sidecar(parts[r])
                    if side is not None:
                        _cluster.fold_remote(r, side)
                _cluster.note_barrier("allgather", self._seq,
                                      self.round_hint, arrivals)
                blob = struct.pack("<I", ctx.world) + b"".join(
                    struct.pack("<Q", len(p)) + p for p in parts)
                for r in sorted(self._peers):
                    try:
                        _send_frame(self._peers[r], blob)
                    except (socket.timeout, ConnectionError, OSError) as e:
                        raise DeadRankError(
                            f"multihost allgather #{self._seq}: "
                            f"broadcast to rank {r} failed "
                            f"({type(e).__name__}: rank died after "
                            f"contributing)") from e
                    self.bytes_sent += len(blob) + 8
                return list(parts)          # type: ignore[arg-type]
            # non-root: ship ours, await the broadcast.  Reset the
            # send-side timeout first — settimeout() PERSISTS on the
            # socket, so without this the send runs under whatever
            # near-expired recv deadline the previous allgather left
            self._sock.settimeout(max(0.001,
                                      deadline - time.monotonic()))
            # live telemetry plane (ISSUE 17): ship a bounded metrics
            # delta as a self-describing payload trailer — rank 0
            # strips it before the broadcast.  Attached ONLY when an
            # obs dir is configured: the obs-off wire stays
            # byte-identical.
            out = payload
            if _cluster.telemetry_enabled():
                out = _cluster.attach_sidecar(payload, _piggyback_delta())
            try:
                _send_frame(self._sock, out)
            except (socket.timeout, ConnectionError, OSError) as e:
                raise DeadRankError(
                    f"multihost allgather #{self._seq}: rank {ctx.rank} "
                    f"could not ship its payload to the rank-0 "
                    f"coordinator ({type(e).__name__}: coordinator dead "
                    f"or backpressured past {timeout:.0f}s)") from e
            self.bytes_sent += len(out) + 8
            self._sock.settimeout(max(0.001, deadline - time.monotonic()))
            try:
                blob = _recv_frame(self._sock)
            except (socket.timeout, ConnectionError, OSError) as e:
                raise DeadRankError(
                    f"multihost allgather #{self._seq}: rank {ctx.rank} "
                    f"got no broadcast from the rank-0 coordinator "
                    f"within {timeout:.0f}s ({type(e).__name__}: "
                    f"coordinator dead, or a peer stalled it)") from e
            self.bytes_received += len(blob)
            (world,) = struct.unpack_from("<I", blob, 0)
            off, parts = 4, []
            for _ in range(world):
                (n,) = struct.unpack_from("<Q", blob, off)
                off += 8
                parts.append(blob[off:off + n])
                off += n
            return parts
        finally:
            obs.histogram("multihost_allgather_seconds").observe(
                time.perf_counter() - t0)

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        self.allgather(b"", timeout_s=timeout_s)

    # -- per-round wire accounting -------------------------------------------
    def mark_round(self) -> None:
        """Open a per-round wire window (ISSUE 16 satellite): the
        compressed arm's bytes-per-round is what the CHANNEL moved
        between mark_round() and round_wire_delta(), not a host-side
        re-derivation of what it should have moved."""
        self._mark = (self.bytes_sent, self.bytes_received)

    def round_wire_delta(self) -> dict[str, int]:
        s0, r0 = self._mark
        return {"sent": self.bytes_sent - s0,
                "received": self.bytes_received - r0}

    # -- pipelined gather (compute/DCN overlap, ISSUE 16) --------------------
    def gather_begin(self, n_frames: int,
                     timeout_s: Optional[float] = None) -> _GatherHandle:
        """Open a pipelined allgather of `n_frames` frames per rank:
        each rank pushes frames as they materialize (gather_push) and
        the collective completes in gather_finish() — frame j's wire
        transfer overlaps frame j+1's block compute instead of
        serializing behind the whole payload.  Equivalent by
        construction to allgather(b"".join(frames)): the per-rank
        frames concatenate in push order (the deterministic owned-block
        order), and the broadcast blob is identical — which is why the
        f32 escape hatch stays bitwise under overlap."""
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        self._seq += 1
        h = _GatherHandle(n_frames, time.monotonic() + timeout, self._seq)
        if self.ctx.world > 1 and self.ctx.rank == 0 and h.n_frames:
            from fedml_tpu.parallel.prefetch import AsyncValue
            h.collector = AsyncValue(self._collect_frames, h,
                                     name=f"gather#{h.seq}")
        return h

    def _collect_frames(self, h: _GatherHandle):
        """Rank 0's background collector: drain every peer's frames in
        per-peer FIFO order while rank 0's own blocks compute.  Runs on
        the gather handle's AsyncValue thread; joined in
        gather_finish() (errors re-raise there).  Returns (frames,
        arrivals): a peer "arrives" at the barrier when its LAST frame
        lands — the ledger stamp the straggler attribution keys on."""
        remaining = {r: h.n_frames for r in self._peers}
        frames: dict[int, list[bytes]] = {r: [] for r in self._peers}
        arrivals: dict[int, float] = {}
        by_sock = {s: r for r, s in self._peers.items()}
        while any(remaining.values()) and not h.aborted:
            budget = h.deadline - time.monotonic()
            if budget <= 0:
                owing = sorted(r for r, n in remaining.items() if n)
                raise DeadRankError(
                    f"multihost gather #{h.seq}: rank(s) {owing} still "
                    f"owe carry frames at the deadline (process dead, "
                    f"hung, or its block compute overran the window)")
            socks = [self._peers[r] for r, n in remaining.items() if n]
            try:
                rl, _, _ = select.select(socks, [], [], min(0.2, budget))
            except (OSError, ValueError):
                rl = []          # a sock closed under us: deadline names it
            for s in rl:
                r = by_sock[s]
                s.settimeout(max(0.001, h.deadline - time.monotonic()))
                try:
                    f = _recv_frame(s)
                except (socket.timeout, ConnectionError, OSError) as e:
                    raise DeadRankError(
                        f"multihost gather #{h.seq}: rank {r} died "
                        f"mid-frame ({type(e).__name__})") from e
                self.bytes_received += len(f)
                frames[r].append(f)
                remaining[r] -= 1
                if remaining[r] == 0:
                    arrivals[r] = time.monotonic()
        return frames, arrivals

    def gather_push(self, h: _GatherHandle, frame: bytes) -> None:
        """Ship one frame into an open gather.  Rank 0 stashes locally
        (its frames never cross the wire); workers chain the send onto
        the previous push's AsyncValue so socket writes serialize while
        the caller returns to computing the next block."""
        h.pushed += 1
        if self.ctx.world <= 1 or self.ctx.rank == 0:
            h.own.append(bytes(frame))
            return
        from fedml_tpu.parallel.prefetch import AsyncValue

        prev = h.pending

        def _ship(prev=prev, frame=frame):
            if prev is not None:
                prev.result()
            self._sock.settimeout(max(0.001,
                                      h.deadline - time.monotonic()))
            try:
                _send_frame(self._sock, frame)
            except (socket.timeout, ConnectionError, OSError) as e:
                raise DeadRankError(
                    f"multihost gather #{h.seq}: rank {self.ctx.rank} "
                    f"could not ship a carry frame to the rank-0 "
                    f"coordinator ({type(e).__name__})") from e
            self.bytes_sent += len(frame) + 8

        h.pending = AsyncValue(_ship, name=f"gather_push#{h.seq}")

    def gather_finish(self, h: _GatherHandle) -> list[bytes]:
        """Complete the collective: returns the rank-ordered list of
        per-rank payloads (each rank's frames concatenated in push
        order) — the same shape allgather returns."""
        ctx = self.ctx
        if ctx.world <= 1:
            return [b"".join(h.own)]
        if h.pushed != h.n_frames:
            raise ValueError(
                f"multihost gather #{h.seq}: {h.pushed} frames pushed "
                f"but {h.n_frames} promised — the collective would "
                f"hang every peer")
        if ctx.rank == 0:
            # rank 0 "arrives" when its own frames are all pushed and
            # it enters the finish — the collector stamps each peer
            t_own = time.monotonic()
            parts: list[bytes] = [b""] * ctx.world
            parts[0] = b"".join(h.own)
            frames, arrivals = (h.collector.result()
                                if h.collector is not None
                                else ({r: [] for r in self._peers}, {}))
            arrivals[0] = t_own
            _cluster.note_barrier("gather", h.seq, self.round_hint,
                                  arrivals)
            for r, fl in frames.items():
                parts[r] = b"".join(fl)
            blob = struct.pack("<I", ctx.world) + b"".join(
                struct.pack("<Q", len(p)) + p for p in parts)
            for r in sorted(self._peers):
                try:
                    self._peers[r].settimeout(
                        max(0.001, h.deadline - time.monotonic()))
                    _send_frame(self._peers[r], blob)
                except (socket.timeout, ConnectionError, OSError) as e:
                    raise DeadRankError(
                        f"multihost gather #{h.seq}: broadcast to rank "
                        f"{r} failed ({type(e).__name__}: rank died "
                        f"after contributing)") from e
                self.bytes_sent += len(blob) + 8
            return parts
        if h.pending is not None:
            h.pending.result()           # drain the push tail first
        self._sock.settimeout(max(0.001, h.deadline - time.monotonic()))
        try:
            blob = _recv_frame(self._sock)
        except (socket.timeout, ConnectionError, OSError) as e:
            raise DeadRankError(
                f"multihost gather #{h.seq}: rank {ctx.rank} got no "
                f"broadcast from the rank-0 coordinator "
                f"({type(e).__name__}: coordinator dead, or a peer "
                f"stalled it)") from e
        self.bytes_received += len(blob)
        (world,) = struct.unpack_from("<I", blob, 0)
        off, parts = 4, []
        for _ in range(world):
            (n,) = struct.unpack_from("<Q", blob, off)
            off += 8
            parts.append(blob[off:off + n])
            off += n
        return parts

    def gather_abort(self, h: _GatherHandle) -> None:
        """Invalidate an in-flight gather on the error path: the
        collector exits at its next poll instead of camping on the
        deadline, and the push tail is drained best-effort."""
        h.aborted = True
        for av in (h.pending, h.collector):
            if av is not None:
                try:
                    av.result()
                except Exception:
                    pass

    def export_byte_counters(self) -> None:
        _export_channel_byte_counters(self.ctx.rank, self.bytes_sent,
                                      self.bytes_received)

    def close(self) -> None:
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
        self._peers.clear()
        for s in (self._sock, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = self._listener = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# topology-independent block sampling
# ---------------------------------------------------------------------------

class BlockCohortSampler:
    """Per-block cohort sampling over fixed population ranges — the
    sampling half of the bitwise anchor.

    The population [0, C) splits into `n_blocks` contiguous ranges (the
    PR-10 registry/shardstore id-range partition, applied to the
    cohort); block b draws `k_per_block` clients without replacement
    from ITS range on a private `default_rng([seed, round, block])`
    stream.  Every quantity is a pure function of (seed, round, block)
    — NOT of which process computes it — so any topology tiling the
    same blocks samples the same cohort (and the draw is
    background-thread-safe: no global-RNG reseed, the PR-10 lesson)."""

    def __init__(self, population: int, n_blocks: int, k_per_block: int,
                 seed: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if population % n_blocks:
            raise ValueError(
                f"population ({population}) must divide evenly into "
                f"{n_blocks} blocks (the id-range partition must be "
                f"topology-independent)")
        self.population = int(population)
        self.n_blocks = int(n_blocks)
        self.range_size = population // n_blocks
        if not 1 <= k_per_block <= self.range_size:
            raise ValueError(
                f"k_per_block ({k_per_block}) must be in [1, "
                f"{self.range_size}] (each block samples within its "
                f"{self.range_size}-client range)")
        self.k_per_block = int(k_per_block)
        self.seed = int(seed)

    def sample_block(self, round_idx: int, block: int) -> np.ndarray:
        """Global client ids of block `block`'s round-`round_idx`
        cohort, sorted ascending (a canonical order so every topology
        builds the identical cohort stack)."""
        if not 0 <= block < self.n_blocks:
            raise ValueError(f"block {block} outside [0, "
                             f"{self.n_blocks})")
        lo = block * self.range_size
        if self.k_per_block == self.range_size:
            return np.arange(lo, lo + self.range_size, dtype=np.int64)
        rng = np.random.default_rng(
            [self.seed, int(round_idx), int(block)])
        ids = rng.choice(self.range_size, size=self.k_per_block,
                         replace=False)
        return np.sort(ids).astype(np.int64) + lo


def fold_block_partials(parts: dict[int, np.ndarray],
                        n_blocks: int) -> np.ndarray:
    """THE deterministic inter-host reduction: left-fold the per-block
    f32 partials in GLOBAL BLOCK ORDER.  Identical on every host and
    for every topology that produced the same blocks — float addition
    is not associative, so the fold order is the contract (never
    tree-reduce here without changing the bitwise anchor)."""
    missing = [b for b in range(n_blocks) if b not in parts]
    if missing:
        raise DeadRankError(
            f"two-level fold: block partial(s) {missing} missing from "
            f"the allgather (owning rank dead mid-round?)")
    total = np.array(parts[0], dtype=np.float32, copy=True)
    for b in range(1, n_blocks):
        total += np.asarray(parts[b], dtype=np.float32)
    return total


def fold_sparse_partials(pairs: dict[int, tuple], n_blocks: int,
                         dim: int) -> np.ndarray:
    """Sparse twin of ``fold_block_partials`` (ISSUE 19): scatter-add
    each block's (idx, vals) pairs into the flat f32 carry IN GLOBAL
    BLOCK ORDER, never densifying a per-block vector.  Per element the
    additions arrive in exactly the block order the dense left-fold
    uses, so replica agreement holds for the same reason: every host
    folds identical wire bytes with identical ops."""
    missing = [b for b in range(n_blocks) if b not in pairs]
    if missing:
        raise DeadRankError(
            f"two-level fold: block partial(s) {missing} missing from "
            f"the allgather (owning rank dead mid-round?)")
    total = np.zeros(int(dim), dtype=np.float32)
    for b in range(n_blocks):
        idx, vals = pairs[b]
        # top-k indices are unique within a block, so fancy-index +=
        # is a well-defined scatter-add
        total[idx] += np.asarray(vals, dtype=np.float32)
    return total


# ---------------------------------------------------------------------------
# elastic membership (ISSUE 14) — epoch-numbered views, heartbeats,
# deterministic block re-adoption, rejoin
# ---------------------------------------------------------------------------

def _send_msg(sock: socket.socket, mtype: str, header: dict,
              payload: bytes = b"") -> int:
    """One elastic-protocol message: length-framed [u32 hdr-len][JSON
    header incl. "t" type][payload].  Returns bytes on the wire."""
    hdr = json.dumps({"t": mtype, **header}, sort_keys=True).encode()
    frame = struct.pack("<I", len(hdr)) + hdr + payload
    _send_frame(sock, frame)
    return len(frame) + 8


def _recv_msg(sock: socket.socket) -> tuple[str, dict, bytes, int]:
    frame = _recv_frame(sock)
    (n,) = struct.unpack_from("<I", frame, 0)
    hdr = json.loads(frame[4:4 + n].decode())
    return hdr.pop("t"), hdr, frame[4 + n:], len(frame) + 8


@dataclasses.dataclass(frozen=True)
class ClusterView:
    """One epoch of elastic membership: the sorted live ranks and THE
    deterministic item→owner map.  `n_items` is the fixed block space
    (the reduction tree's shape — NEVER repartitioned); only ownership
    moves.  owner_of is a pure function of (members, n_items), so every
    rank that knows the member list derives the identical partition —
    no assignment table crosses the wire beyond the member list.  With
    the full initial membership it reduces to the PR-13 contiguous
    tiling (rank r owns blocks [r·B/W, (r+1)·B/W))."""
    epoch: int
    members: tuple
    n_items: int

    def owner_of(self, item: int) -> int:
        if not 0 <= item < self.n_items:
            raise ValueError(f"item {item} outside [0, {self.n_items})")
        return self.members[item * len(self.members) // self.n_items]

    def assigned(self, rank: int) -> tuple:
        return tuple(i for i in range(self.n_items)
                     if self.owner_of(i) == rank)


class ElasticChannel:
    """Epoch-numbered elastic cluster membership over the HostChannel's
    star topology (ISSUE 14).  Rank 0 coordinates: it owns the member
    list, detects death (data-link EOF, bounded waits, AND heartbeats —
    a SIGSTOP'd rank stops heartbeating and is suspected within
    `hb_timeout_s`, between allgathers, not only inside one), drives
    view changes, and admits rejoiners at commit barriers.

    The collective is `exchange(round, parts, compute)`: a block-keyed
    allgather.  Every item (block) is a pure function of (seed, round,
    block) — NOT of who computes it — so when a rank dies mid-round the
    coordinator re-asks the survivors for exactly the missing items
    (`need` lists in VIEW messages, ownership from ClusterView.owner_of
    over the shrunk membership) and the round completes with the SAME
    folded bytes as a clean run: bitwise survival by construction.

    Wire roles (every connection's first frame is a typed hello):
    "data" (CONTRIB/VIEW/RESULT), "hb" (periodic heartbeats), "rejoin"
    (config-digest-checked admission: REJECTed by name on mismatch,
    SNAPSHOT {epoch, resume_round, members} + model blob at the next
    commit barrier otherwise).  Rank-0 death stays fatal by design —
    the coordinator is the single failure observer, exactly the
    HostChannel contract; workers name it in DeadRankError.

    Fail-fast (`HostChannel`) remains the default transport; this class
    is opt-in via `--elastic` / MultihostRunner's elastic twin."""

    def __init__(self, ctx: MultihostContext, *, n_items: int,
                 config_digest: str = "",
                 timeout_s: float = 120.0,
                 connect_timeout_s: float = 60.0,
                 hb_interval_s: float = 0.25,
                 hb_timeout_s: float = 2.0,
                 rejoin: bool = False):
        if n_items < 1:
            raise ValueError(f"n_items must be >= 1, got {n_items}")
        self.ctx = ctx
        self.n_items = int(n_items)
        self.config_digest = str(config_digest)
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.bytes_sent = 0
        self.bytes_received = 0
        self._mark = (0, 0)
        self.view = ClusterView(0, tuple(range(ctx.world)), self.n_items)
        self.view_events: list[dict] = []
        self.hb_paused = False          # fault-injection hook: a paused
        #                                 sender emulates a hung (SIGSTOP)
        #                                 rank without stopping the process
        self._item_nbytes: Optional[int] = None
        self._lock = threading.Lock()
        # byte counters are bumped from the exchange thread AND the
        # accept/heartbeat handler threads — a bare += would lose
        # updates; a dedicated lock (never held across I/O waits)
        # keeps the accounting exact without deadlock exposure
        self._io_lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._sock: Optional[socket.socket] = None        # worker data
        self._hb_sock: Optional[socket.socket] = None     # worker hb
        self._listener: Optional[socket.socket] = None
        self._data: dict[int, socket.socket] = {}         # coord tables
        self._hb: dict[int, socket.socket] = {}
        self._hb_last: dict[int, float] = {}
        self._suspect: dict[int, str] = {}
        self._pending_rejoin: list[tuple[int, socket.socket]] = []
        host, port = ctx.coordinator.rsplit(":", 1)
        self._host, self._port = host, int(port)
        _cluster.set_role(ctx.rank, ctx.world, elastic=True)
        if ctx.world <= 1:
            return
        if ctx.rank == 0:
            # coordinated incident dumps (ISSUE 17): the observatory's
            # throttled chokepoint fans out through this channel's
            # typed DUMP frames (telemetry-gated — obs-off wire clean)
            _cluster.set_dump_broadcaster(self._broadcast_dump_frames)
            grace = time.monotonic() + self.connect_timeout_s
            for m in self.view.members:
                if m != 0:
                    self._hb_last[m] = grace   # future-dated connect grace
            self._listener = socket.create_server((host, self._port))
            self._listener.settimeout(0.25)
            threading.Thread(target=self._accept_loop, daemon=True,
                             name="elastic-accept").start()
        elif not rejoin:
            self._connect_worker()
        # rejoin=True defers ALL dialing to rejoin_handshake()

    # -- byte-counted message wrappers ---------------------------------------
    def _send(self, sock, mtype, header, payload=b"") -> None:
        n = _send_msg(sock, mtype, header, payload)
        with self._io_lock:
            self.bytes_sent += n

    def _recv(self, sock):
        mtype, hdr, payload, n = _recv_msg(sock)
        with self._io_lock:
            self.bytes_received += n
        return mtype, hdr, payload

    # -- per-round wire accounting -------------------------------------------
    def mark_round(self) -> None:
        """Open a per-round wire window (ISSUE 16 satellite) — same
        contract as HostChannel.mark_round, under the io lock because
        the heartbeat/accept threads bump the counters concurrently."""
        with self._io_lock:
            self._mark = (self.bytes_sent, self.bytes_received)

    def round_wire_delta(self) -> dict[str, int]:
        with self._io_lock:
            s0, r0 = self._mark
            return {"sent": self.bytes_sent - s0,
                    "received": self.bytes_received - r0}

    # -- worker side ---------------------------------------------------------
    def _connect_worker(self) -> None:
        ctx = self.ctx
        deadline = time.monotonic() + self.connect_timeout_s
        self._sock = _dial_with_backoff(
            self._host, self._port, deadline,
            f"elastic channel: rank {ctx.rank} data link to the "
            f"coordinator at {ctx.coordinator}")
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send(self._sock, "hello",
                   {"rank": ctx.rank, "role": "data",
                    "digest": self.config_digest})
        self._sock.settimeout(max(0.1, deadline - time.monotonic()))
        try:
            mtype, hdr, _ = self._recv(self._sock)
        except (socket.timeout, ConnectionError, OSError) as e:
            raise DeadRankError(
                f"elastic channel: rank {ctx.rank} got no hello reply "
                f"from the coordinator within "
                f"{self.connect_timeout_s:.0f}s "
                f"({type(e).__name__})") from e
        if mtype == "reject":
            raise DeadRankError(hdr.get("error", "rejected"))
        self._install_view(hdr)
        self._hb_sock = _dial_with_backoff(
            self._host, self._port, deadline,
            f"elastic channel: rank {ctx.rank} heartbeat link to the "
            f"coordinator at {ctx.coordinator}")
        self._send(self._hb_sock, "hello",
                   {"rank": ctx.rank, "role": "hb"})
        threading.Thread(target=self._hb_loop, daemon=True,
                         name=f"elastic-hb-{ctx.rank}").start()

    def _hb_loop(self) -> None:
        while not self._closed:
            if not self.hb_paused:
                # live telemetry plane (ISSUE 17): piggyback a bounded
                # metrics delta on the heartbeat header.  With
                # telemetry off the header stays exactly {} — the
                # obs-off heartbeat bytes are byte-identical.
                hdr = {}
                if _cluster.telemetry_enabled():
                    d = _piggyback_delta()
                    if d is not None:
                        hdr["delta"] = d
                try:
                    self._send(self._hb_sock, "hb", hdr)
                except OSError:
                    return      # coordinator gone: the data path names it
            time.sleep(self.hb_interval_s)

    def _install_view(self, hdr: dict) -> None:
        v = ClusterView(int(hdr["epoch"]),
                        tuple(int(m) for m in hdr["members"]),
                        self.n_items)
        if v.epoch < self.view.epoch:
            return                       # stale (reordered) view
        if v.epoch > self.view.epoch:
            self.view_events.append({"epoch": v.epoch,
                                     "members": list(v.members)})
            obs.counter("multihost_view_changes_total").inc()
        self.view = v
        obs.gauge("multihost_epoch", rank=str(self.ctx.rank)).set(
            float(v.epoch))

    # -- coordinator side ----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle_hello, args=(conn,),
                             daemon=True).start()

    def _handle_hello(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10.0)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            mtype, hdr, _ = self._recv(conn)
        except (socket.timeout, ConnectionError, OSError, ValueError):
            conn.close()
            return
        rank = int(hdr.get("rank", -1))
        role = hdr.get("role", mtype)
        if mtype == "rejoin" or role == "rejoin":
            self._handle_rejoin_hello(rank, hdr, conn)
            return
        if mtype != "hello" or rank < 0:
            conn.close()
            return
        if role == "data":
            if hdr.get("digest", "") != self.config_digest:
                try:
                    self._send(conn, "reject", {"error": (
                        f"elastic channel: rank {rank} config digest "
                        f"{hdr.get('digest', '')!r} does not match the "
                        f"cluster's {self.config_digest!r} — the "
                        f"two-level reduction would not be bitwise")})
                except OSError:
                    pass
                conn.close()
                return
            with self._cond:
                old = self._data.pop(rank, None)
                self._data[rank] = conn
                self._hb_last[rank] = max(
                    self._hb_last.get(rank, 0.0), time.monotonic())
                view = self.view
                self._cond.notify_all()
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
            try:
                self._send(conn, "hello_ok",
                           {"epoch": view.epoch,
                            "members": list(view.members),
                            "n_items": self.n_items})
            except OSError:
                pass
        elif role == "hb":
            with self._lock:
                old = self._hb.pop(rank, None)
                self._hb[rank] = conn
                self._hb_last[rank] = max(
                    self._hb_last.get(rank, 0.0), time.monotonic())
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
            self._hb_reader(rank, conn)
        else:
            conn.close()

    def _handle_rejoin_hello(self, rank: int, hdr: dict,
                             conn: socket.socket) -> None:
        """Digest-check NOW (a stale build must be named immediately),
        queue for admission at the next commit barrier otherwise."""
        digest = hdr.get("digest", "")
        if digest != self.config_digest:
            try:
                self._send(conn, "reject", {"error": (
                    f"elastic rejoin: rank {rank} config digest "
                    f"{digest!r} does not match the cluster's "
                    f"{self.config_digest!r} — stale config/code; "
                    f"admission refused")})
            except OSError:
                pass
            conn.close()
            obs.counter("multihost_rejoins_rejected_total").inc()
            return
        with self._lock:
            self._pending_rejoin.append((rank, conn))
        obs.instant("multihost.rejoin_request", rank=rank)
        log.info("elastic: rank %d requested rejoin (pending admission "
                 "at the next commit barrier)", rank)

    def _hb_reader(self, rank: int, conn: socket.socket) -> None:
        conn.settimeout(self.hb_timeout_s)
        while not self._closed:
            try:
                mtype, hdr, _ = self._recv(conn)   # byte-counted
                with self._lock:
                    self._hb_last[rank] = time.monotonic()
                    self._suspect.pop(rank, None)
                _cluster.note_heartbeat(rank)
                delta = (hdr.get("delta") if mtype == "hb" else None)
                if delta:
                    _cluster.fold_remote(rank, delta)
            except socket.timeout:
                with self._lock:
                    fresh = (rank in self.view.members
                             and rank not in self._suspect)
                    if fresh:
                        self._suspect[rank] = (
                            f"no heartbeat for {self.hb_timeout_s:.1f}s "
                            f"(process hung or stopped)")
                if fresh:
                    obs.instant("multihost.rank_suspect", rank=rank)
                    obs.counter("multihost_rank_suspects_total",
                                rank=str(rank)).inc()
                    log.warning("elastic: rank %d heartbeat silent — "
                                "suspected hung", rank)
            except (ConnectionError, OSError, ValueError):
                with self._lock:
                    if rank in self.view.members:
                        self._suspect.setdefault(
                            rank, "heartbeat link closed")
                return

    def wait_members(self) -> None:
        """Rank 0, setup barrier: wait for every initial member's data
        link within connect_timeout_s; ranks that never connect are
        EVICTED (epoch bump, loudly) instead of failing the launch —
        the elastic contract from the very first round."""
        if self.ctx.rank != 0 or self.ctx.world <= 1:
            return
        deadline = time.monotonic() + self.connect_timeout_s
        with self._cond:
            while time.monotonic() < deadline:
                missing = [m for m in self.view.members
                           if m != 0 and m not in self._data]
                if not missing:
                    return
                self._cond.wait(0.1)
            missing = [m for m in self.view.members
                       if m != 0 and m not in self._data]
        if missing:
            log.warning("elastic setup: rank(s) %s never connected "
                        "within %.0fs — evicting and starting without "
                        "them", missing, self.connect_timeout_s)
            self._coord_view_change(missing, -1, None, None,
                                    reason="never connected at setup")

    def _coord_view_change(self, dead: list, round_idx: int,
                           have: Optional[dict], compute,
                           reason: str = "dead or hung") -> None:
        """THE view change: evict `dead`, bump the epoch, notify every
        surviving member (VIEW message carrying the member list + the
        missing items that member now owns), then adopt rank 0's own
        newly-owned missing items.  Latency is measured to the point
        every survivor has been re-tasked — the recompute itself is
        goodput, not membership latency."""
        t0 = time.perf_counter()
        dead = sorted(set(int(r) for r in dead))
        with obs.span("multihost.view_change",
                      epoch=self.view.epoch + 1, round=round_idx):
            with self._lock:
                for r in dead:
                    self._suspect.pop(r, None)
                    for tbl in (self._data, self._hb):
                        s = tbl.pop(r, None)
                        if s is not None:
                            try:
                                s.close()
                            except OSError:
                                pass
                members = tuple(m for m in self.view.members
                                if m not in dead)
                self.view = ClusterView(self.view.epoch + 1, members,
                                        self.n_items)
                view = self.view
                socks = dict(self._data)
            for r in dead:
                obs.counter("multihost_rank_deaths_total",
                            rank=str(r)).inc()
            obs.counter("multihost_view_changes_total").inc()
            obs.gauge("multihost_epoch", rank="0").set(float(view.epoch))
            missing = ([] if have is None else
                       [b for b in range(self.n_items) if b not in have])
            for m in view.members:
                if m == 0 or m not in socks:
                    continue
                need = [b for b in missing if view.owner_of(b) == m]
                try:
                    socks[m].settimeout(self.timeout_s)
                    self._send(socks[m], "view",
                               {"epoch": view.epoch, "round": round_idx,
                                "members": list(view.members),
                                "need": need})
                except (socket.timeout, OSError):
                    with self._lock:
                        self._suspect.setdefault(
                            m, "view notification failed")
        latency = time.perf_counter() - t0
        obs.histogram("multihost_view_change_seconds").observe(latency)
        self.view_events.append({
            "epoch": view.epoch, "round": round_idx, "dead": dead,
            "members": list(view.members), "latency_s": latency,
            "reason": reason})
        log.warning("elastic view change: epoch %d, rank(s) %s evicted "
                    "(%s), members now %s (%.1f ms)", view.epoch, dead,
                    reason, list(view.members), latency * 1e3)
        # coordinated incident dump (ISSUE 17): every survivor snapshots
        # the same incident window (throttled; no-op with telemetry off)
        _cluster.maybe_coordinated_dump(
            f"view_change:epoch{view.epoch}:dead{dead}")
        # rank 0's own re-adoption (outside the latency window: this is
        # recompute goodput, the survivors are already re-tasked)
        if have is not None and compute is not None:
            mine = [b for b in missing if view.owner_of(b) == 0]
            if mine:
                have.update({int(b): bytes(v)
                             for b, v in compute(mine).items()})

    # -- the elastic collective ----------------------------------------------
    def _note_items(self, values) -> None:
        for v in values:
            n = len(v)
            if self._item_nbytes is None:
                self._item_nbytes = n
            elif n != self._item_nbytes:
                raise ValueError(
                    f"elastic exchange: item payload of {n} bytes, "
                    f"expected {self._item_nbytes} (config skew or a "
                    f"truncated frame)")

    def contrib_begin(self, round_idx: int) -> _ContribHandle:
        """Open an early-contribution window for `round_idx` (the
        overlap path): blocks pushed through contrib_push ship while
        the remaining blocks still compute, and exchange(pending=h)
        closes the window."""
        return _ContribHandle(round_idx)

    def contrib_push(self, h: _ContribHandle, block: int,
                     data: bytes) -> None:
        """Ship one block's payload into an open window.  Rank 0
        stashes (its blocks never cross the wire); workers chain a
        single-block contrib send onto the previous push so socket
        writes serialize while the caller computes the next block.  A
        death mid-window surfaces at the exchange() join — the round's
        re-adoption then runs against the frozen carry via `compute`,
        never against this stale buffer."""
        data = bytes(data)
        self._note_items([data])
        h.blocks.append(int(block))
        if self.ctx.world <= 1 or self.ctx.rank == 0:
            h.stash[int(block)] = data
            return
        from fedml_tpu.parallel.prefetch import AsyncValue

        prev = h.pending

        def _ship(prev=prev, block=int(block), data=data):
            if prev is not None:
                prev.result()
            self._send_contrib(h.round_idx, {block: data})

        h.pending = AsyncValue(_ship,
                               name=f"contrib_push#{h.round_idx}")

    def exchange(self, round_idx: int, parts: dict,
                 compute: Optional[Callable] = None,
                 pending: Optional[_ContribHandle] = None
                 ) -> tuple[dict, ClusterView]:
        """The block-keyed elastic allgather: contribute `parts`
        ({item: f32 bytes/ndarray}), receive ALL n_items item payloads
        plus the view that completed the round.  `compute(items)` is
        the re-adoption callback — invoked when a view change
        re-assigns a dead rank's missing items to this rank mid-round.
        `pending` closes an overlap window opened by contrib_begin:
        its pushes are drained (worker) or merged into `parts` (rank
        0) before the collective proper.  Every rank receives the
        identical payload set, so any deterministic fold over it
        (fold_block_partials) commits the same bits on every
        survivor."""
        t0 = time.perf_counter()
        parts = {int(b): (v.tobytes() if hasattr(v, "tobytes")
                          else bytes(v))
                 for b, v in parts.items()}
        self._note_items(parts.values())
        pre_sent: tuple = ()
        if pending is not None:
            if pending.round_idx != round_idx:
                raise ValueError(
                    f"elastic exchange round {round_idx}: pending "
                    f"contributions belong to round "
                    f"{pending.round_idx}")
            if self.ctx.rank == 0 or self.ctx.world <= 1:
                parts = {**pending.stash, **parts}
            else:
                if pending.pending is not None:
                    pending.pending.result()   # DeadRankError re-raises
                pre_sent = tuple(pending.blocks)
        try:
            if self.ctx.rank == 0:
                return self._exchange_coord(round_idx, parts, compute)
            return self._exchange_worker(round_idx, parts, compute,
                                         pre_sent)
        finally:
            obs.histogram("multihost_allgather_seconds").observe(
                time.perf_counter() - t0)

    def _exchange_coord(self, round_idx, parts, compute):
        have: dict[int, bytes] = dict(parts)
        # barrier ledger (ISSUE 17): rank 0 arrives with its own parts
        # in hand; each member arrives at its first accepted contrib
        # for THIS round.  Dead ranks never arrive and stay absent.
        arrivals: dict[int, float] = {self.ctx.rank: time.monotonic()}
        deadline = time.monotonic() + self.timeout_s
        while True:
            missing = [b for b in range(self.n_items) if b not in have]
            if not missing:
                break
            # rank 0's own outstanding items first (covers world==1 and
            # re-adoption immediately after a view change)
            mine = [b for b in missing if self.view.owner_of(b) == 0]
            if mine:
                if compute is None:
                    raise DeadRankError(
                        f"elastic exchange #{round_idx}: items {mine} "
                        f"fell to rank 0 but no compute callback was "
                        f"given")
                got = {int(b): bytes(v)
                       for b, v in compute(mine).items()}
                self._note_items(got.values())
                have.update(got)
                continue
            now = time.monotonic()
            with self._lock:
                dead = set(self._suspect)
                hb_stale = [m for m in self.view.members
                            if m != 0
                            and now - self._hb_last.get(m, now)
                            > self.hb_timeout_s]
                socks = dict(self._data)
            dead |= set(hb_stale)
            dead &= set(self.view.members) - {0}
            if now > deadline:
                # whoever still owes an item at the deadline is hung
                dead |= {self.view.owner_of(b) for b in missing} - {0}
            if dead:
                self._coord_view_change(sorted(dead), round_idx, have,
                                        compute)
                # the re-tasked survivors legitimately need fresh time
                # to recompute the dead rank's blocks — without this, a
                # view change late in the window would cascade into
                # false evictions of healthy, still-computing ranks
                deadline = max(deadline,
                               time.monotonic() + self.timeout_s)
                continue
            rl: list = []
            waitable = [s for m, s in socks.items()
                        if m in self.view.members]
            if waitable:
                try:
                    rl, _, _ = select.select(waitable, [], [], 0.1)
                except (OSError, ValueError):
                    rl = []     # a sock closed under us: re-snapshot
            else:
                time.sleep(0.05)
            for s in rl:
                m = next((r for r, c in socks.items() if c is s), None)
                if m is None:
                    continue
                try:
                    s.settimeout(max(0.05, min(5.0,
                                               deadline - now)))
                    mtype, hdr, payload = self._recv(s)
                except (socket.timeout, ConnectionError, OSError,
                        ValueError):
                    with self._lock:
                        self._suspect.setdefault(m, "data link failed")
                    continue
                if mtype != "contrib":
                    continue
                if int(hdr.get("round", -1)) != round_idx:
                    log.warning("elastic: dropping stale contrib for "
                                "round %s from rank %d (at round %d)",
                                hdr.get("round"), m, round_idx)
                    continue
                arrivals.setdefault(m, time.monotonic())
                blocks = [int(b) for b in hdr.get("blocks", [])]
                if self._item_nbytes is None and blocks:
                    self._item_nbytes = len(payload) // len(blocks)
                sz = self._item_nbytes or 0
                if sz * len(blocks) != len(payload):
                    with self._lock:
                        self._suspect.setdefault(
                            m, f"contrib size mismatch "
                               f"({len(payload)} bytes for "
                               f"{len(blocks)} items of {sz})")
                    continue
                for j, b in enumerate(blocks):
                    if 0 <= b < self.n_items and b not in have:
                        have[b] = payload[j * sz:(j + 1) * sz]
        _cluster.note_barrier("exchange", round_idx, round_idx,
                              arrivals)
        # broadcast the complete, identically-ordered payload set
        blob = b"".join(have[b] for b in range(self.n_items))
        view = self.view
        with self._lock:
            socks = dict(self._data)
        for m in view.members:
            if m == 0 or m not in socks:
                continue
            try:
                socks[m].settimeout(self.timeout_s)
                self._send(socks[m], "result",
                           {"epoch": view.epoch, "round": round_idx,
                            "members": list(view.members)},
                           blob)
            except (socket.timeout, OSError):
                with self._lock:
                    self._suspect.setdefault(m, "result send failed")
        return have, view

    def _exchange_worker(self, round_idx, parts, compute,
                         pre_sent: tuple = ()):
        sent = set(parts) | set(pre_sent)
        if parts or not pre_sent:
            # an all-early overlap round has nothing left to contribute
            # inline; everything else keeps the eager single contrib
            self._send_contrib(round_idx, parts)
        deadline = time.monotonic() + self.timeout_s
        while True:
            self._sock.settimeout(
                max(0.05, deadline - time.monotonic()))
            try:
                mtype, hdr, payload = self._recv(self._sock)
            except (socket.timeout, ConnectionError, OSError,
                    ValueError) as e:
                raise DeadRankError(
                    f"elastic exchange round {round_idx}: rank "
                    f"{self.ctx.rank} lost the rank-0 coordinator "
                    f"({type(e).__name__}: coordinator dead, or this "
                    f"rank was evicted from the view)") from e
            if mtype == "view":
                self._install_view(hdr)
                # a view change re-tasks the survivors: the round
                # legitimately runs longer than one clean window
                deadline = max(deadline,
                               time.monotonic() + self.timeout_s)
                need = [int(b) for b in hdr.get("need", [])
                        if int(b) not in sent]
                if need and compute is not None:
                    out = {int(b): bytes(v)
                           for b, v in compute(need).items()}
                    self._send_contrib(round_idx, out)
                    sent |= set(out)
            elif mtype == "result":
                if int(hdr.get("round", -1)) != round_idx:
                    continue             # stale (already-consumed) round
                self._install_view(hdr)
                sz = len(payload) // self.n_items
                if sz * self.n_items != len(payload):
                    raise DeadRankError(
                        f"elastic exchange round {round_idx}: result "
                        f"payload of {len(payload)} bytes does not "
                        f"tile {self.n_items} items")
                return ({b: payload[b * sz:(b + 1) * sz]
                         for b in range(self.n_items)}, self.view)
            elif mtype == "dump":
                # coordinated incident dump (ISSUE 17): the coordinator
                # saw a view change / death / SLO breach — snapshot the
                # same window into THIS rank's obs dir (no-op when obs
                # is off)
                obs.dump_flight(
                    "coordinated:" + str(hdr.get("reason", "")))
            # other message types: ignore

    def _send_contrib(self, round_idx: int,
                      parts: dict[int, bytes]) -> None:
        blocks = sorted(parts)
        try:
            self._sock.settimeout(self.timeout_s)
            self._send(self._sock, "contrib",
                       {"epoch": self.view.epoch, "round": round_idx,
                        "blocks": blocks},
                       b"".join(parts[b] for b in blocks))
        except (socket.timeout, ConnectionError, OSError) as e:
            raise DeadRankError(
                f"elastic exchange round {round_idx}: rank "
                f"{self.ctx.rank} could not ship its contribution to "
                f"the coordinator ({type(e).__name__})") from e

    # -- rejoin --------------------------------------------------------------
    def rejoin_handshake(self) -> tuple[bytes, int, str]:
        """Restarted-worker entry: dial the coordinator's rejoin role,
        present the config digest, await admission (granted at the next
        commit barrier) — returns (snapshot payload, resume_round,
        run_tag) and leaves the channel fully connected (data +
        heartbeat links) under the new membership.  `run_tag` names
        WHICH run the snapshot belongs to (a worker driving several
        sequential runs over one channel — mh_worker's residency modes
        — must resume the run the coordinator is actually in, not
        whichever it would have started first)."""
        ctx = self.ctx
        deadline = time.monotonic() + self.connect_timeout_s
        sock = _dial_with_backoff(
            self._host, self._port, deadline,
            f"elastic rejoin: rank {ctx.rank} dialing the coordinator "
            f"at {ctx.coordinator}")
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._send(sock, "rejoin",
                       {"rank": ctx.rank,
                        "digest": self.config_digest})
            # admission lands at a commit barrier: budget a full round
            # on top of the connect window
            sock.settimeout(self.timeout_s + self.connect_timeout_s)
            try:
                mtype, hdr, payload = self._recv(sock)
            except (socket.timeout, ConnectionError, OSError) as e:
                raise DeadRankError(
                    f"elastic rejoin: rank {ctx.rank} got no admission "
                    f"from the coordinator within "
                    f"{self.timeout_s + self.connect_timeout_s:.0f}s "
                    f"({type(e).__name__}: run finished or coordinator "
                    f"dead)") from e
            if mtype == "reject":
                raise DeadRankError(hdr.get("error", "rejoin rejected"))
            if mtype != "snapshot":
                raise DeadRankError(
                    f"elastic rejoin: unexpected {mtype!r} reply")
        finally:
            sock.close()
        self._install_view(hdr)
        self._connect_worker()
        log.info("elastic: rank %d readmitted at epoch %d, resuming "
                 "run %r at round %d", ctx.rank, self.view.epoch,
                 hdr.get("tag", ""), int(hdr["resume_round"]))
        return payload, int(hdr["resume_round"]), hdr.get("tag", "")

    def admit_rejoins(self, resume_round: int,
                      snapshot_fn: Callable[[], bytes],
                      tag: str = "") -> list:
        """Rank 0, at a commit barrier: admit every pending rejoiner —
        epoch bump, SNAPSHOT reply (view + resume round + the model
        blob snapshot_fn builds), VIEW notification to the incumbents.
        Returns the admitted ranks."""
        if self.ctx.rank != 0:
            return []
        with self._lock:
            pending, self._pending_rejoin = self._pending_rejoin, []
        if not pending:
            return []
        blob = snapshot_fn()
        admitted = []
        for rank, conn in pending:
            if rank in self.view.members:
                try:
                    self._send(conn, "reject", {"error": (
                        f"elastic rejoin: rank {rank} is still a live "
                        f"member of epoch {self.view.epoch} — a rank id "
                        f"cannot be claimed twice")})
                except OSError:
                    pass
                conn.close()
                continue
            members = tuple(sorted(set(self.view.members) | {rank}))
            view = ClusterView(self.view.epoch + 1, members,
                               self.n_items)
            try:
                conn.settimeout(self.timeout_s)
                self._send(conn, "snapshot",
                           {"epoch": view.epoch,
                            "resume_round": int(resume_round),
                            "members": list(members),
                            "n_items": self.n_items,
                            "tag": tag},
                           blob)
            except (socket.timeout, OSError):
                conn.close()
                log.warning("elastic: rejoiner rank %d vanished before "
                            "its snapshot was delivered", rank)
                continue
            conn.close()
            with self._lock:
                self.view = view
                # connect grace for the fresh data/hb links
                self._hb_last[rank] = (time.monotonic()
                                       + self.connect_timeout_s)
                self._suspect.pop(rank, None)
            admitted.append(rank)
            obs.counter("multihost_rejoins_admitted_total").inc()
            obs.gauge("multihost_epoch", rank="0").set(float(view.epoch))
            obs.counter("multihost_view_changes_total").inc()
            self.view_events.append({
                "epoch": view.epoch, "round": int(resume_round),
                "rejoined": [rank], "members": list(members),
                "latency_s": 0.0, "reason": "rejoin admitted"})
            log.warning("elastic: rank %d readmitted at epoch %d "
                        "(resume round %d)", rank, view.epoch,
                        resume_round)
        if admitted:
            with self._lock:
                socks = dict(self._data)
            for m in self.view.members:
                if m == 0 or m in admitted or m not in socks:
                    continue
                try:
                    socks[m].settimeout(self.timeout_s)
                    self._send(socks[m], "view",
                               {"epoch": self.view.epoch,
                                "round": int(resume_round),
                                "members": list(self.view.members),
                                "need": []})
                except (socket.timeout, OSError):
                    with self._lock:
                        self._suspect.setdefault(
                            m, "view notification failed")
        return admitted

    def _broadcast_dump_frames(self, reason: str) -> None:
        """Fan a coordinated-dump order out to every surviving member's
        data link (registered with the observatory as the DUMP
        broadcaster at construction).  Best-effort: a member that died
        between the snapshot and the send is already being handled by
        the failure detector."""
        with self._lock:
            socks = {m: s for m, s in self._data.items()
                     if m in self.view.members}
        for m, s in socks.items():
            try:
                self._send(s, "dump", {"reason": str(reason)})
            except OSError:
                pass

    # -- plumbing shared with HostChannel ------------------------------------
    def export_byte_counters(self) -> None:
        _export_channel_byte_counters(self.ctx.rank, self.bytes_sent,
                                      self.bytes_received)

    def close(self) -> None:
        self._closed = True
        if self.ctx.rank == 0:
            _cluster.set_dump_broadcaster(None)
        with self._lock:
            socks = (list(self._data.values()) + list(self._hb.values())
                     + [c for _, c in self._pending_rejoin])
            self._data.clear()
            self._hb.clear()
            self._pending_rejoin.clear()
        for s in socks + [self._sock, self._hb_sock, self._listener]:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = self._hb_sock = self._listener = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# the two-level round loop
# ---------------------------------------------------------------------------

# per-PROCESS metrics-rollup baseline: (registry identity, prev state).
# Keyed on the registry object so obs.reset() (tests) naturally resets
# the baseline with it.  The heartbeat thread's live piggyback (ISSUE
# 17) and the end-of-run rollup advance the SAME baseline — their
# shipped windows are disjoint, so rank 0 never double-counts — which
# is why a lock guards the read-modify-write.
_rollup_state: Optional[tuple] = None
_rollup_lock = threading.Lock()


def _delta_since_last_rollup() -> dict:
    global _rollup_state
    with _rollup_lock:
        reg = obs.registry()
        prev = (_rollup_state[1]
                if _rollup_state is not None and _rollup_state[0] is reg
                else None)
        delta, state = reg.delta_snapshot(prev)
        _rollup_state = (reg, state)
        return delta


def _piggyback_delta(
        cap_bytes: int = _cluster.SIDECAR_CAP_BYTES) -> Optional[dict]:
    """Bounded per-beat metrics delta for the live telemetry plane.
    Advances the rollup baseline ONLY when something ships: an empty
    delta returns None, and a delta over the frame budget returns None
    WITHOUT advancing — it rides a later beat or the final rollup
    instead of bloating a control frame."""
    global _rollup_state
    with _rollup_lock:
        reg = obs.registry()
        prev = (_rollup_state[1]
                if _rollup_state is not None and _rollup_state[0] is reg
                else None)
        delta, state = reg.delta_snapshot(prev)
        if not delta.get("metrics"):
            return None
        if len(json.dumps(delta, sort_keys=True).encode()) > cap_bytes:
            return None
        _rollup_state = (reg, state)
        return delta


class MultihostRunner:
    """Two-level multihost round loop over a FedAvg-family mesh engine.

    Per round, on every process:

      1. sample: `BlockCohortSampler` draws each block's cohort from its
         population range — pure function of (seed, round, block);
      2. partial (ICI tier): for each OWNED block (contiguous tiling:
         rank r owns blocks [r·B/W, (r+1)·B/W)), gather+upload the
         block cohort (host-sharded data: only this process's blocks
         cross H2D; double-buffered per-host prefetch on the streaming
         path) and run the engine's `{family}_twolevel` partial program
         — chunk-scanned local training + intra-host psum on the LOCAL
         mesh, returning the flat f32 carry;
      3. allreduce (DCN tier): `HostChannel.allgather` of the owned
         partials, then EVERY process folds all B partials in global
         block order (`fold_block_partials`);
      4. commit: the replicated `twolevel_commit` program divides and
         applies the server update identically on every process.

    Bitwise anchor: with a fixed `n_blocks`, same-seed runs at ANY
    process count that tiles the blocks commit identical bits (the
    2-vs-1-process pin in tests/test_multihost_spmd.py).  Resident
    mode uploads only this process's population range to device;
    streaming mode uploads only its blocks' cohorts per round —
    nothing population-sized crosses process boundaries either way."""

    def __init__(self, engine, ctx: Optional[MultihostContext] = None,
                 *, n_blocks: Optional[int] = None,
                 channel: Optional[HostChannel] = None,
                 timeout_s: float = 120.0,
                 carry_codec: str = "f32",
                 carry_chunk: Optional[int] = None,
                 overlap_exchange: bool = False,
                 on_round_end: Optional[Callable[[int], None]] = None):
        from fedml_tpu.parallel.engine import MeshFedAvgEngine
        from fedml_tpu.parallel.hierarchical import MeshHierarchicalEngine
        if (not isinstance(engine, MeshFedAvgEngine)
                or isinstance(engine, MeshHierarchicalEngine)):
            # hierarchical subclasses the FedAvg engine but its rounds
            # are group_comm_round-structured — folding its sums flat
            # here would SILENTLY compute plain FedAvg instead (its
            # multihost story is the silo-per-host mesh above)
            raise ValueError(
                f"MultihostRunner drives the flat FedAvg-family mesh "
                f"engines, not {type(engine).__name__}")
        if engine.stream_block is not None:
            raise ValueError(
                "MultihostRunner does not drive block-streamed rounds "
                "yet: stream WITHIN a host via smaller blocks, or use "
                "streaming mode (per-block cohorts already bound device "
                "memory by O(block))")
        if getattr(engine, "defense", "norm_clip") not in ("norm_clip",):
            raise ValueError(
                f"two-level aggregation is linear: order-statistic "
                f"defense {engine.defense!r} cannot fold across hosts "
                f"(its [K, P] matrix needs every client row)")
        # the engine's mesh must be process-local: the cross-host tier
        # is the HostChannel, never an in-program collective
        for d in engine.mesh.devices.flat:
            if d.process_index != jax.process_index():
                raise ValueError(
                    "MultihostRunner needs a LOCAL mesh (build the "
                    "engine with make_local_mesh()): device "
                    f"{d} belongs to process {d.process_index}")
        self.engine = engine
        self.ctx = ctx if ctx is not None else (
            MultihostContext.from_env() or MultihostContext.single())
        self.timeout_s = float(timeout_s)
        self.on_round_end = on_round_end
        world = self.ctx.world
        self.n_blocks = int(n_blocks) if n_blocks else world
        if self.n_blocks % world:
            raise ValueError(
                f"n_blocks ({self.n_blocks}) must be a multiple of the "
                f"process count ({world}) — contiguous tiling is the "
                f"bitwise contract")
        cfg = engine.cfg
        if cfg.client_num_per_round % self.n_blocks:
            raise ValueError(
                f"client_num_per_round ({cfg.client_num_per_round}) "
                f"must divide evenly into {self.n_blocks} blocks")
        self.sampler = BlockCohortSampler(
            engine.data.client_num, self.n_blocks,
            cfg.client_num_per_round // self.n_blocks, cfg.seed)
        bpp = self.n_blocks // world
        self.owned_blocks = tuple(range(self.ctx.rank * bpp,
                                        (self.ctx.rank + 1) * bpp))
        # this process's population id range (contiguous because its
        # blocks are) — the resident device stack holds ONLY this slice
        self.range_lo = self.owned_blocks[0] * self.sampler.range_size
        self.range_hi = ((self.owned_blocks[-1] + 1)
                         * self.sampler.range_size)
        self._channel = channel
        self._owns_channel = channel is None
        self._range_stack = None
        self._range_stack_w = None
        self._prefetched = None
        from fedml_tpu.parallel.carry_codec import (DEFAULT_CHUNK,
                                                    make_carry_codec)
        self.codec = make_carry_codec(
            carry_codec,
            chunk=DEFAULT_CHUNK if carry_chunk is None else carry_chunk)
        self.overlap_exchange = bool(overlap_exchange)
        self.round_walls: list[float] = []
        self.carry_bytes: list[int] = []
        self.carry_wire_sent: list[int] = []
        self.carry_raw: list[int] = []       # f32 bytes before encoding
        self.carry_payload: list[int] = []   # encoded payload bytes
        self.overlap_waits: list[float] = []
        self.exchange_walls: list[float] = []
        engine._ensure_twolevel()

    # -- setup ---------------------------------------------------------------
    @property
    def channel(self) -> HostChannel:
        if self._channel is None:
            self._channel = HostChannel(self.ctx,
                                        timeout_s=self.timeout_s)
        return self._channel

    def _config_doc(self) -> bytes:
        """The canonical cross-rank config document: the quantities the
        bitwise contract depends on.  Fail-fast mode allgathers it
        (_handshake); elastic mode hellos/rejoins carry its md5 as the
        cluster config digest."""
        eng = self.engine
        return json.dumps({
            "n_blocks": self.n_blocks,
            "k_per_block": self.sampler.k_per_block,
            "population": self.sampler.population,
            "n_shards": eng.n_shards,
            "chunk": eng.chunk,
            "seed": eng.cfg.seed,
            "family": eng.program_family,
            "streaming": bool(eng.streaming),
            # the carry codec shapes every wire payload — a mixed-codec
            # cluster must be NAMED at handshake, not discovered as a
            # size mismatch mid-round
            "carry_codec": self.codec.name,
            "carry_chunk": self.codec.chunk,
        }, sort_keys=True).encode()

    def _handshake(self) -> None:
        """Cross-rank config agreement: the bitwise contract only holds
        when every process runs the identical partition and programs —
        a mismatch names the ranks instead of silently diverging."""
        doc = self._config_doc()
        docs = self.channel.allgather(doc, timeout_s=self.timeout_s)
        for r, d in enumerate(docs):
            if d != docs[0]:
                raise RuntimeError(
                    f"multihost config mismatch: rank {r} runs "
                    f"{d.decode()!r} vs rank 0's {docs[0].decode()!r} — "
                    f"the two-level reduction would not be bitwise")

    # -- per-round pieces ----------------------------------------------------
    def _block_inputs(self, round_idx: int, block: int, train_rng):
        """(global ids, wmask, crngs) for one block — all pure functions
        of (seed, round, block)."""
        from fedml_tpu.parallel.engine import pad_ids
        ids, wmask = pad_ids(self.sampler.sample_block(round_idx, block),
                             self.engine.n_shards)
        block_rng = jax.random.fold_in(train_rng, block)
        crngs = np.asarray(jax.random.split(block_rng, len(ids)))
        return ids, wmask, crngs

    def _upload_id_range(self, lo: int, hi: int) -> tuple:
        """Slice the host client stack to [lo, hi), cast/pad, and
        upload it sharded over the local mesh — THE one resident
        upload body (the contiguous whole-range stack and the elastic
        per-block stacks both go through here, so cast/pad/byte
        accounting can never diverge)."""
        from fedml_tpu.parallel.mesh import (client_sharding, pad_cohort,
                                             shard_stack)
        eng = self.engine
        shards = {k: np.asarray(v)[lo:hi]
                  for k, v in eng._host_shards().items()}
        weights = np.asarray(eng.data.client_num_samples,
                             np.float32)[lo:hi]
        shards, weights = pad_cohort(eng._cast_stack_x(shards), weights,
                                     eng.n_shards)
        eng.transfer_stats.add_h2d_bytes(
            sum(np.asarray(v).nbytes for v in shards.values())
            + weights.nbytes)
        stack = shard_stack(eng.mesh, shards)
        stack_w = jax.device_put(weights.astype(np.float32),
                                 client_sharding(eng.mesh))
        return stack, stack_w

    def _upload_range_stack(self):
        """Resident mode: upload THIS process's population id range
        once, sharded over the local mesh (device residency is
        id-range-partitioned across hosts — the registry/shardstore
        partition, applied to HBM)."""
        if self._range_stack is not None:
            return self._range_stack, self._range_stack_w
        self._range_stack, self._range_stack_w = self._upload_id_range(
            self.range_lo, self.range_hi)
        return self._range_stack, self._range_stack_w

    def _gather_streaming(self, round_idx: int, train_rng):
        """Host-gather + upload every OWNED block's cohort (the per-host
        input pipeline; runs on the prefetch thread when pipelined)."""
        out = []
        for b in self.owned_blocks:
            ids, wmask, crngs = self._block_inputs(round_idx, b,
                                                   train_rng)
            cohort, weights = self.engine._stream_gather(ids, wmask)
            out.append((b, cohort, weights, crngs))
        return out

    def _partials_resident(self, variables, round_idx: int, train_rng):
        eng = self.engine
        stack, stack_w = self._upload_range_stack()
        parts = {}
        for b in self.owned_blocks:
            ids, wmask, crngs = self._block_inputs(round_idx, b,
                                                   train_rng)
            local_ids = ids - self.range_lo
            flat = eng._twolevel_partial_resident(
                variables, stack, stack_w, jax.numpy.asarray(local_ids),
                jax.numpy.asarray(wmask), jax.numpy.asarray(crngs))
            parts[b] = np.asarray(flat, dtype=np.float32)
        return parts

    def _streaming_blocks(self, round_idx: int, train_rng, rng_base,
                          rounds: int) -> list:
        """The streaming input head with the per-host double-buffered
        prefetch: consume round r's gathered blocks (from the prefetch
        thread when pipelined), schedule round r+1's gather+upload
        (parallel/prefetch.py AsyncValue — the engines' own pipeline,
        reused per host)."""
        from fedml_tpu.parallel.prefetch import AsyncValue
        eng = self.engine
        pre = self._prefetched
        if pre is not None and pre[0] == round_idx:
            blocks = pre[1].result()
        else:
            if pre is not None:
                try:
                    pre[1].result()
                except Exception:
                    log.warning("discarding failed stale multihost "
                                "prefetch for round %d", pre[0],
                                exc_info=True)
            blocks = self._gather_streaming(round_idx, train_rng)
        self._prefetched = None
        if eng.prefetch and round_idx + 1 < rounds:
            nxt_rng = jax.random.split(
                jax.random.fold_in(rng_base, round_idx + 1))[0]
            self._prefetched = (
                round_idx + 1,
                AsyncValue(self._gather_streaming, round_idx + 1,
                           nxt_rng, stats=eng.transfer_stats))
        return blocks

    def _partials_streaming(self, variables, round_idx: int, train_rng,
                            rng_base, rounds: int):
        eng = self.engine
        parts = {}
        for b, cohort, weights, crngs in self._streaming_blocks(
                round_idx, train_rng, rng_base, rounds):
            flat = eng._twolevel_partial(variables, cohort, weights,
                                         jax.numpy.asarray(crngs))
            parts[b] = np.asarray(flat, dtype=np.float32)
        return parts

    def _iter_partials(self, variables, round_idx: int, train_rng,
                       rng_base, rounds: int):
        """Per-block partial stream for the overlapped exchange: yields
        (block, f32 vector) in owned-block order, so each block's carry
        can ship while the next one computes."""
        eng = self.engine
        if eng.streaming:
            for b, cohort, weights, crngs in self._streaming_blocks(
                    round_idx, train_rng, rng_base, rounds):
                flat = eng._twolevel_partial(variables, cohort, weights,
                                             jax.numpy.asarray(crngs))
                yield b, np.asarray(flat, dtype=np.float32)
            return
        stack, stack_w = self._upload_range_stack()
        for b in self.owned_blocks:
            ids, wmask, crngs = self._block_inputs(round_idx, b,
                                                   train_rng)
            local_ids = ids - self.range_lo
            flat = eng._twolevel_partial_resident(
                variables, stack, stack_w, jax.numpy.asarray(local_ids),
                jax.numpy.asarray(wmask), jax.numpy.asarray(crngs))
            yield b, np.asarray(flat, dtype=np.float32)

    # -- codec plumbing ------------------------------------------------------
    def _encode_block(self, block: int, vec: np.ndarray) -> bytes:
        with obs.span("multihost.encode_carry", codec=self.codec.name,
                      block=block):
            data = self.codec.encode(block, vec)
        self._round_raw += vec.size * 4
        self._round_payload += len(data)
        return data

    def _finish_round_bytes(self) -> None:
        """Close this round's byte accounting: payload-level raw/wire
        into the codec counters + the channel-measured wire deltas (the
        ISSUE-16 satellite: the ratio the bench judges is what the
        channel moved)."""
        _account_carry(self._round_raw, self._round_payload)
        self.carry_raw.append(self._round_raw)
        self.carry_payload.append(self._round_payload)
        d = self.channel.round_wire_delta()
        self.carry_bytes.append(d["received"])
        self.carry_wire_sent.append(d["sent"])

    def _fold_docs(self, docs: list, dim: int) -> np.ndarray:
        """Decode every rank's payload through the codec and fold in
        global block order — decode is deterministic f64 math, so all
        ranks fold identical f32 partials from identical wire bytes."""
        world = self.ctx.world
        bpp = self.n_blocks // world
        enb = self.codec.encoded_nbytes(dim)
        all_parts: dict[int, np.ndarray] = {}
        for r, doc in enumerate(docs):
            if len(doc) != bpp * enb:
                raise DeadRankError(
                    f"two-level allreduce: rank {r} shipped "
                    f"{len(doc)} bytes, expected {bpp * enb} "
                    f"({bpp} blocks x {enb} B {self.codec.name} "
                    f"carry) — config skew or a truncated frame")
            for j in range(bpp):
                all_parts[r * bpp + j] = doc[j * enb:(j + 1) * enb]
        return self._decode_fold(all_parts)

    def _decode_fold(self, bufs: dict) -> np.ndarray:
        """Decode per-block wire payloads and fold: dense codecs decode
        then left-fold; sparse codecs scatter-add (idx, vals) pairs in
        the SAME global block order (ISSUE 19) without densifying a
        per-block vector.  The f32 path is untouched — the bitwise
        anchors ride fold_block_partials exactly as before."""
        if getattr(self.codec, "sparse", False):
            if hasattr(self.codec, "integrate"):
                # stateful sparse (topk_ef): every rank advances every
                # block's reconstruction mirror on the same wire bytes
                # — the delta frames integrate into dense per-block
                # reconstructions, then the dense left-fold keeps the
                # block-order contract
                return fold_block_partials(
                    {int(b): self.codec.integrate(int(b), bytes(v))
                     for b, v in bufs.items()}, self.n_blocks)
            pairs, dim = {}, 0
            for b, v in bufs.items():
                dim, idx, vals = self.codec.decode_pairs(bytes(v))
                pairs[int(b)] = (idx, vals)
            return fold_sparse_partials(pairs, self.n_blocks, dim)
        return fold_block_partials(
            {int(b): self.codec.decode(bytes(v))
             for b, v in bufs.items()}, self.n_blocks)

    def carry_state(self) -> dict:
        """The codec's residual state (error-feedback accumulators):
        ship it as FedCheckpointManager extra_state so crash-resume
        continues the same compression-error trajectory."""
        return self.codec.state_dict()

    def load_carry_state(self, state: Optional[dict]) -> None:
        self.codec.load_state_dict(state or {})

    def _round_exchange(self, variables, round_idx: int, train_rng,
                        rng_base, rounds: int) -> np.ndarray:
        """One round's partials + inter-host carry allreduce, returning
        the folded carry.  Serial path: compute everything, then one
        blocking allgather of the encoded payload.  Overlapped path
        (--overlap_exchange): open a pipelined gather and push each
        block's encoded carry as it materializes, so the DCN transfer
        rides under the remaining blocks' compute; only the final
        gather_finish is visible wait (the multihost.overlap_wait
        span).  Both paths move identical bytes in identical order —
        the f32 escape hatch stays bitwise under overlap."""
        ch = self.channel
        ch.mark_round()
        self._round_raw = self._round_payload = 0
        w0 = time.perf_counter()
        if self.overlap_exchange and self.ctx.world > 1:
            h = ch.gather_begin(len(self.owned_blocks),
                                timeout_s=self.timeout_s)
            dim = 0
            try:
                for b, vec in self._iter_partials(
                        variables, round_idx, train_rng, rng_base,
                        rounds):
                    dim = vec.size
                    ch.gather_push(h, self._encode_block(b, vec))
                with obs.span("multihost.overlap_wait",
                              round=round_idx):
                    t0 = time.perf_counter()
                    docs = ch.gather_finish(h)
                    wait = time.perf_counter() - t0
            except Exception:
                ch.gather_abort(h)
                raise
            self.overlap_waits.append(wait)
            self.exchange_walls.append(time.perf_counter() - w0)
        else:
            if self.engine.streaming:
                parts = self._partials_streaming(
                    variables, round_idx, train_rng, rng_base, rounds)
            else:
                parts = self._partials_resident(variables, round_idx,
                                                train_rng)
            dim = next(iter(parts.values())).size
            payload = b"".join(self._encode_block(b, parts[b])
                               for b in sorted(parts))
            with obs.span("multihost.allreduce", round=round_idx):
                t0 = time.perf_counter()
                docs = ch.allgather(payload, timeout_s=self.timeout_s)
                wait = time.perf_counter() - t0
            # the whole exchange is visible wait on the serial path, so
            # overlap_fraction reports an honest ~0 (InlineFetcher's
            # convention)
            self.overlap_waits.append(wait)
            self.exchange_walls.append(wait)
        self._finish_round_bytes()
        return self._fold_docs(docs, dim)

    # -- the loop ------------------------------------------------------------
    def run(self, variables=None, rounds: Optional[int] = None,
            logger=None):
        """Drive `rounds` two-level rounds; returns the trained
        variables (identical bits on every process).  Only rank 0
        appends metrics_history/logs — peers compute the same values
        anyway."""
        eng = self.engine
        cfg = eng.cfg
        rounds = rounds if rounds is not None else cfg.comm_round
        if variables is None:
            variables = eng.init_variables()
        variables = eng._prepare_variables(variables)
        server_state = eng._prepare_server_state(
            eng.server_init(variables))
        rng_base = jax.random.PRNGKey(cfg.seed + 1)
        self._handshake()
        try:
            for round_idx in range(rounds):
                t0 = time.perf_counter()
                round_rng = jax.random.fold_in(rng_base, round_idx)
                train_rng, agg_rng = jax.random.split(round_rng)
                with obs.span("round.twolevel", round=round_idx,
                              rank=self.ctx.rank,
                              blocks=len(self.owned_blocks)):
                    self.channel.round_hint = round_idx
                    total = self._round_exchange(variables, round_idx,
                                                 train_rng, rng_base,
                                                 rounds)
                    variables, server_state, m = eng._twolevel_commit(
                        variables, server_state,
                        jax.numpy.asarray(total), agg_rng)
                jax.block_until_ready(variables)
                obs.counter("multihost_rounds_committed_total",
                            rank=str(self.ctx.rank)).inc()
                self.round_walls.append(time.perf_counter() - t0)
                self.channel.export_byte_counters()
                if self.ctx.rank == 0 and (
                        round_idx % cfg.frequency_of_the_test == 0
                        or round_idx == rounds - 1):
                    stats = eng.evaluate(variables)
                    stats.update(round=round_idx,
                                 train_loss=float(m["train_loss"]),
                                 round_time=self.round_walls[-1])
                    eng.metrics_history.append(stats)
                    if logger is not None:
                        logger.log(stats, step=round_idx)
                    log.info("round %d: %s", round_idx, stats)
                if self.on_round_end is not None:
                    self.on_round_end(round_idx)
        except Exception as e:
            obs.dump_flight(f"multihost_error:rank{self.ctx.rank}: "
                            f"{e!r}")
            raise
        finally:
            pre, self._prefetched = self._prefetched, None
            if pre is not None:
                try:
                    pre[1].result()
                except Exception:
                    pass
        self._rollup_metrics()
        return variables

    def _rollup_metrics(self) -> None:
        """Ship every rank's metric deltas to rank 0 and fold them under
        origin="host<i>" (the PR-7 remote-fold shape): an N-process run
        keeps per-process series instead of last-writer-wins gauges,
        and programs.report() gains its per-process breakdown rows from
        exactly these merged series.  The shipped delta is SINCE THE
        LAST ROLLUP in this process (baseline threaded like the PR-7
        uplink piggyback), so back-to-back runners — mh_worker's
        streaming-then-resident pair — don't re-ship and double-count
        the earlier run's counters."""
        if self.ctx.world <= 1:
            return
        try:
            self.channel.round_hint = None   # ledger: not a round barrier
            delta = _delta_since_last_rollup()
            docs = self.channel.allgather(
                json.dumps(delta).encode(), timeout_s=self.timeout_s)
            if self.ctx.rank == 0:
                for r, doc in enumerate(docs):
                    if r == 0 or not doc:
                        continue
                    obs.registry().merge_delta(json.loads(doc.decode()),
                                               origin=f"host{r}")
        except DeadRankError:
            raise
        except Exception:
            log.warning("multihost metrics rollup failed", exc_info=True)

    def report(self, warmup_rounds: int = 0) -> dict:
        """Timing/byte rollup over the rounds run so far (warmup rounds
        excluded from the rate)."""
        walls = self.round_walls[warmup_rounds:]
        carry = self.carry_bytes[warmup_rounds:] or [0]
        sent = self.carry_wire_sent[warmup_rounds:] or [0]
        raw = self.carry_raw[warmup_rounds:]
        payload = self.carry_payload[warmup_rounds:]
        waits = self.overlap_waits[warmup_rounds:]
        ewalls = self.exchange_walls[warmup_rounds:]
        return {
            "rank": self.ctx.rank,
            "world": self.ctx.world,
            "n_blocks": self.n_blocks,
            "rounds": len(self.round_walls),
            "rounds_per_sec": (len(walls) / sum(walls)
                               if walls and sum(walls) > 0 else 0.0),
            "round_wall_p50_s": (float(np.median(walls))
                                 if walls else 0.0),
            "carry_allreduce_bytes_per_round": float(np.mean(carry)),
            # sum of the per-round deltas, NOT channel.bytes_received:
            # the channel also carries handshake/rollup frames and (in
            # mh_worker) a sibling runner's traffic
            "carry_allreduce_bytes_total": int(sum(self.carry_bytes)),
            # -- compressed tier (ISSUE 16) --
            "carry_codec": self.codec.name,
            "carry_raw_bytes_per_round": (float(np.mean(raw))
                                          if raw else 0.0),
            "carry_payload_bytes_per_round": (float(np.mean(payload))
                                              if payload else 0.0),
            # payload-level ratio: deterministic per (codec, dim); the
            # channel-measured per-round deltas above price the framing
            "carry_compression_ratio": (sum(raw) / sum(payload)
                                        if sum(payload) else 1.0),
            "carry_wire_sent_bytes_per_round": float(np.mean(sent)),
            # fraction of the exchange window (first partial shipped →
            # folded carry ready) NOT spent blocking the round loop:
            # ~0 on the serial path, > 0 when --overlap_exchange hides
            # the DCN transfer behind block compute
            "overlap_fraction": (max(0.0, 1.0 - sum(waits)
                                     / sum(ewalls))
                                 if ewalls and sum(ewalls) > 0
                                 else 0.0),
        }

    def close(self) -> None:
        if self._channel is not None and self._owns_channel:
            self._channel.close()
            self._channel = None


class ElasticRunner(MultihostRunner):
    """Elastic twin of the two-level round loop (ISSUE 14): the same
    sample→partial→allreduce→commit structure, but the inter-host tier
    rides an ElasticChannel — a dead or hung rank triggers a view
    change, its blocks are re-adopted by the survivors mid-round, and a
    restarted process re-enters through the rejoin handshake (config
    digest + a rank-0 model snapshot at the commit barrier).

    Bitwise anchor under death, by construction: `BlockCohortSampler`
    draws on [seed, round, block] streams and every partial is a pure
    function of (variables, seed, round, block), so a re-adopted
    block's partial is byte-identical to the one the dead rank would
    have shipped; `fold_block_partials` folds ALL blocks in global
    block order regardless of who computed them — a run that loses a
    rank commits the same bits as the clean same-partition run
    (tests/test_multihost_spmd.py's elastic kill pin).

    Differences from the fail-fast runner, deliberate: resident mode
    caches PER-BLOCK device stacks (ownership is dynamic, so the
    contiguous whole-range stack no longer exists — every block's
    stack/gather compiles one shape, identical on every survivor set);
    the streaming path gathers synchronously (cross-round prefetch
    assumes static ownership); and the end-of-run metrics rollup is
    skipped (membership may change under it).  Fail-fast stays the
    default — this runner is opt-in via cli --elastic."""

    def __init__(self, engine, ctx: Optional[MultihostContext] = None,
                 *, n_blocks: Optional[int] = None,
                 channel: Optional[ElasticChannel] = None,
                 timeout_s: float = 120.0,
                 connect_timeout_s: float = 60.0,
                 hb_interval_s: float = 0.25,
                 hb_timeout_s: float = 2.0,
                 run_tag: str = "run",
                 carry_codec: str = "f32",
                 carry_chunk: Optional[int] = None,
                 overlap_exchange: bool = False,
                 on_round_end: Optional[Callable[[int], None]] = None):
        if channel is not None and not isinstance(channel,
                                                  ElasticChannel):
            raise ValueError(
                f"ElasticRunner needs an ElasticChannel (got "
                f"{type(channel).__name__}); use MultihostRunner for "
                f"the fail-fast HostChannel")
        super().__init__(engine, ctx, n_blocks=n_blocks,
                         channel=channel, timeout_s=timeout_s,
                         carry_codec=carry_codec,
                         carry_chunk=carry_chunk,
                         overlap_exchange=overlap_exchange,
                         on_round_end=on_round_end)
        self.connect_timeout_s = float(connect_timeout_s)
        self.hb_interval_s = float(hb_interval_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self.run_tag = str(run_tag)
        if channel is not None and channel.n_items != self.n_blocks:
            raise ValueError(
                f"channel n_items ({channel.n_items}) != n_blocks "
                f"({self.n_blocks}) — the block space is the reduction "
                f"tree and must agree")
        self._block_stacks: dict[int, tuple] = {}
        self._round_ctx: Optional[tuple] = None

    @property
    def channel(self) -> ElasticChannel:
        if self._channel is None:
            self._channel = ElasticChannel(
                self.ctx, n_items=self.n_blocks,
                config_digest=self.config_digest(),
                timeout_s=self.timeout_s,
                connect_timeout_s=self.connect_timeout_s,
                hb_interval_s=self.hb_interval_s,
                hb_timeout_s=self.hb_timeout_s,
                rejoin=os.environ.get("FEDML_MH_REJOIN") == "1")
        return self._channel

    def config_digest(self) -> str:
        return hashlib.md5(self._config_doc()).hexdigest()

    # -- per-block partials (ownership-agnostic) -----------------------------
    def _block_stack(self, b: int) -> tuple:
        """Resident mode, one block's device stack (cached): uniform
        [range_size→pad(n_shards)] shape for EVERY block, so any
        survivor adopting any block dispatches the same compiled
        program — and re-adoption costs one H2D upload, not a
        recompile."""
        hit = self._block_stacks.get(b)
        if hit is not None:
            return hit
        rs = self.sampler.range_size
        self._block_stacks[b] = self._upload_id_range(b * rs,
                                                      (b + 1) * rs)
        return self._block_stacks[b]

    def _compute_partials(self, variables, round_idx: int, train_rng,
                          blocks) -> dict[int, np.ndarray]:
        eng = self.engine
        parts: dict[int, np.ndarray] = {}
        for b in blocks:
            ids, wmask, crngs = self._block_inputs(round_idx, b,
                                                   train_rng)
            if eng.streaming:
                cohort, weights = eng._stream_gather(ids, wmask)
                flat = eng._twolevel_partial(variables, cohort, weights,
                                             jax.numpy.asarray(crngs))
            else:
                stack, stack_w = self._block_stack(b)
                local_ids = ids - b * self.sampler.range_size
                flat = eng._twolevel_partial_resident(
                    variables, stack, stack_w,
                    jax.numpy.asarray(local_ids),
                    jax.numpy.asarray(wmask), jax.numpy.asarray(crngs))
            parts[int(b)] = np.asarray(flat, dtype=np.float32)
        return parts

    def _readopt_compute(self, blocks) -> dict[int, bytes]:
        """The mid-round re-adoption callback the channel invokes on a
        view change: recompute the named blocks against THIS round's
        frozen (variables, train_rng) — pure functions, so the bytes
        match what the dead rank would have shipped."""
        if self._round_ctx is None:
            raise RuntimeError("re-adoption requested outside a round")
        variables, train_rng, round_idx = self._round_ctx
        with obs.span("multihost.readopt", round=round_idx,
                      blocks=len(tuple(blocks))):
            parts = self._compute_partials(variables, round_idx,
                                           train_rng, blocks)
        # re-adopted blocks ship through the SAME codec as owned ones
        # (the channel's uniform-item contract); an int8_ef residual
        # for a freshly adopted block starts at zero — compression
        # error trajectory only, never replica agreement
        return {b: self.codec.encode(int(b), v)
                for b, v in parts.items()}

    def _snapshot_blob(self, resume_round: int, variables,
                       server_state) -> bytes:
        """The rejoin catch-up snapshot: the committed model + server
        state as host numpy trees (byte-exact — the rejoiner must
        re-enter the bitwise contract, not an approximation of it).
        Cluster-internal trust boundary: this rides the same
        coordinator sockets as every carry frame."""
        tree = jax.tree.map(np.asarray, (variables, server_state))
        # stateful-codec state rides the snapshot (ISSUE 19): topk_ef's
        # reconstruction mirror is replicated decode state — a rejoiner
        # folding future rounds from a zero mirror would disagree with
        # every survivor.  (int8_ef residuals are encoder-local; the
        # rejoiner's retain_blocks() drops the coordinator's copies, so
        # shipping them preserves the restart-at-zero convention.)
        return pickle.dumps({"round": int(resume_round), "state": tree,
                             "carry": self.carry_state()},
                            protocol=4)

    # -- the elastic loop ----------------------------------------------------
    def run(self, variables=None, rounds: Optional[int] = None,
            logger=None, rejoin: Optional[bool] = None,
            rejoin_state: Optional[tuple] = None):
        """Drive the elastic two-level loop.  `rejoin=True` (defaulted
        from FEDML_MH_REJOIN — the launcher's respawn sets it) makes
        this process re-enter a running cluster: config-digest
        handshake, model snapshot install, resume at the coordinator's
        commit barrier.  `rejoin_state=(snapshot_blob, resume_round)`
        injects a handshake the caller already performed (mh_worker
        does its own so the SNAPSHOT's run tag can pick which runner to
        resume)."""
        eng = self.engine
        cfg = eng.cfg
        rounds = rounds if rounds is not None else cfg.comm_round
        if rejoin is None:
            rejoin = (os.environ.get("FEDML_MH_REJOIN") == "1"
                      and self.ctx.rank != 0)
        ch = self.channel
        if self.ctx.rank == 0:
            ch.wait_members()
        if rejoin or rejoin_state is not None:
            if rejoin_state is not None:
                blob, resume_round = rejoin_state
            else:
                blob, resume_round, tag = ch.rejoin_handshake()
                if tag and tag != self.run_tag:
                    log.warning(
                        "elastic rejoin: admitted into run %r but this "
                        "runner drives %r — resuming anyway (the "
                        "caller should route on the tag, see "
                        "mh_worker)", tag, self.run_tag)
            payload = pickle.loads(blob)
            variables, server_state = payload["state"]
            variables = eng._prepare_variables(variables)
            server_state = eng._prepare_server_state(server_state)
            # install the coordinator's codec state BEFORE the first
            # fold: a stateful sparse codec's reconstruction mirror
            # must match the survivors' bit-for-bit (ISSUE 19)
            self.load_carry_state(payload.get("carry"))
            start_round = int(payload["round"])
        else:
            if variables is None:
                variables = eng.init_variables()
            variables = eng._prepare_variables(variables)
            server_state = eng._prepare_server_state(
                eng.server_init(variables))
            start_round = 0
        rng_base = jax.random.PRNGKey(cfg.seed + 1)
        try:
            for round_idx in range(start_round, rounds):
                t0 = time.perf_counter()
                round_rng = jax.random.fold_in(rng_base, round_idx)
                train_rng, agg_rng = jax.random.split(round_rng)
                self._round_ctx = (variables, train_rng, round_idx)
                with obs.span("round.twolevel", round=round_idx,
                              rank=self.ctx.rank,
                              epoch=ch.view.epoch, elastic=True):
                    mine = ch.view.assigned(self.ctx.rank)
                    # drop resident stacks for blocks the view no
                    # longer assigns here (e.g. a rejoin returned them
                    # to their original owner) — without eviction,
                    # repeated death/rejoin cycles would converge on
                    # every host holding the WHOLE population in HBM,
                    # defeating the id-range partition
                    for b in list(self._block_stacks):
                        if b not in mine:
                            del self._block_stacks[b]
                    # error-feedback residuals follow ownership too
                    self.codec.retain_blocks(mine)
                    ch.mark_round()
                    self._round_raw = self._round_payload = 0
                    w0 = time.perf_counter()
                    if self.overlap_exchange and self.ctx.world > 1:
                        hnd = ch.contrib_begin(round_idx)
                        for b in mine:
                            part = self._compute_partials(
                                variables, round_idx, train_rng, [b])
                            ch.contrib_push(
                                hnd, b,
                                self._encode_block(b, part[int(b)]))
                        with obs.span("multihost.overlap_wait",
                                      round=round_idx), \
                             obs.span("multihost.allreduce",
                                      round=round_idx):
                            t0 = time.perf_counter()
                            all_parts, _view = ch.exchange(
                                round_idx, {}, self._readopt_compute,
                                pending=hnd)
                            wait = time.perf_counter() - t0
                        self.overlap_waits.append(wait)
                        self.exchange_walls.append(
                            time.perf_counter() - w0)
                    else:
                        parts = self._compute_partials(
                            variables, round_idx, train_rng, mine)
                        enc = {b: self._encode_block(b, v)
                               for b, v in parts.items()}
                        with obs.span("multihost.allreduce",
                                      round=round_idx):
                            t0 = time.perf_counter()
                            all_parts, _view = ch.exchange(
                                round_idx, enc, self._readopt_compute)
                            wait = time.perf_counter() - t0
                        self.overlap_waits.append(wait)
                        self.exchange_walls.append(wait)
                    self._finish_round_bytes()
                    total = self._decode_fold(all_parts)
                    variables, server_state, m = eng._twolevel_commit(
                        variables, server_state,
                        jax.numpy.asarray(total), agg_rng)
                jax.block_until_ready(variables)
                obs.counter("multihost_rounds_committed_total",
                            rank=str(self.ctx.rank)).inc()
                self._round_ctx = None
                self.round_walls.append(time.perf_counter() - t0)
                ch.export_byte_counters()
                if self.ctx.rank == 0:
                    # the commit barrier IS the admission point: the
                    # snapshot ships the just-committed bits
                    ch.admit_rejoins(
                        round_idx + 1,
                        lambda: self._snapshot_blob(
                            round_idx + 1, variables, server_state),
                        tag=self.run_tag)
                if self.ctx.rank == 0 and (
                        round_idx % cfg.frequency_of_the_test == 0
                        or round_idx == rounds - 1):
                    stats = eng.evaluate(variables)
                    stats.update(round=round_idx,
                                 train_loss=float(m["train_loss"]),
                                 round_time=self.round_walls[-1])
                    eng.metrics_history.append(stats)
                    if logger is not None:
                        logger.log(stats, step=round_idx)
                    log.info("round %d: %s", round_idx, stats)
                if self.on_round_end is not None:
                    self.on_round_end(round_idx)
        except Exception as e:
            obs.dump_flight(f"multihost_elastic_error:"
                            f"rank{self.ctx.rank}: {e!r}")
            raise
        finally:
            self._round_ctx = None
        return variables

    def report(self, warmup_rounds: int = 0) -> dict:
        rep = super().report(warmup_rounds)
        ch = self._channel
        events = list(ch.view_events) if ch is not None else []
        lat = [e["latency_s"] for e in events if e.get("latency_s")]
        rep.update({
            "elastic": True,
            "epoch": ch.view.epoch if ch is not None else 0,
            "members": list(ch.view.members) if ch is not None else [],
            "view_changes": len(events),
            "view_change_latency_s": (float(np.mean(lat)) if lat
                                      else 0.0),
            "view_events": events,
        })
        return rep


def variables_digest(variables) -> str:
    """md5 over the raw bytes of every leaf (deterministic leaf order)
    — THE bitwise-equality digest of the multihost pins."""
    h = hashlib.md5()
    for leaf in jax.tree.leaves(variables):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()
