"""Multi-host (DCN) runtime — bootstrap, host channel, and the
two-level round loop (ISSUE 13).

The reference scales across machines with `mpirun -np N -hostfile ...`
(run_fedavg_distributed_pytorch.sh:16-35) — one OS process per client rank
over MPI.  TPU-native, multi-host is one SPMD program: every host runs the
same code, `jax.distributed.initialize` wires the hosts into a single
runtime, and `jax.devices()` becomes the global chip list.  The engines in
parallel/ are already global-view (shard_map over a Mesh, device_put with
NamedShardings), so they run unchanged on a multi-host mesh — XLA routes
in-slice collectives over ICI and cross-slice traffic over DCN.

ISSUE 13 adds the runnable-today runtime on top of that seam, following
the MLPerf pod recipe (arXiv:1909.09756 — per-host input pipelines,
hierarchical gradient reduction) mapped onto FedML's hierarchical
aggregation (arXiv:2007.13518):

* `MultihostContext` / `spawn_cluster` / `tools/launch_multihost.py` —
  a multi-process launcher: N OS processes wired by env
  (`FEDML_MH_RANK/WORLD/COORD`), optionally joined into one jax runtime
  via `init_multihost` (`FEDML_MH_JAX_COORD`; on TPU pods this is what
  makes the local chips visible).
* `HostChannel` — the DCN tier executed for real: a tiny TCP
  coordinator (rank 0) carrying the P-sized flat f32 carry between
  hosts.  On the CPU dev box this stands in for gloo/DCN; it needs NO
  backend collective support, which is what makes the runtime runnable
  on jaxlib builds whose CPU backend lacks cross-process computations
  (the 0.4.x line — see tests/test_multihost_spmd.py's version gate on
  the in-program gloo path).  Every wait is BOUNDED: a dead or hung
  rank raises `DeadRankError` NAMING the rank instead of hanging the
  cluster.
* `MultihostRunner` — the two-level round loop: intra-host psum over
  the flat f32 carry on the LOCAL mesh (the engine's new
  `{family}_twolevel` partial program, ICI tier), then an inter-host
  allreduce of the P-sized per-block partials over the HostChannel
  (DCN tier), then a replicated commit (`twolevel_commit` program) on
  every host.

Bitwise anchor (the pin that anchors this subsystem, like the reactor
and async ones): the reduction tree is a function of the BLOCK
PARTITION, not the process count.  The cohort is sampled per block
from fixed population ranges (`BlockCohortSampler`, rng streams keyed
[seed, round, block]), each block's partial is one compiled program on
a same-shaped local mesh, and every host folds ALL block partials in
global block order.  Any process count that tiles the same blocks
therefore commits bitwise-identically — `n_blocks=2` at 1 process and
at 2 processes produce the same bits (tests/test_multihost_spmd.py).
This is STRONGER than an in-program psum can promise (a topology
change reorders XLA's reduction ring).

Mesh layout guidance (the scaling-book recipe): put the axis with the
heaviest collective traffic (the client/cohort axis — its psum moves the
whole model) INSIDE a slice so it rides ICI; put the hierarchical silo
axis across slices so only the second-tier reduction crosses DCN —
`make_hierarchical_host_mesh` encodes exactly that on top of
mesh.make_mesh_2d.

IMPORTANT: init_multihost() must run before ANY jax call that initializes
the XLA backend (so: first thing in main) — jax.distributed.initialize
refuses to run afterwards.

Streaming/prefetch note (parallel/prefetch.py): the streaming and
block-stream paths' background upload thread is PER PROCESS, and every
process runs the same round loop, so the prefetchers issue their
`jax.device_put(..., NamedSharding)` calls in the same order on every
host — each process materializes only its addressable shards, and the
upload/compute overlap composes across hosts (each host hides its own
gather+DMA behind its chips' compute).  The block-streamed
order-statistic defenses remain single-process (enforced at engine
construction): their host [K, P] offload needs every client shard
addressable.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from fedml_tpu import obs
from fedml_tpu.parallel.mesh import CLIENT_AXIS, make_mesh, make_mesh_2d

log = logging.getLogger(__name__)

ENV_RANK = "FEDML_MH_RANK"
ENV_WORLD = "FEDML_MH_WORLD"
ENV_COORD = "FEDML_MH_COORD"           # host:port of the HostChannel
ENV_JAX_COORD = "FEDML_MH_JAX_COORD"   # host:port for jax.distributed


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   required: bool = False) -> None:
    """Join this host into the global runtime (idempotent).

    With no arguments, relies on the cluster's auto-detection (TPU pods
    expose the coordinator via metadata) and degrades gracefully to
    single-process mode on a dev box.  With EXPLICIT arguments — or
    required=True (the CLI's --multihost sets it) — a failure raises:
    silently training independent single-host replicas would corrupt the
    run.  Replaces the reference's mpirun/hostfile bootstrap."""
    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:              # older jax: no is_initialized
        pass
    explicit = (required or coordinator_address is not None
                or num_processes is not None or process_id is not None)
    try:
        # CPU cross-process collectives need a transport; without one the
        # global mesh forms but the first psum fails.  Current jaxlib
        # defaults the option to "gloo" (test_multihost_spmd runs over
        # it); this fallback covers builds whose default is unset/"none".
        # It must happen BEFORE initialize, and without probing the
        # platform — that would initialize the backend, which
        # jax.distributed.initialize forbids (see module docstring) — so
        # the option is set whenever it is not already configured (it
        # only affects the cpu backend; TPU pods use ICI/DCN natively).
        # getattr's default covers the older-jaxlib option-absent case
        # (cur = "absent" skips the update); a FAILING update on a jaxlib
        # that HAS the option is a real configuration error and must not
        # be swallowed — deferring it to the first cross-process psum
        # yields a much worse message
        cur = getattr(jax.config,
                      "jax_cpu_collectives_implementation", "absent")
        if cur in (None, "", "none"):
            # unset/disabled only (this jaxlib's default is already
            # "gloo"): an operator's explicit transport choice (env
            # JAX_CPU_COLLECTIVES_IMPLEMENTATION=mpi or a prior
            # config.update) must win
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        log.info("multihost: process %d/%d, %d global devices",
                 jax.process_index(), jax.process_count(),
                 len(jax.devices()))
    except Exception as e:
        if explicit:
            raise RuntimeError(
                f"multi-host initialization failed for coordinator "
                f"{coordinator_address!r}: {e}") from e
        log.info("multihost init skipped (%s); single-process mode", e)


def make_global_mesh(axis_name: str = CLIENT_AXIS) -> Mesh:
    """1-D mesh over ALL chips of ALL hosts — the cohort axis spans the
    pod; psum rides ICI within a slice and DCN across."""
    return make_mesh(axis_name=axis_name)


def make_local_mesh(axis_name: str = CLIENT_AXIS) -> Mesh:
    """1-D mesh over THIS process's chips only — the intra-host tier of
    the two-level aggregation (MultihostRunner requires a local-only
    mesh: its cross-host traffic is the HostChannel carry exchange, not
    in-program collectives)."""
    return make_mesh(axis_name=axis_name, devices=jax.local_devices())


def make_hierarchical_host_mesh(silos: Optional[int] = None) -> Mesh:
    """2-D (silo × clients) mesh with one silo per host by default: the
    inner FedAvg psum stays on each host's ICI, only the per-silo means
    cross DCN — the two-tier reduction of hierarchical FL mapped onto the
    physical network (SURVEY.md §2.5 'hierarchical aggregation').

    VIRTUAL-SILO semantics (single process, silos>1): with only one
    process there is no host boundary to place the silo tier on — the
    requested silo rows are carved out of THIS host's devices, so the
    "DCN tier" is simulated on local links.  That is the intended
    dev/test topology (the virtual-CPU oracles in
    tests/multihost_case.py rely on it), but it measures NOTHING about
    cross-host cost — a loud warning says so, because on a real pod the
    same call with one process per host is the genuine two-tier layout
    and silently accepting the single-process shape has masked
    misconfigured launches (ISSUE 13 satellite)."""
    devs = jax.devices()
    procs = max(jax.process_count(), 1)
    silos = silos or procs
    if len(devs) % silos != 0:
        raise ValueError(f"{len(devs)} devices not divisible into "
                         f"{silos} silos")
    if procs == 1 and silos > 1:
        log.warning(
            "make_hierarchical_host_mesh: building %d VIRTUAL silos on a "
            "single process — every silo row shares this host's devices, "
            "so the cross-silo tier rides local links, not DCN.  This is "
            "the dev/test topology (virtual-CPU oracles); on a pod, "
            "launch one process per host so the silo tier really crosses "
            "hosts.", silos)
    # global device order is NOT guaranteed host-contiguous; sort by
    # process so each silo row really sits on one host's ICI
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    return make_mesh_2d(n_silos=silos, devices=devs)


# ---------------------------------------------------------------------------
# process context + cluster spawning
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MultihostContext:
    """One process's place in the launched cluster (env-carried so any
    entry point — cli, bench worker, test worker — resolves the same
    way)."""
    rank: int
    world: int
    coordinator: str                    # "host:port" of the HostChannel
    jax_coordinator: Optional[str] = None   # jax.distributed, when wired

    @classmethod
    def from_env(cls) -> Optional["MultihostContext"]:
        if ENV_RANK not in os.environ or ENV_WORLD not in os.environ:
            return None
        world = int(os.environ[ENV_WORLD])
        rank = int(os.environ[ENV_RANK])
        if not 0 <= rank < world:
            raise ValueError(f"{ENV_RANK}={rank} outside world "
                             f"{world}")
        return cls(rank=rank, world=world,
                   coordinator=os.environ.get(ENV_COORD,
                                              "localhost:0"),
                   jax_coordinator=os.environ.get(ENV_JAX_COORD))

    @classmethod
    def single(cls) -> "MultihostContext":
        return cls(rank=0, world=1, coordinator="localhost:0")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


class MultihostLaunchError(RuntimeError):
    """A launched rank failed/hung; the message names it."""


def spawn_cluster(cmd: list[str], procs: int, *,
                  env: Optional[dict] = None,
                  timeout_s: float = 600.0,
                  jax_distributed: bool = False,
                  echo: bool = False,
                  coordinator_host: str = "localhost") -> list[str]:
    """Fork `procs` copies of `cmd` wired as one multihost cluster (env
    FEDML_MH_RANK/WORLD/COORD [+ FEDML_MH_JAX_COORD with
    jax_distributed]); returns each rank's stdout, rank-ordered.

    Failure policy: the first rank to exit nonzero kills the rest and
    raises MultihostLaunchError NAMING that rank (with its stderr
    tail); a deadline overrun kills everything and names the ranks
    still running.  `echo` streams child stderr line-prefixed
    (`[rank i]`) for interactive launches."""
    if procs < 1:
        raise ValueError(f"procs must be >= 1, got {procs}")
    if not cmd:
        raise ValueError("empty worker command")
    coord = f"{coordinator_host}:{free_port()}"
    base_env = {**os.environ, **(env or {}),
                ENV_WORLD: str(procs), ENV_COORD: coord}
    if jax_distributed:
        base_env[ENV_JAX_COORD] = f"{coordinator_host}:{free_port()}"
    ps = []
    for r in range(procs):
        e = dict(base_env)
        e[ENV_RANK] = str(r)
        ps.append(subprocess.Popen(cmd, env=e, text=True,
                                   stdout=subprocess.PIPE,
                                   stderr=subprocess.PIPE))
    outs: list = [None] * procs
    errs: list = [None] * procs

    def _drain(i):
        buf_out, buf_err = [], []

        def _pump(stream, buf, is_err):
            for line in stream:
                buf.append(line)
                if echo and is_err:
                    # stderr streams live (progress/tracebacks); stdout
                    # is returned buffered so machine-readable lines
                    # stay contiguous per rank
                    print(f"[rank {i}] {line}", end="", file=sys.stderr,
                          flush=True)
        t_err = threading.Thread(target=_pump,
                                 args=(ps[i].stderr, buf_err, True))
        t_err.start()
        _pump(ps[i].stdout, buf_out, False)
        t_err.join()
        outs[i], errs[i] = "".join(buf_out), "".join(buf_err)

    drains = [threading.Thread(target=_drain, args=(i,))
              for i in range(procs)]
    for t in drains:
        t.start()
    deadline = time.monotonic() + timeout_s
    first_failed: Optional[int] = None
    try:
        while True:
            live = [i for i, p in enumerate(ps) if p.poll() is None]
            failed = [i for i, p in enumerate(ps)
                      if p.poll() is not None and p.returncode != 0]
            if failed and first_failed is None:
                first_failed = failed[0]
            if failed or not live:
                break
            if time.monotonic() > deadline:
                for p in ps:
                    if p.poll() is None:
                        p.kill()
                raise MultihostLaunchError(
                    f"multihost launch timed out after {timeout_s:.0f}s: "
                    f"rank(s) {live} still running (of {procs})")
            time.sleep(0.05)
        if failed:
            # give survivors a short grace (a dead peer's channel EOF
            # usually fails them promptly with their OWN named error),
            # then kill
            grace = time.monotonic() + 5.0
            while (time.monotonic() < grace
                   and any(p.poll() is None for p in ps)):
                time.sleep(0.05)
            for p in ps:
                if p.poll() is None:
                    p.kill()
    finally:
        for t in drains:
            t.join()
    bad = [i for i, p in enumerate(ps) if p.returncode != 0]
    if bad:
        # blame the FIRST rank observed failing (the injected/original
        # fault), not a survivor that died of the resulting channel EOF
        i = first_failed if first_failed in bad else bad[0]
        tail = (errs[i] or "")[-3000:]
        raise MultihostLaunchError(
            f"multihost rank {i}/{procs} failed first "
            f"(rc={ps[i].returncode}; {len(bad)}/{procs} ranks "
            f"failed):\n{tail}")
    return [o or "" for o in outs]


# ---------------------------------------------------------------------------
# HostChannel — the DCN tier, executed for real
# ---------------------------------------------------------------------------

class DeadRankError(RuntimeError):
    """A peer rank died or stalled past the bounded channel timeout; the
    message names it (the crash-of-one-process acceptance case)."""


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


class HostChannel:
    """Small-payload allgather/barrier between the cluster's processes —
    the inter-host (DCN) tier of the two-level aggregation, carrying the
    P-sized flat f32 carry partials.

    Star topology: rank 0 coordinates (gathers every rank's payload,
    broadcasts the rank-ordered list).  Deliberately NOT a ring: the
    payloads are O(P) model-carry vectors, tiny next to the cohort data
    that never crosses processes, and a star gives every failure a
    single observer that can NAME the dead rank.  All waits are bounded
    (`timeout_s`): a dead peer raises DeadRankError naming it instead
    of hanging the round loop (the PR-8 crash lesson, applied to the
    cluster tier).  Byte/time accounting lands in
    multihost_bytes_sent/received_total and multihost_allgather_seconds
    (the bench's carry-allreduce bytes read)."""

    def __init__(self, ctx: MultihostContext, *,
                 timeout_s: float = 120.0,
                 connect_timeout_s: float = 60.0):
        self.ctx = ctx
        self.timeout_s = float(timeout_s)
        self.bytes_sent = 0
        self.bytes_received = 0
        self._seq = 0
        self._peers: dict[int, socket.socket] = {}
        self._sock: Optional[socket.socket] = None
        self._listener: Optional[socket.socket] = None
        if ctx.world <= 1:
            return
        host, port = ctx.coordinator.rsplit(":", 1)
        port = int(port)
        if ctx.rank == 0:
            self._listener = socket.create_server((host, port))
            self._listener.settimeout(connect_timeout_s)
            deadline = time.monotonic() + connect_timeout_s

            def _setup_dead(reason: str):
                missing = sorted(set(range(1, ctx.world))
                                 - set(self._peers))
                for s in self._peers.values():
                    s.close()
                self._listener.close()
                raise DeadRankError(
                    f"multihost channel setup: rank(s) {missing} "
                    f"{reason} within {connect_timeout_s:.0f}s")

            while len(self._peers) < ctx.world - 1:
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    conn = None
                if conn is None or time.monotonic() > deadline:
                    _setup_dead("never connected")
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # accepted sockets are BLOCKING regardless of the
                # listener's timeout — bound the rank handshake too, or
                # a connected-but-stalled peer hangs setup unboundedly
                conn.settimeout(max(0.001, deadline - time.monotonic()))
                try:
                    (r,) = struct.unpack("<I", _recv_exact(conn, 4))
                except (socket.timeout, ConnectionError, OSError):
                    conn.close()
                    _setup_dead("connected but never sent a rank "
                                "handshake")
                self._peers[r] = conn
        else:
            deadline = time.monotonic() + connect_timeout_s
            last_err: Optional[Exception] = None
            while True:
                try:
                    self._sock = socket.create_connection(
                        (host, port), timeout=5.0)
                    break
                except OSError as e:
                    last_err = e
                    if time.monotonic() > deadline:
                        raise DeadRankError(
                            f"multihost channel setup: rank {ctx.rank} "
                            f"could not reach the rank-0 coordinator at "
                            f"{ctx.coordinator} within "
                            f"{connect_timeout_s:.0f}s: {e}") from e
                    time.sleep(0.1)
            del last_err
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._sock.sendall(struct.pack("<I", ctx.rank))

    # -- collective ops ------------------------------------------------------
    def allgather(self, payload: bytes,
                  timeout_s: Optional[float] = None) -> list[bytes]:
        """Every rank contributes `payload`; every rank receives the
        rank-ordered list.  Bounded: a silent rank raises DeadRankError
        naming it."""
        t0 = time.perf_counter()
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        self._seq += 1
        ctx = self.ctx
        if ctx.world <= 1:
            return [payload]
        deadline = time.monotonic() + timeout
        try:
            if ctx.rank == 0:
                parts: list[Optional[bytes]] = [None] * ctx.world
                parts[0] = payload
                for r in sorted(self._peers):
                    sock = self._peers[r]
                    sock.settimeout(max(0.001,
                                        deadline - time.monotonic()))
                    try:
                        parts[r] = _recv_frame(sock)
                    except (socket.timeout, ConnectionError, OSError) as e:
                        missing = sorted(r2 for r2 in range(1, ctx.world)
                                         if parts[r2] is None)
                        raise DeadRankError(
                            f"multihost allgather #{self._seq}: no "
                            f"payload from rank(s) {missing} within "
                            f"{timeout:.0f}s ({type(e).__name__}: "
                            f"process dead or hung)") from e
                    self.bytes_received += len(parts[r])
                blob = struct.pack("<I", ctx.world) + b"".join(
                    struct.pack("<Q", len(p)) + p for p in parts)
                for r in sorted(self._peers):
                    try:
                        _send_frame(self._peers[r], blob)
                    except (socket.timeout, ConnectionError, OSError) as e:
                        raise DeadRankError(
                            f"multihost allgather #{self._seq}: "
                            f"broadcast to rank {r} failed "
                            f"({type(e).__name__}: rank died after "
                            f"contributing)") from e
                    self.bytes_sent += len(blob) + 8
                return list(parts)          # type: ignore[arg-type]
            # non-root: ship ours, await the broadcast.  Reset the
            # send-side timeout first — settimeout() PERSISTS on the
            # socket, so without this the send runs under whatever
            # near-expired recv deadline the previous allgather left
            self._sock.settimeout(max(0.001,
                                      deadline - time.monotonic()))
            try:
                _send_frame(self._sock, payload)
            except (socket.timeout, ConnectionError, OSError) as e:
                raise DeadRankError(
                    f"multihost allgather #{self._seq}: rank {ctx.rank} "
                    f"could not ship its payload to the rank-0 "
                    f"coordinator ({type(e).__name__}: coordinator dead "
                    f"or backpressured past {timeout:.0f}s)") from e
            self.bytes_sent += len(payload) + 8
            self._sock.settimeout(max(0.001, deadline - time.monotonic()))
            try:
                blob = _recv_frame(self._sock)
            except (socket.timeout, ConnectionError, OSError) as e:
                raise DeadRankError(
                    f"multihost allgather #{self._seq}: rank {ctx.rank} "
                    f"got no broadcast from the rank-0 coordinator "
                    f"within {timeout:.0f}s ({type(e).__name__}: "
                    f"coordinator dead, or a peer stalled it)") from e
            self.bytes_received += len(blob)
            (world,) = struct.unpack_from("<I", blob, 0)
            off, parts = 4, []
            for _ in range(world):
                (n,) = struct.unpack_from("<Q", blob, off)
                off += 8
                parts.append(blob[off:off + n])
                off += n
            return parts
        finally:
            obs.histogram("multihost_allgather_seconds").observe(
                time.perf_counter() - t0)

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        self.allgather(b"", timeout_s=timeout_s)

    def export_byte_counters(self) -> None:
        """Publish the cumulative byte counters as obs metrics (called
        at round boundaries — the counters themselves stay cheap plain
        ints on the hot path)."""
        r = str(self.ctx.rank)
        sent = obs.counter("multihost_bytes_sent_total", rank=r)
        recv = obs.counter("multihost_bytes_received_total", rank=r)
        sent.inc(max(0.0, self.bytes_sent - sent.value))
        recv.inc(max(0.0, self.bytes_received - recv.value))

    def close(self) -> None:
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
        self._peers.clear()
        for s in (self._sock, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = self._listener = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# topology-independent block sampling
# ---------------------------------------------------------------------------

class BlockCohortSampler:
    """Per-block cohort sampling over fixed population ranges — the
    sampling half of the bitwise anchor.

    The population [0, C) splits into `n_blocks` contiguous ranges (the
    PR-10 registry/shardstore id-range partition, applied to the
    cohort); block b draws `k_per_block` clients without replacement
    from ITS range on a private `default_rng([seed, round, block])`
    stream.  Every quantity is a pure function of (seed, round, block)
    — NOT of which process computes it — so any topology tiling the
    same blocks samples the same cohort (and the draw is
    background-thread-safe: no global-RNG reseed, the PR-10 lesson)."""

    def __init__(self, population: int, n_blocks: int, k_per_block: int,
                 seed: int):
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        if population % n_blocks:
            raise ValueError(
                f"population ({population}) must divide evenly into "
                f"{n_blocks} blocks (the id-range partition must be "
                f"topology-independent)")
        self.population = int(population)
        self.n_blocks = int(n_blocks)
        self.range_size = population // n_blocks
        if not 1 <= k_per_block <= self.range_size:
            raise ValueError(
                f"k_per_block ({k_per_block}) must be in [1, "
                f"{self.range_size}] (each block samples within its "
                f"{self.range_size}-client range)")
        self.k_per_block = int(k_per_block)
        self.seed = int(seed)

    def sample_block(self, round_idx: int, block: int) -> np.ndarray:
        """Global client ids of block `block`'s round-`round_idx`
        cohort, sorted ascending (a canonical order so every topology
        builds the identical cohort stack)."""
        if not 0 <= block < self.n_blocks:
            raise ValueError(f"block {block} outside [0, "
                             f"{self.n_blocks})")
        lo = block * self.range_size
        if self.k_per_block == self.range_size:
            return np.arange(lo, lo + self.range_size, dtype=np.int64)
        rng = np.random.default_rng(
            [self.seed, int(round_idx), int(block)])
        ids = rng.choice(self.range_size, size=self.k_per_block,
                         replace=False)
        return np.sort(ids).astype(np.int64) + lo


def fold_block_partials(parts: dict[int, np.ndarray],
                        n_blocks: int) -> np.ndarray:
    """THE deterministic inter-host reduction: left-fold the per-block
    f32 partials in GLOBAL BLOCK ORDER.  Identical on every host and
    for every topology that produced the same blocks — float addition
    is not associative, so the fold order is the contract (never
    tree-reduce here without changing the bitwise anchor)."""
    missing = [b for b in range(n_blocks) if b not in parts]
    if missing:
        raise DeadRankError(
            f"two-level fold: block partial(s) {missing} missing from "
            f"the allgather (owning rank dead mid-round?)")
    total = np.array(parts[0], dtype=np.float32, copy=True)
    for b in range(1, n_blocks):
        total += np.asarray(parts[b], dtype=np.float32)
    return total


# ---------------------------------------------------------------------------
# the two-level round loop
# ---------------------------------------------------------------------------

# per-PROCESS metrics-rollup baseline: (registry identity, prev state).
# Keyed on the registry object so obs.reset() (tests) naturally resets
# the baseline with it.
_rollup_state: Optional[tuple] = None


def _delta_since_last_rollup() -> dict:
    global _rollup_state
    reg = obs.registry()
    prev = (_rollup_state[1]
            if _rollup_state is not None and _rollup_state[0] is reg
            else None)
    delta, state = reg.delta_snapshot(prev)
    _rollup_state = (reg, state)
    return delta


class MultihostRunner:
    """Two-level multihost round loop over a FedAvg-family mesh engine.

    Per round, on every process:

      1. sample: `BlockCohortSampler` draws each block's cohort from its
         population range — pure function of (seed, round, block);
      2. partial (ICI tier): for each OWNED block (contiguous tiling:
         rank r owns blocks [r·B/W, (r+1)·B/W)), gather+upload the
         block cohort (host-sharded data: only this process's blocks
         cross H2D; double-buffered per-host prefetch on the streaming
         path) and run the engine's `{family}_twolevel` partial program
         — chunk-scanned local training + intra-host psum on the LOCAL
         mesh, returning the flat f32 carry;
      3. allreduce (DCN tier): `HostChannel.allgather` of the owned
         partials, then EVERY process folds all B partials in global
         block order (`fold_block_partials`);
      4. commit: the replicated `twolevel_commit` program divides and
         applies the server update identically on every process.

    Bitwise anchor: with a fixed `n_blocks`, same-seed runs at ANY
    process count that tiles the blocks commit identical bits (the
    2-vs-1-process pin in tests/test_multihost_spmd.py).  Resident
    mode uploads only this process's population range to device;
    streaming mode uploads only its blocks' cohorts per round —
    nothing population-sized crosses process boundaries either way."""

    def __init__(self, engine, ctx: Optional[MultihostContext] = None,
                 *, n_blocks: Optional[int] = None,
                 channel: Optional[HostChannel] = None,
                 timeout_s: float = 120.0,
                 on_round_end: Optional[Callable[[int], None]] = None):
        from fedml_tpu.parallel.engine import MeshFedAvgEngine
        from fedml_tpu.parallel.hierarchical import MeshHierarchicalEngine
        if (not isinstance(engine, MeshFedAvgEngine)
                or isinstance(engine, MeshHierarchicalEngine)):
            # hierarchical subclasses the FedAvg engine but its rounds
            # are group_comm_round-structured — folding its sums flat
            # here would SILENTLY compute plain FedAvg instead (its
            # multihost story is the silo-per-host mesh above)
            raise ValueError(
                f"MultihostRunner drives the flat FedAvg-family mesh "
                f"engines, not {type(engine).__name__}")
        if engine.stream_block is not None:
            raise ValueError(
                "MultihostRunner does not drive block-streamed rounds "
                "yet: stream WITHIN a host via smaller blocks, or use "
                "streaming mode (per-block cohorts already bound device "
                "memory by O(block))")
        if getattr(engine, "defense", "norm_clip") not in ("norm_clip",):
            raise ValueError(
                f"two-level aggregation is linear: order-statistic "
                f"defense {engine.defense!r} cannot fold across hosts "
                f"(its [K, P] matrix needs every client row)")
        # the engine's mesh must be process-local: the cross-host tier
        # is the HostChannel, never an in-program collective
        for d in engine.mesh.devices.flat:
            if d.process_index != jax.process_index():
                raise ValueError(
                    "MultihostRunner needs a LOCAL mesh (build the "
                    "engine with make_local_mesh()): device "
                    f"{d} belongs to process {d.process_index}")
        self.engine = engine
        self.ctx = ctx if ctx is not None else (
            MultihostContext.from_env() or MultihostContext.single())
        self.timeout_s = float(timeout_s)
        self.on_round_end = on_round_end
        world = self.ctx.world
        self.n_blocks = int(n_blocks) if n_blocks else world
        if self.n_blocks % world:
            raise ValueError(
                f"n_blocks ({self.n_blocks}) must be a multiple of the "
                f"process count ({world}) — contiguous tiling is the "
                f"bitwise contract")
        cfg = engine.cfg
        if cfg.client_num_per_round % self.n_blocks:
            raise ValueError(
                f"client_num_per_round ({cfg.client_num_per_round}) "
                f"must divide evenly into {self.n_blocks} blocks")
        self.sampler = BlockCohortSampler(
            engine.data.client_num, self.n_blocks,
            cfg.client_num_per_round // self.n_blocks, cfg.seed)
        bpp = self.n_blocks // world
        self.owned_blocks = tuple(range(self.ctx.rank * bpp,
                                        (self.ctx.rank + 1) * bpp))
        # this process's population id range (contiguous because its
        # blocks are) — the resident device stack holds ONLY this slice
        self.range_lo = self.owned_blocks[0] * self.sampler.range_size
        self.range_hi = ((self.owned_blocks[-1] + 1)
                         * self.sampler.range_size)
        self._channel = channel
        self._owns_channel = channel is None
        self._range_stack = None
        self._range_stack_w = None
        self._prefetched = None
        self.round_walls: list[float] = []
        self.carry_bytes: list[int] = []
        engine._ensure_twolevel()

    # -- setup ---------------------------------------------------------------
    @property
    def channel(self) -> HostChannel:
        if self._channel is None:
            self._channel = HostChannel(self.ctx,
                                        timeout_s=self.timeout_s)
        return self._channel

    def _handshake(self) -> None:
        """Cross-rank config agreement: the bitwise contract only holds
        when every process runs the identical partition and programs —
        a mismatch names the ranks instead of silently diverging."""
        eng = self.engine
        doc = json.dumps({
            "n_blocks": self.n_blocks,
            "k_per_block": self.sampler.k_per_block,
            "population": self.sampler.population,
            "n_shards": eng.n_shards,
            "chunk": eng.chunk,
            "seed": eng.cfg.seed,
            "family": eng.program_family,
            "streaming": bool(eng.streaming),
        }, sort_keys=True).encode()
        docs = self.channel.allgather(doc, timeout_s=self.timeout_s)
        for r, d in enumerate(docs):
            if d != docs[0]:
                raise RuntimeError(
                    f"multihost config mismatch: rank {r} runs "
                    f"{d.decode()!r} vs rank 0's {docs[0].decode()!r} — "
                    f"the two-level reduction would not be bitwise")

    # -- per-round pieces ----------------------------------------------------
    def _block_inputs(self, round_idx: int, block: int, train_rng):
        """(global ids, wmask, crngs) for one block — all pure functions
        of (seed, round, block)."""
        from fedml_tpu.parallel.engine import pad_ids
        ids, wmask = pad_ids(self.sampler.sample_block(round_idx, block),
                             self.engine.n_shards)
        block_rng = jax.random.fold_in(train_rng, block)
        crngs = np.asarray(jax.random.split(block_rng, len(ids)))
        return ids, wmask, crngs

    def _upload_range_stack(self):
        """Resident mode: upload THIS process's population id range
        once, sharded over the local mesh (device residency is
        id-range-partitioned across hosts — the registry/shardstore
        partition, applied to HBM)."""
        if self._range_stack is not None:
            return self._range_stack, self._range_stack_w
        from fedml_tpu.parallel.mesh import (client_sharding, pad_cohort,
                                             shard_stack)
        eng = self.engine
        lo, hi = self.range_lo, self.range_hi
        shards = {k: np.asarray(v)[lo:hi]
                  for k, v in eng._host_shards().items()}
        weights = np.asarray(eng.data.client_num_samples,
                             np.float32)[lo:hi]
        shards, weights = pad_cohort(eng._cast_stack_x(shards), weights,
                                     eng.n_shards)
        eng.transfer_stats.add_h2d_bytes(
            sum(np.asarray(v).nbytes for v in shards.values())
            + weights.nbytes)
        self._range_stack = shard_stack(eng.mesh, shards)
        self._range_stack_w = jax.device_put(
            weights.astype(np.float32), client_sharding(eng.mesh))
        return self._range_stack, self._range_stack_w

    def _gather_streaming(self, round_idx: int, train_rng):
        """Host-gather + upload every OWNED block's cohort (the per-host
        input pipeline; runs on the prefetch thread when pipelined)."""
        out = []
        for b in self.owned_blocks:
            ids, wmask, crngs = self._block_inputs(round_idx, b,
                                                   train_rng)
            cohort, weights = self.engine._stream_gather(ids, wmask)
            out.append((b, cohort, weights, crngs))
        return out

    def _partials_resident(self, variables, round_idx: int, train_rng):
        eng = self.engine
        stack, stack_w = self._upload_range_stack()
        parts = {}
        for b in self.owned_blocks:
            ids, wmask, crngs = self._block_inputs(round_idx, b,
                                                   train_rng)
            local_ids = ids - self.range_lo
            flat = eng._twolevel_partial_resident(
                variables, stack, stack_w, jax.numpy.asarray(local_ids),
                jax.numpy.asarray(wmask), jax.numpy.asarray(crngs))
            parts[b] = np.asarray(flat, dtype=np.float32)
        return parts

    def _partials_streaming(self, variables, round_idx: int, train_rng,
                            rng_base, rounds: int):
        """Streaming partials with the per-host double-buffered
        prefetch: round r+1's gather+upload runs on a background thread
        while round r computes (parallel/prefetch.py AsyncValue — the
        engines' own pipeline, reused per host)."""
        from fedml_tpu.parallel.prefetch import AsyncValue
        eng = self.engine
        pre = self._prefetched
        if pre is not None and pre[0] == round_idx:
            blocks = pre[1].result()
        else:
            if pre is not None:
                try:
                    pre[1].result()
                except Exception:
                    log.warning("discarding failed stale multihost "
                                "prefetch for round %d", pre[0],
                                exc_info=True)
            blocks = self._gather_streaming(round_idx, train_rng)
        self._prefetched = None
        if eng.prefetch and round_idx + 1 < rounds:
            nxt_rng = jax.random.split(
                jax.random.fold_in(rng_base, round_idx + 1))[0]
            self._prefetched = (
                round_idx + 1,
                AsyncValue(self._gather_streaming, round_idx + 1,
                           nxt_rng, stats=eng.transfer_stats))
        parts = {}
        for b, cohort, weights, crngs in blocks:
            flat = eng._twolevel_partial(variables, cohort, weights,
                                         jax.numpy.asarray(crngs))
            parts[b] = np.asarray(flat, dtype=np.float32)
        return parts

    def _allreduce(self, parts: dict[int, np.ndarray]) -> np.ndarray:
        """Inter-host carry allreduce: ship owned block partials (block
        order, f32 LE), receive everyone's, fold in global block
        order."""
        payload = b"".join(parts[b].tobytes()
                           for b in sorted(parts))
        rx0 = self.channel.bytes_received
        docs = self.channel.allgather(payload, timeout_s=self.timeout_s)
        self.carry_bytes.append(self.channel.bytes_received - rx0)
        world = self.ctx.world
        bpp = self.n_blocks // world
        dim = next(iter(parts.values())).size
        all_parts: dict[int, np.ndarray] = {}
        for r, doc in enumerate(docs):
            if len(doc) != bpp * dim * 4:
                raise DeadRankError(
                    f"two-level allreduce: rank {r} shipped "
                    f"{len(doc)} bytes, expected {bpp * dim * 4} "
                    f"({bpp} blocks x {dim} f32) — config skew or a "
                    f"truncated frame")
            vecs = np.frombuffer(doc, dtype="<f4").reshape(bpp, dim)
            for j in range(bpp):
                all_parts[r * bpp + j] = vecs[j]
        return fold_block_partials(all_parts, self.n_blocks)

    # -- the loop ------------------------------------------------------------
    def run(self, variables=None, rounds: Optional[int] = None,
            logger=None):
        """Drive `rounds` two-level rounds; returns the trained
        variables (identical bits on every process).  Only rank 0
        appends metrics_history/logs — peers compute the same values
        anyway."""
        eng = self.engine
        cfg = eng.cfg
        rounds = rounds if rounds is not None else cfg.comm_round
        if variables is None:
            variables = eng.init_variables()
        variables = eng._prepare_variables(variables)
        server_state = eng._prepare_server_state(
            eng.server_init(variables))
        rng_base = jax.random.PRNGKey(cfg.seed + 1)
        self._handshake()
        try:
            for round_idx in range(rounds):
                t0 = time.perf_counter()
                round_rng = jax.random.fold_in(rng_base, round_idx)
                train_rng, agg_rng = jax.random.split(round_rng)
                with obs.span("round.twolevel", round=round_idx,
                              rank=self.ctx.rank,
                              blocks=len(self.owned_blocks)):
                    if eng.streaming:
                        parts = self._partials_streaming(
                            variables, round_idx, train_rng, rng_base,
                            rounds)
                    else:
                        parts = self._partials_resident(
                            variables, round_idx, train_rng)
                    with obs.span("multihost.allreduce",
                                  round=round_idx):
                        total = self._allreduce(parts)
                    variables, server_state, m = eng._twolevel_commit(
                        variables, server_state,
                        jax.numpy.asarray(total), agg_rng)
                jax.block_until_ready(variables)
                self.round_walls.append(time.perf_counter() - t0)
                self.channel.export_byte_counters()
                if self.ctx.rank == 0 and (
                        round_idx % cfg.frequency_of_the_test == 0
                        or round_idx == rounds - 1):
                    stats = eng.evaluate(variables)
                    stats.update(round=round_idx,
                                 train_loss=float(m["train_loss"]),
                                 round_time=self.round_walls[-1])
                    eng.metrics_history.append(stats)
                    if logger is not None:
                        logger.log(stats, step=round_idx)
                    log.info("round %d: %s", round_idx, stats)
                if self.on_round_end is not None:
                    self.on_round_end(round_idx)
        except Exception as e:
            obs.dump_flight(f"multihost_error:rank{self.ctx.rank}: "
                            f"{e!r}")
            raise
        finally:
            pre, self._prefetched = self._prefetched, None
            if pre is not None:
                try:
                    pre[1].result()
                except Exception:
                    pass
        self._rollup_metrics()
        return variables

    def _rollup_metrics(self) -> None:
        """Ship every rank's metric deltas to rank 0 and fold them under
        origin="host<i>" (the PR-7 remote-fold shape): an N-process run
        keeps per-process series instead of last-writer-wins gauges,
        and programs.report() gains its per-process breakdown rows from
        exactly these merged series.  The shipped delta is SINCE THE
        LAST ROLLUP in this process (baseline threaded like the PR-7
        uplink piggyback), so back-to-back runners — mh_worker's
        streaming-then-resident pair — don't re-ship and double-count
        the earlier run's counters."""
        if self.ctx.world <= 1:
            return
        try:
            delta = _delta_since_last_rollup()
            docs = self.channel.allgather(
                json.dumps(delta).encode(), timeout_s=self.timeout_s)
            if self.ctx.rank == 0:
                for r, doc in enumerate(docs):
                    if r == 0 or not doc:
                        continue
                    obs.registry().merge_delta(json.loads(doc.decode()),
                                               origin=f"host{r}")
        except DeadRankError:
            raise
        except Exception:
            log.warning("multihost metrics rollup failed", exc_info=True)

    def report(self, warmup_rounds: int = 0) -> dict:
        """Timing/byte rollup over the rounds run so far (warmup rounds
        excluded from the rate)."""
        walls = self.round_walls[warmup_rounds:]
        carry = self.carry_bytes[warmup_rounds:] or [0]
        return {
            "rank": self.ctx.rank,
            "world": self.ctx.world,
            "n_blocks": self.n_blocks,
            "rounds": len(self.round_walls),
            "rounds_per_sec": (len(walls) / sum(walls)
                               if walls and sum(walls) > 0 else 0.0),
            "round_wall_p50_s": (float(np.median(walls))
                                 if walls else 0.0),
            "carry_allreduce_bytes_per_round": float(np.mean(carry)),
            # sum of the per-round deltas, NOT channel.bytes_received:
            # the channel also carries handshake/rollup frames and (in
            # mh_worker) a sibling runner's traffic
            "carry_allreduce_bytes_total": int(sum(self.carry_bytes)),
        }

    def close(self) -> None:
        if self._channel is not None and self._owns_channel:
            self._channel.close()
            self._channel = None


def variables_digest(variables) -> str:
    """md5 over the raw bytes of every leaf (deterministic leaf order)
    — THE bitwise-equality digest of the multihost pins."""
    h = hashlib.md5()
    for leaf in jax.tree.leaves(variables):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()
