"""Multi-host (DCN) runtime bootstrap.

The reference scales across machines with `mpirun -np N -hostfile ...`
(run_fedavg_distributed_pytorch.sh:16-35) — one OS process per client rank
over MPI.  TPU-native, multi-host is one SPMD program: every host runs the
same code, `jax.distributed.initialize` wires the hosts into a single
runtime, and `jax.devices()` becomes the global chip list.  The engines in
parallel/ are already global-view (shard_map over a Mesh, device_put with
NamedShardings), so they run unchanged on a multi-host mesh — XLA routes
in-slice collectives over ICI and cross-slice traffic over DCN.

Mesh layout guidance (the scaling-book recipe): put the axis with the
heaviest collective traffic (the client/cohort axis — its psum moves the
whole model) INSIDE a slice so it rides ICI; put the hierarchical silo
axis across slices so only the second-tier reduction crosses DCN —
`make_hierarchical_host_mesh` encodes exactly that on top of
mesh.make_mesh_2d.

IMPORTANT: init_multihost() must run before ANY jax call that initializes
the XLA backend (so: first thing in main) — jax.distributed.initialize
refuses to run afterwards.

Streaming/prefetch note (parallel/prefetch.py): the streaming and
block-stream paths' background upload thread is PER PROCESS, and every
process runs the same round loop, so the prefetchers issue their
`jax.device_put(..., NamedSharding)` calls in the same order on every
host — each process materializes only its addressable shards, and the
upload/compute overlap composes across hosts (each host hides its own
gather+DMA behind its chips' compute).  The block-streamed
order-statistic defenses remain single-process (enforced at engine
construction): their host [K, P] offload needs every client shard
addressable.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
from jax.sharding import Mesh

from fedml_tpu.parallel.mesh import CLIENT_AXIS, make_mesh, make_mesh_2d

log = logging.getLogger(__name__)


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   required: bool = False) -> None:
    """Join this host into the global runtime (idempotent).

    With no arguments, relies on the cluster's auto-detection (TPU pods
    expose the coordinator via metadata) and degrades gracefully to
    single-process mode on a dev box.  With EXPLICIT arguments — or
    required=True (the CLI's --multihost sets it) — a failure raises:
    silently training independent single-host replicas would corrupt the
    run.  Replaces the reference's mpirun/hostfile bootstrap."""
    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:              # older jax: no is_initialized
        pass
    explicit = (required or coordinator_address is not None
                or num_processes is not None or process_id is not None)
    try:
        # CPU cross-process collectives need a transport; without one the
        # global mesh forms but the first psum fails.  Current jaxlib
        # defaults the option to "gloo" (test_multihost_spmd runs over
        # it); this fallback covers builds whose default is unset/"none".
        # It must happen BEFORE initialize, and without probing the
        # platform — that would initialize the backend, which
        # jax.distributed.initialize forbids (see module docstring) — so
        # the option is set whenever it is not already configured (it
        # only affects the cpu backend; TPU pods use ICI/DCN natively).
        # getattr's default covers the older-jaxlib option-absent case
        # (cur = "absent" skips the update); a FAILING update on a jaxlib
        # that HAS the option is a real configuration error and must not
        # be swallowed — deferring it to the first cross-process psum
        # yields a much worse message
        cur = getattr(jax.config,
                      "jax_cpu_collectives_implementation", "absent")
        if cur in (None, "", "none"):
            # unset/disabled only (this jaxlib's default is already
            # "gloo"): an operator's explicit transport choice (env
            # JAX_CPU_COLLECTIVES_IMPLEMENTATION=mpi or a prior
            # config.update) must win
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        log.info("multihost: process %d/%d, %d global devices",
                 jax.process_index(), jax.process_count(),
                 len(jax.devices()))
    except Exception as e:
        if explicit:
            raise RuntimeError(
                f"multi-host initialization failed for coordinator "
                f"{coordinator_address!r}: {e}") from e
        log.info("multihost init skipped (%s); single-process mode", e)


def make_global_mesh(axis_name: str = CLIENT_AXIS) -> Mesh:
    """1-D mesh over ALL chips of ALL hosts — the cohort axis spans the
    pod; psum rides ICI within a slice and DCN across."""
    return make_mesh(axis_name=axis_name)


def make_hierarchical_host_mesh(silos: Optional[int] = None) -> Mesh:
    """2-D (silo × clients) mesh with one silo per host by default: the
    inner FedAvg psum stays on each host's ICI, only the per-silo means
    cross DCN — the two-tier reduction of hierarchical FL mapped onto the
    physical network (SURVEY.md §2.5 'hierarchical aggregation')."""
    devs = jax.devices()
    silos = silos or max(jax.process_count(), 1)
    if len(devs) % silos != 0:
        raise ValueError(f"{len(devs)} devices not divisible into "
                         f"{silos} silos")
    # global device order is NOT guaranteed host-contiguous; sort by
    # process so each silo row really sits on one host's ICI
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    return make_mesh_2d(n_silos=silos, devices=devs)
