"""Mesh-sharded federated engines — the 128-client north-star path.

One federated round is ONE jit-compiled SPMD program over a device mesh:

    round_fn(variables, server_state, ids, wmask, rng)
      cohort   = take(client_stack, ids)          # HBM-resident, sharded
      shard_map over the client axis:
        vmap(local_train)  over this device's slice of the cohort
        client_transform   per-client hook (robust clipping, ...)
        psum(w_i · v_i), psum(w_i)                # ICI collectives
      server_update(avg)                          # replicated (FedOpt, noise)

This replaces the reference's per-client OS processes + MPI sends + CPU
aggregation loop (FedAvgAPI.py:20-66, mpi/com_manager.py:13-98,
FedAVGAggregator.py:59-88).  The client stack {x,y,mask}[C,B,bs,...] is
uploaded once, sharded over the mesh; per-round traffic is an index vector.
"""
from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fedml_tpu import obs
from fedml_tpu.obs import programs as obs_programs
from fedml_tpu.algorithms.fedavg import FedAvgEngine
from fedml_tpu.algorithms.fedopt import make_server_optimizer
from fedml_tpu.core import robust as robust_ops
from fedml_tpu.core.trainer import ClientTrainer
from fedml_tpu.data.federated import FederatedData
from fedml_tpu.parallel.mesh import (BATCH_AXIS, client_axes,
                                     client_sharding, make_mesh, pvary_tree,
                                     replicated_sharding, shard_stack,
                                     stack_leaf_sharding, stack_leaf_spec)
from fedml_tpu.parallel.prefetch import (AsyncValue, InlineFetcher,
                                         Prefetcher)
from fedml_tpu.utils.config import FedConfig
from fedml_tpu.utils.profiling import TransferOverlapStats

log = logging.getLogger(__name__)
Pytree = Any


def cast_local(tree, dtype):
    """Cast the float leaves of a variables tree to the LOCAL training
    dtype (bf16 local masters — see MeshFedAvgEngine docstring); None is
    the identity."""
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)


def weighted_acc(w):
    """Accumulator step for the chunked loops: acc + Σₖ wₖ·vₖ in f32.
    One definition so every engine's accumulation (FedAvg/Nova/robust/
    GAN/NAS) shares the exact cast-and-einsum policy."""
    return lambda acc, v: acc + jnp.einsum(
        "k,k...->...", w, v.astype(jnp.float32))


def weighted_sum_tree(w, tree):
    """Σₖ wₖ·vₖ over a [k, ...]-stacked pytree, per leaf, in f32 — the
    same cast-and-einsum policy as weighted_acc, without the carry add
    (the chunked loops accumulate the result into their FLAT carry)."""
    return jax.tree.map(
        lambda v: jnp.einsum("k,k...->...", w, v.astype(jnp.float32)), tree)


def flatten_carry_f32(tree):
    """Pack an (unstacked) pytree into ONE [P] f32 vector + unflatten
    spec — THE scan-carry layout for the chunked cohort loops.

    Why: a pytree carry gives XLA one while-loop buffer per leaf, and
    any leaf whose in-loop producer prefers a different layout than the
    carry (e.g. the einsum's transposed output vs the row-major carry)
    gets a relayout `copy` EVERY scan trip — the round-2b trace's
    scan-carry copy category (PERF.md), reproduced structurally on CPU
    by tools/hlo_copy_audit.py (a params-shaped copy per trip in the
    block step).  A single 1-D f32 buffer has exactly one layout, so the
    carry aliases across trips and the per-leaf adds fuse into one
    concatenated update.  Exact: ravel+concat reorder nothing, each
    element sees the same adds in the same order as the per-leaf carry."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((0,), jnp.float32), tree
    if len(leaves) == 1:
        flat = leaves[0].astype(jnp.float32).reshape(-1)
    else:
        flat = jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1) for l in leaves])
    return flat, tree


def unflatten_carry_f32(flat, spec_tree):
    """Undo flatten_carry_f32: [P] f32 vector back to the pytree of
    `spec_tree`'s leaf shapes (f32 — the chunk-loop accumulators stay
    f32; callers apply their own ref-dtype cast when dividing)."""
    leaves, treedef = jax.tree.flatten(spec_tree)
    if not leaves:
        return spec_tree
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape)) if l.ndim else 1
        out.append(flat[off:off + size].reshape(l.shape))
        off += size
    return jax.tree.unflatten(treedef, out)


def pad_ids(ids: np.ndarray, n_shards: int):
    """THE cohort-padding policy (host side): pad sampled client ids to a
    mesh-size multiple with zero-weight repeats of client 0 — wmask=0
    drops them from every weighted reduction.  Shared by all mesh
    engines."""
    ids = np.asarray(ids)
    pad = (-len(ids)) % n_shards
    wmask = np.concatenate([np.ones(len(ids), np.float32),
                            np.zeros(pad, np.float32)])
    ids = np.concatenate([ids, np.zeros(pad, ids.dtype)])
    return ids, wmask


def pad_and_chunk(cohort, weights, rngs, chunk_cap: int):
    """Balanced chunk sizing shared by every chunked cohort loop: same
    number of scan trips as ceil(k/cap) but lanes spread evenly (k=12,
    cap=8 gives 2x6 not 2x8); non-multiple cohorts are padded in-program
    with zero-weight lanes (static shapes; the empty-batch guard makes
    them numeric no-ops).  Returns (cohort, weights, rngs) reshaped to
    [n_chunks, chunk, ...]."""
    k_local = weights.shape[0]
    n_trips = -(-k_local // min(chunk_cap, k_local))
    chunk = -(-k_local // n_trips)
    pad = (-k_local) % chunk
    if pad:
        cohort = jax.tree.map(
            lambda a: jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]), cohort)
        weights = jnp.concatenate(
            [weights, jnp.zeros((pad,), weights.dtype)])
        rngs = jnp.concatenate([rngs, rngs[:pad]])   # masked lanes; any key
    n_chunks = (k_local + pad) // chunk
    resh = lambda a: a.reshape((n_chunks, chunk) + a.shape[1:])
    return jax.tree.map(resh, cohort), resh(weights), resh(rngs)


def default_chunk(local_dtype) -> int:
    """Measured v5e chunk optima (tools/profile_bench.py, PERF.md): the
    L-curve bottoms at 2 with bf16 local masters (1.851 s/round vs 2.080
    at 4, 1.920 at 1); with f32 masters the F-curve bottoms at 8."""
    return 2 if local_dtype == jnp.bfloat16 else 8


def flatten_stack_x(shards: dict):
    """flat_stack flatten (host-side view): image x [C, B, bs, h, w(, c)]
    -> [C, B, bs, prod]; returns (shards, image_shape) with
    image_shape None when x is not image-shaped.  Rationale in
    MeshFedAvgEngine.__init__ (flat_stack)."""
    x = np.asarray(shards["x"]) if "x" in shards else None
    if x is None or x.ndim < 5:
        return shards, None
    return {**shards, "x": x.reshape(x.shape[:3] + (-1,))}, x.shape[3:]


def restore_chunk_x(image_shape, chunk_shards: dict) -> dict:
    """Undo flatten_stack_x on one in-scan chunk slice: [chunk, B, bs, F]
    -> [chunk, B, bs, *image].  Exact (a reshape), O(chunk) memory."""
    if image_shape is None or "x" not in chunk_shards:
        return chunk_shards
    x = chunk_shards["x"]
    return {**chunk_shards, "x": x.reshape(x.shape[:3] + tuple(image_shape))}


def restore_shard_x(image_shape, shard: dict) -> dict:
    """Undo flatten_stack_x on ONE client's shard: [B, bs, F] ->
    [B, bs, *image] (the per-worker/per-client variant of
    restore_chunk_x — gossip's worker loop and the mesh local-eval hook
    both restore at this granularity)."""
    if image_shape is None or "x" not in shard:
        return shard
    x = shard["x"]
    return {**shard, "x": x.reshape(x.shape[:2] + tuple(image_shape))}


def restore_flat_eval_shard(image_shape, shard: dict) -> dict:
    """evaluate_local's per-client restore guard, shared by EVERY engine
    whose resident stack stores x flat (mesh + gossip — ADVICE r4): the
    vmapped eval reuses that stack, so restore [B, bs, F] ->
    [B, bs, *image] in-program; uploaded unflattened stacks pass
    through on the ndim check."""
    if image_shape is not None and "x" in shard and shard["x"].ndim == 3:
        return restore_shard_x(image_shape, shard)
    return shard


def chunked_weighted_train(trainer, variables, cohort, weights, rngs,
                           epochs, vary_axes, chunk_cap: int = 8,
                           client_transform=None,
                           emit_flat_params: bool = False,
                           restore_x=None):
    """Train a shard-local cohort as a lax.scan over chunks of at most
    `chunk_cap` vmapped clients, accumulating Σ w·v / Σ w / Σ w·loss in the
    carry — the HBM-bounded inner loop shared by the flat and hierarchical
    mesh engines (measured on v5e: see MeshFedAvgEngine docstring).

    `variables` must already carry the vma types of `vary_axes` (pvary'd by
    the caller); the f32 accumulators are pvary'd here to match.  Returns
    (num_tree_f32, den, loss_sum) — the caller applies its own psum tier(s).

    With `emit_flat_params` the scan ALSO emits each client's trained
    params flattened to an f32 row (ops/aggregate tile padding), returned
    as a fourth value [n_chunks, chunk, P] — the order-statistic robust
    defenses consume this (any chunk-pad lanes sit at the flattened tail).

    A cohort whose size is not a chunk multiple is padded IN-PROGRAM with
    zero-weight lanes (pad_and_chunk), so chunk stays at the cap instead
    of degenerating to small divisors for awkward (e.g. prime) cohort
    sizes.
    """
    from fedml_tpu.ops.aggregate import flatten_stacked_tree
    cohort, weights, rngs = pad_and_chunk(cohort, weights, rngs, chunk_cap)
    global_params = variables["params"] if trainer.prox_mu > 0 else None

    def one(shard, crng):
        v, loss, _n = trainer.local_train(
            variables, shard, crng, epochs, global_params=global_params)
        return v, loss

    def chunk_body(carry, xs):
        num_flat, den, lsum = carry
        cs, cw, cr = xs
        if restore_x is not None:      # flat_stack: image shape back,
            cs = restore_x(cs)         # O(chunk) per trip
        vs, losses = jax.vmap(one)(cs, cr)
        if client_transform is not None:
            vs = jax.vmap(client_transform,
                          in_axes=(0, 0, None))(vs, cw, variables)
        # Σ w·v per leaf, folded into the ONE-vector f32 carry: a pytree
        # carry gets per-leaf relayout copies every scan trip (the
        # round-2b copy category — see flatten_carry_f32)
        num_flat = num_flat + flatten_carry_f32(
            weighted_sum_tree(cw, vs))[0]
        ys = (flatten_stacked_tree(vs["params"])[0]
              if emit_flat_params else None)
        return (num_flat, den + jnp.sum(cw),
                lsum + jnp.sum(losses * cw)), ys

    zeros = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                         variables)
    zeros_flat, num_spec = flatten_carry_f32(zeros)
    zeros_flat = pvary_tree(zeros_flat, vary_axes)
    zf = pvary_tree(jnp.float32(0), vary_axes)
    (num_flat, den, lsum), flats = jax.lax.scan(
        chunk_body, (zeros_flat, zf, zf), (cohort, weights, rngs))
    num = unflatten_carry_f32(num_flat, num_spec)
    if emit_flat_params:
        return num, den, lsum, flats
    return num, den, lsum


class MeshFedAvgEngine(FedAvgEngine):
    """FedAvg with the cohort sharded over a `jax.sharding.Mesh`.

    `chunk` caps how many client model replicas are live at once on each
    shard: the per-shard cohort is processed as a lax.scan over groups of
    `chunk` vmapped clients, weighted-sums accumulated in the scan carry.
    Measured on a v5e chip (tools/profile_bench.py): 128 concurrent
    ResNet-18 replicas run 3.72 s/round; chunked at 8 the same round is
    2.31 s — the full-width vmap blows the HBM working set.

    `streaming=True` keeps the client stack on HOST and uploads only each
    round's sampled cohort (breaks the HBM-resident wall for cross-device
    scale: 3,400-client femnist, 342,477-client stackoverflow —
    reference benchmark/README.md:54-57 — without holding every shard in
    device memory).

    `local_dtype=jnp.bfloat16` runs the LOCAL training loop on bf16 master
    weights: the round's global f32 variables are cast once per round, so
    the per-step f32→bf16 cast inside the loss becomes a no-op and grads,
    optimizer updates and the 13-step weight chain stay bf16 end-to-end.
    Aggregation is unchanged — each client's final weights enter the Σ w·v
    psum in f32, and the global model stays f32 across rounds (the server
    average's small increments need the f32 grid; the 13 local steps at
    lr≫ulp do not).  Measured on v5e: 2.310 → 2.080 s/round at chunk 4
    (tools/profile_bench.py L4 vs F8).

    A mesh with a "batch" axis (make_mesh_batch) additionally splits each
    client's per-step batch over that axis — per-client SAMPLE parallelism
    for when chips outnumber the cohort.  The trainer completes each
    step's gradient with one psum over the batch axis (ClientTrainer
    batch_axes; set here automatically), so per-client weights stay
    replicated along it and the round's result equals the unsplit run.
    The cohort pads/shards over the CLIENT axes only.  Models whose
    normalization is per-sample (GroupNorm/LayerNorm — incl. the flagship
    ResNet-18-GN) are oracle-equal to the unsplit run; plain BatchNorm
    would normalize by shard-local statistics, so engines reject a
    batch_stats collection under a batch axis unless
    `allow_batch_stats=True` asserts the model's BN is the cross-replica
    variant bound to the "batch" axis (models/norms.py::sync_batch_norm
    with axis_name="batch")."""

    def __init__(self, trainer: ClientTrainer, data: FederatedData,
                 cfg: FedConfig, mesh: Optional[Mesh] = None,
                 donate: bool = True, chunk: Optional[int] = None,
                 streaming: bool = False, local_dtype=None,
                 stack_dtype=None, flat_stack: bool = True,
                 stream_block: Optional[int] = None,
                 allow_batch_stats: bool = False,
                 prefetch: bool = True):
        self.allow_batch_stats = allow_batch_stats
        # prefetch: background-thread host→device upload pipeline on the
        # streaming/block-stream paths (parallel/prefetch.py): the host
        # gather+cast+device_put of block/cohort k+1 runs while the
        # device trains on k — double-buffered, so device data memory
        # keeps the synchronous path's O(2·block) bound.  False is the
        # --no_prefetch escape hatch: strictly synchronous
        # upload→compute, bitwise-identical results (same jitted
        # programs, same inputs — pinned by tests/test_prefetch.py).
        self.prefetch = prefetch
        # upload/compute overlap accounting, always on (two perf_counter
        # calls per event); bench.py and tools/profile_bench.py surface
        # overlap_fraction from here (PERF.md §"Prefetch pipeline")
        self.transfer_stats = TransferOverlapStats()
        # flat_stack stores image cohorts as [C, B, bs, h*w*c] on device
        # and restores [h, w, c] per chunk INSIDE the scan: XLA assigns
        # the big input a tiled layout padded on small minor dims —
        # measured on v5e at the 2048-client bf16 cohort: a 4x-padded
        # relayout copy (bf16[2048,13,32,32,32,3] -> 20.9 GB vs 5.2 GB
        # unpadded) that OOMs 15.75 GB HBM in compile.  The flat layout
        # tiles cleanly (minor dim h*w*c = 3072 = 24*128); only the
        # O(chunk) slice materializes in image layout per scan trip.
        self.flat_stack = flat_stack
        self._x_image_shape = None
        # stack_dtype stores the client stack's INPUT leaf ("x") in this
        # dtype on device — bf16 halves the cohort's HBM footprint and
        # upload bytes, which is what prices in past ~512 bench-shaped
        # clients per chip (measured: the 1024-client knee flattens from
        # 1.32x to 1.06x per client — PERF.md/SCALING.md).  Only "x" is
        # cast: y is integral, and mask must stay f32 (bf16 0/1 sums
        # lose exactness past 256 — sample counts feed the aggregation
        # weights).  Opt-in: inputs at bf16 precision is an accuracy
        # tradeoff the user chooses (tests pin closeness to f32).
        #
        # stack_dtype=uint8 is the transfer-compression tier below bf16
        # (PERF.md "Transfer compression"): the input leaf is stored as
        # uint8 + an affine DequantSpec (data/quant.py) — 4x fewer H2D
        # bytes than f32, 2x fewer than bf16 — and the dequantize
        # (u*scale + offset, f32) is FUSED into the jitted round program
        # as the first op of the block/chunk scan (_dequant_chunk_x via
        # the restore_x hook), so local training still runs the
        # committed float compute recipe.  A loader-quantized stack
        # (load_data store_uint8 / data.x_dequant) passes through as-is;
        # a float stack is quantized ONCE here with a min/max spec.
        self.stack_dtype = stack_dtype
        self._stack_dtype_noop_warned = False
        self._x_dequant = None          # DequantSpec when the stack is u8
        self._u8_host_shards = None     # quantized host view (data stays
        #                                 untouched — it may be shared)
        self._stack_u8 = (stack_dtype is not None
                          and np.dtype(stack_dtype) == np.dtype(np.uint8))
        self.mesh = mesh if mesh is not None else make_mesh()
        # a "batch" mesh axis splits each client's per-step batch over
        # devices (per-client sample parallelism: mesh.py BATCH_AXIS, the
        # chips>cohort scaling axis).  The cohort pads to the CLIENT axes
        # only; the trainer gains a per-step grad psum over the batch axes.
        self.client_axes = client_axes(self.mesh)
        self.batch_axes = tuple(a for a in self.mesh.axis_names
                                if a == BATCH_AXIS)
        self.n_shards = int(np.prod([self.mesh.shape[a]
                                     for a in self.client_axes]))
        if self.batch_axes:
            nb = self.mesh.shape[BATCH_AXIS]
            bs = int(np.shape(data.client_shards["mask"])[2])
            if bs % nb:
                raise ValueError(
                    f"batch mesh axis ({nb}) must divide the per-step "
                    f"batch size ({bs})")
            if getattr(trainer, "batch_axes", ()) != self.batch_axes:
                import copy
                trainer = copy.copy(trainer)
                trainer.batch_axes = self.batch_axes
        if chunk is not None and chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = chunk if chunk is not None else default_chunk(local_dtype)
        # stream_block: block-streamed rounds — the cohort is uploaded in
        # blocks of `stream_block` clients WITHIN the round (double-
        # buffered), the linear sums accumulating on device across block
        # steps.  Device data memory becomes O(stream_block) instead of
        # O(cohort): the cohort axis is bounded by host RAM and upload
        # bandwidth only, not HBM (SCALING.md).  Implies streaming.
        if stream_block is not None:
            streaming = True
        self.stream_block = stream_block
        self.streaming = streaming
        self.local_dtype = local_dtype
        # a loader-quantized stack (store_uint8) arrives uint8 with its
        # spec on the data object: honor it even without the knob — the
        # dequant is a correctness requirement, not a preference
        if (not self._stack_u8 and getattr(data, "x_dequant", None)
                is not None and "x" in data.client_shards
                and np.asarray(data.client_shards["x"]).dtype == np.uint8):
            self._stack_u8 = True
        if self._stack_u8:
            self._prepare_uint8_stack(data)
        super().__init__(trainer, data, cfg, donate=donate)
        self._stack = None           # sharded client stack, uploaded lazily
        self._stack_weights = None
        # stack/stack_w are explicit (pre-sharded) args, not closed-over
        # constants, so the jit never embeds the dataset in the program.
        # ISSUE 12: every engine names its jit-program FAMILY — the
        # hlo_copy_audit taxonomy (fedavg_resident/fedavg_streaming/
        # fedavg_blockstream, subclass stems override) — and its round
        # programs dispatch through the obs/programs.py profile
        # registry: per-family dispatch counts + host-wall histograms +
        # compile attribution, values untouched (obs-off results stay
        # bitwise, the standing pins)
        self.program_family = self._program_family_name(streaming,
                                                        stream_block)
        self.round_fn = obs_programs.instrument(
            self.program_family,
            jax.jit(self._mesh_round,
                    donate_argnums=(0, 1) if donate else ()))
        # streaming variant: the gather happened on host; cohort arrives
        # pre-sharded [K, ...] with K = padded cohort size.  This public
        # entry donates variables/server_state ONLY — bench.py and the
        # convergence tools upload one cohort and replay it for every
        # round, so the cohort args must survive the call.
        self.round_fn_streaming = obs_programs.instrument(
            self.program_family,
            jax.jit(self._mesh_round_streaming,
                    donate_argnums=(0, 1) if donate else ()))
        # ...but the run() loop gathers a FRESH cohort every round
        # (_round_args), each consumed exactly once — donate it too, so
        # a retired cohort's HBM is recycled into the round instead of
        # sitting next to the prefetched next one (same rationale as the
        # block-step input donation; results are bitwise donate-on/off,
        # pinned in tests/test_parallel_stream.py)
        self._round_fn_streaming_consume = obs_programs.instrument(
            self.program_family,
            jax.jit(self._mesh_round_streaming,
                    donate_argnums=(0, 1, 2, 3) if donate else ()))
        if streaming:
            self.round_fn = self._round_fn_streaming_consume
        if self.stream_block is not None:
            if self.stream_block < 1 or self.stream_block % self.n_shards:
                raise ValueError(
                    f"stream_block ({self.stream_block}) must be a "
                    f"positive multiple of the mesh's client-shard count "
                    f"({self.n_shards})")
            # block accumulation step + round finalize: two small jitted
            # programs the host loop drives per round.  The accumulators
            # (argnum 1) are donated so the sums carry through without
            # copies; the block inputs (2-4) are donated too — each is
            # consumed exactly once, and without donation a retired
            # block would stay resident in HBM next to the prefetched
            # one, breaking the O(2·block) device-data bound
            self._block_step = obs_programs.instrument(
                self.program_family,
                jax.jit(self._block_step_impl,
                        donate_argnums=(1, 2, 3, 4)))
            # sums (argnum 2) is engine-internal and dead after finalize
            # — always donated; variables/server_state follow the
            # user-visible donate flag
            self._block_finalize = obs_programs.instrument(
                self.program_family,
                jax.jit(self._block_finalize_impl,
                        donate_argnums=(0, 1, 2) if donate else (2,)))
            self.round_fn = self._round_blockstream


    # jit-program family stem (ISSUE 12): subclasses override so their
    # profile rows and compile attribution name the right family in the
    # hlo_copy_audit taxonomy
    _family_stem = "fedavg"

    def _program_family_name(self, streaming: bool,
                             stream_block) -> str:
        if stream_block is not None:
            return f"{self._family_stem}_blockstream"
        if streaming:
            return f"{self._family_stem}_streaming"
        return f"{self._family_stem}_resident"

    # -- hooks ---------------------------------------------------------------
    def client_transform(self, client_variables: Pytree, weight: jax.Array,
                         global_variables: Pytree) -> Pytree:
        """Per-client post-training hook (vmapped inside the shard). Robust
        engines clip here; FedAvg is identity."""
        return client_variables

    def server_update(self, avg_variables: Pytree, global_variables: Pytree,
                      server_state: Pytree, rng: jax.Array):
        """Replicated server-side update applied to the psum'd average.
        FedAvg installs the average directly (FedAVGAggregator.py:59-88)."""
        return avg_variables, server_state

    # -- device data ----------------------------------------------------------
    def _prepare_uint8_stack(self, data) -> None:
        """uint8 cohort storage (stack_dtype=uint8): resolve the dequant
        spec and the uint8 HOST view of the client stack, ONCE at
        construction.  A loader-quantized stack (data.x_dequant) passes
        through; a float stack is quantized here with a min/max spec —
        into a separate view, never mutating `data` (test oracles and
        sibling engines share the data object).  Eager so the spec is
        set on the construction thread before any jit trace or prefetch
        worker reads it."""
        from fedml_tpu.data.quant import quantize_uint8, spec_from_minmax
        shards = data.client_shards
        x = np.asarray(shards["x"]) if "x" in shards else None
        if x is None or (x.dtype != np.uint8
                         and not np.issubdtype(x.dtype, np.floating)):
            self._stack_u8 = False
            if x is not None and not self._stack_dtype_noop_warned:
                self._stack_dtype_noop_warned = True
                log.warning(
                    "stack_dtype=uint8 ignored: the input leaf is %s "
                    "(integer token-id datasets must not be quantized)",
                    x.dtype)
            return
        if x.dtype == np.uint8:
            spec = getattr(data, "x_dequant", None)
            if spec is None:
                raise ValueError(
                    "client stack x is uint8 but data.x_dequant is unset: "
                    "a uint8 stack needs its DequantSpec (load_data "
                    "store_uint8=True sets it)")
            self._u8_host_shards = shards
        else:
            spec = spec_from_minmax(x)
            self._u8_host_shards = {**shards, "x": quantize_uint8(x, spec)}
        self._x_dequant = spec

    def _host_shards(self) -> dict:
        """The host-side client stack every upload path gathers from:
        the uint8-quantized view when stack_dtype=uint8, else the data's
        own shards."""
        return (self._u8_host_shards if self._u8_host_shards is not None
                else self.data.client_shards)

    def _cast_stack_x(self, shards: dict) -> dict:
        """Apply stack_dtype to the input leaf (see __init__); identity
        when unset — and for INTEGER inputs (token ids on the text
        datasets): bf16 represents integers exactly only up to 256, so
        casting ids would silently remap most of a 10k vocabulary.
        The uint8 tier never casts here: `_host_shards` is already
        quantized (once, at construction)."""
        if (self.stack_dtype is not None and not self._stack_u8
                and "x" in shards):
            if np.issubdtype(np.asarray(shards["x"]).dtype, np.floating):
                shards = dict(shards)
                shards["x"] = np.asarray(shards["x"],
                                         jnp.dtype(self.stack_dtype))
            elif not self._stack_dtype_noop_warned:
                self._stack_dtype_noop_warned = True
                log.warning(
                    "stack_dtype=%s ignored: the input leaf is %s (token-id "
                    "datasets keep integer inputs — casting would remap the "
                    "vocabulary)", self.stack_dtype,
                    np.asarray(shards["x"]).dtype)
        if self.flat_stack:
            shards, image_shape = flatten_stack_x(shards)
            if image_shape is not None:
                self._x_image_shape = image_shape
        return shards

    def _dequant_chunk_x(self, shards: dict) -> dict:
        """In-program dequantize of a uint8 input slice — the FIRST op
        of the block/chunk scan body (after the flat_stack restore, so a
        per-channel spec broadcasts over [..., h, w, c]).  Identity when
        the stack is not quantized, and for float leaves (the local-eval
        fallback stacks stay f32)."""
        if self._x_dequant is None or "x" not in shards:
            return shards
        x = shards["x"]
        if not jnp.issubdtype(x.dtype, jnp.integer):
            return shards
        scale = jnp.asarray(self._x_dequant.scale, jnp.float32)
        offset = jnp.asarray(self._x_dequant.offset, jnp.float32)
        return {**shards, "x": x.astype(jnp.float32) * scale + offset}

    def _restore_chunk_x(self, chunk_shards: dict) -> dict:
        """Undo flat_stack on one in-scan chunk slice (restore_chunk_x),
        then dequantize a uint8 slice — O(chunk) memory either way."""
        return self._dequant_chunk_x(
            restore_chunk_x(self._x_image_shape, chunk_shards))

    def _local_eval_transform(self, shard: dict) -> dict:
        """Per-client shard hook inside evaluate_local's vmap (shared
        flat_stack restore guard — restore_flat_eval_shard — plus the
        uint8 dequant when the resident stack is quantized)."""
        return self._dequant_chunk_x(
            restore_flat_eval_shard(self._x_image_shape, shard))

    def _device_stack(self):
        """Upload the [C,...] client stack ONCE, leading axis sharded over the
        mesh (C padded to a mesh-size multiple with zero-weight clients)."""
        if self._stack is None:
            from fedml_tpu.parallel.mesh import pad_cohort
            shards, weights = self._host_shards(), self.data.client_num_samples
            shards, weights = pad_cohort(
                self._cast_stack_x(dict(shards)),
                np.asarray(weights, np.float32), self.n_shards)
            self.transfer_stats.add_h2d_bytes(
                sum(np.asarray(v).nbytes for v in shards.values())
                + weights.nbytes)
            self._stack = shard_stack(self.mesh, shards)
            self._stack_weights = jax.device_put(
                weights.astype(np.float32), client_sharding(self.mesh))
        return self._stack, self._stack_weights

    def _upload_eval_stack(self, shards):
        """Per-client eval stacks ride the mesh too: pad the client axis
        to a mesh multiple (mask-0 lanes add nothing to the eval sums)
        and shard it — the train stack needed sharding to fit, so the
        test stack gets the same treatment (ADVICE r2)."""
        from fedml_tpu.parallel.mesh import pad_cohort
        C = jax.tree.leaves(shards)[0].shape[0]
        shards, _ = pad_cohort(dict(shards),
                               np.zeros(C, np.float32), self.n_shards)
        return shard_stack(self.mesh, shards)

    # -- the round program ----------------------------------------------------
    def _shard_sums(self, variables, cohort, weights, client_rngs):
        """Per-shard cohort training (chunked_weighted_train) + one psum
        tier over the mesh: returns the REPLICATED (Σ w·v, Σ w, Σ w·loss)
        — the linear core shared by the whole-cohort round (_shard_body)
        and the block-streamed round (_round_blockstream), which
        accumulates these sums across blocks before dividing."""
        axes = self.mesh.axis_names
        # the global model arrives replicated; per-client training makes
        # it shard-varying, so cast up-front for the vma type system
        variables = pvary_tree(variables, axes)
        local_vars = cast_local(variables, self.local_dtype)
        num, den, lsum = chunked_weighted_train(
            self.trainer, local_vars, cohort, weights, client_rngs,
            self.cfg.epochs, vary_axes=axes, chunk_cap=self.chunk,
            client_transform=self.client_transform,
            restore_x=self._restore_chunk_x)
        return (jax.lax.psum(num, axes), jax.lax.psum(den, axes),
                jax.lax.psum(lsum, axes))

    def _zero_sums(self, variables):
        """Zero accumulators matching _shard_sums' output structure (the
        block-streamed round's carry; engines with extra linear sums —
        FedNova's tau — override the triple together)."""
        return (jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                             variables), jnp.float32(0), jnp.float32(0))

    def _finalize_from_sums(self, variables, sums):
        """(aggregated model, mean loss) from the accumulated linear sums
        — pure math, shared verbatim by the whole-cohort shard body and
        the block-streamed finalize."""
        num, den, lsum = sums
        avg = jax.tree.map(
            lambda s, ref: (s / den).astype(ref.dtype), num, variables)
        return avg, lsum / den

    def _shard_body(self, variables, cohort, weights, client_rngs):
        """Whole-cohort round body: the two-collective FedAvg aggregation
        (SURVEY.md §5) — sums then the weighted mean."""
        return self._finalize_from_sums(
            variables,
            self._shard_sums(variables, cohort, weights, client_rngs))

    def _train_and_update(self, variables, server_state, cohort, weights,
                          rng):
        """Common round tail for the resident and streaming entry points:
        shard_map the chunked cohort training, then the (replicated) server
        update — so subclass overrides of _shard_body/server_update apply to
        BOTH paths identically."""
        mesh = self.mesh
        csh = P(self.client_axes)
        cohort_specs = {k: stack_leaf_spec(mesh, v)
                        for k, v in cohort.items()}
        rng, agg_rng = jax.random.split(rng)
        client_rngs = jax.random.split(rng, weights.shape[0])
        avg, train_loss = jax.shard_map(
            self._shard_body, mesh=mesh,
            in_specs=(P(), cohort_specs, csh, csh), out_specs=(P(), P()))(
                variables, cohort, weights, client_rngs)
        new_variables, server_state = self.server_update(
            avg, variables, server_state, agg_rng)
        return new_variables, server_state, {"train_loss": train_loss}

    def _mesh_round(self, variables, server_state, stack, stack_w, ids,
                    wmask, rng):
        # cohort gather: device-side take along the sharded client axis; XLA
        # lowers the cross-shard gather to ICI collectives.
        cohort = {k: jax.lax.with_sharding_constraint(
            jnp.take(v, ids, axis=0), stack_leaf_sharding(self.mesh, v))
            for k, v in stack.items()}
        weights = jnp.take(stack_w, ids) * wmask
        return self._train_and_update(variables, server_state, cohort,
                                      weights, rng)

    def _mesh_round_streaming(self, variables, server_state, cohort, weights,
                              rng):
        """Streaming round: the cohort was gathered on HOST (only the
        sampled clients' shards were uploaded, sharded over the mesh) — the
        device never holds the full client stack."""
        return self._train_and_update(variables, server_state, cohort,
                                      weights, rng)

    # -- two-level (multi-host) aggregation programs (ISSUE 13) --------------
    # The multihost runner (parallel/multihost.py) decomposes a round
    # into per-block PARTIALS (this engine's linear sums, psum'd over
    # the LOCAL mesh only — the ICI tier) and one replicated COMMIT
    # after the host-level inter-process fold of the P-sized flat
    # carries (the DCN tier).  The partial returns the carry FLAT
    # (flatten_carry_f32 over the engine's sums pytree) because the
    # flat f32 vector is exactly what crosses hosts; the commit
    # unflattens, divides, and applies the server update — so subclass
    # overrides of _shard_sums/_zero_sums/_finalize_from_sums/
    # server_update (FedNova's tau sums, FedOpt's optimizer, robust
    # norm_clip's noise) ride the two-level path unchanged.
    def _ensure_twolevel(self) -> None:
        """Build the two-level programs lazily (most engines never run
        multihost; the extra jits must not tax single-host
        construction)."""
        if getattr(self, "_twolevel_ready", False):
            return
        if getattr(self, "defense", "norm_clip") != "norm_clip":
            raise ValueError(
                f"two-level aggregation is linear: order-statistic "
                f"defense {self.defense!r} cannot fold per-host "
                f"partials (it needs the full [K, P] cohort matrix)")
        fam = f"{self._family_stem}_twolevel"
        # block cohorts are gathered fresh per round and consumed
        # exactly once — donated like the streaming-consume round
        self._twolevel_partial = obs_programs.instrument(
            fam, jax.jit(self._twolevel_partial_impl,
                         donate_argnums=(1, 2, 3) if self.donate
                         else ()))
        self._twolevel_partial_resident = obs_programs.instrument(
            fam, jax.jit(self._twolevel_partial_resident_impl))
        # flat_sums (argnum 2) is NOT donated: a 1-D [S] carry can never
        # alias the variables-shaped outputs, so donating it only buys
        # an unusable-donation warning per compile — unlike the
        # block-finalize sums, whose variables-shaped num tree aliases
        # the averaged output
        self._twolevel_commit = obs_programs.instrument(
            "twolevel_commit",
            jax.jit(self._twolevel_commit_impl,
                    donate_argnums=(0, 1) if self.donate else ()))
        self._twolevel_ready = True

    def _twolevel_partial_body(self, variables, cohort, weights, rngs):
        specs = {k: stack_leaf_spec(self.mesh, v)
                 for k, v in cohort.items()}
        csh = P(self.client_axes)
        sums = jax.shard_map(
            self._shard_sums, mesh=self.mesh,
            in_specs=(P(), specs, csh, csh), out_specs=P())(
                variables, cohort, weights, rngs)
        return flatten_carry_f32(sums)[0]

    def _twolevel_partial_impl(self, variables, cohort, weights, rngs):
        """One block's partial from a host-gathered cohort (streaming
        residency): intra-host psum'd linear sums, returned as ONE flat
        f32 carry — the vector the inter-host allreduce folds."""
        return self._twolevel_partial_body(variables, cohort, weights,
                                           rngs)

    def _twolevel_partial_resident_impl(self, variables, stack, stack_w,
                                        ids, wmask, rngs):
        """Resident variant: the process's id-range stack lives on
        device; the block cohort is a device-side take by LOCAL index.
        Gather values are bitwise the host-gather's, so both residency
        modes feed the identical partial math."""
        cohort = {k: jax.lax.with_sharding_constraint(
            jnp.take(v, ids, axis=0), stack_leaf_sharding(self.mesh, v))
            for k, v in stack.items()}
        weights = jnp.take(stack_w, ids) * wmask
        return self._twolevel_partial_body(variables, cohort, weights,
                                           rngs)

    def _twolevel_commit_impl(self, variables, server_state, flat_sums,
                              agg_rng):
        """Replicated commit from the globally-folded flat carry:
        unflatten into the engine's sums structure, divide, apply the
        server update — run identically on every host (audited as the
        `twolevel_commit` hlo family: 0 copy ops, donation
        complete)."""
        sums = unflatten_carry_f32(flat_sums, self._zero_sums(variables))
        avg, loss = self._finalize_from_sums(variables, sums)
        new_variables, server_state = self.server_update(
            avg, variables, server_state, agg_rng)
        return new_variables, server_state, {"train_loss": loss}

    def _host_gather_upload(self, ids) -> dict:
        """THE host-gather upload pipeline (shared by stream_cohort and
        _upload_block so the two streaming granularities can never
        diverge): slice the host arrays (the uint8 view when the stack
        is quantized — compressed bytes are what cross H2D), apply
        stack_dtype/flat_stack (_cast_stack_x), async device_put with
        per-leaf sharding.  Every byte handed to device_put lands in
        the engine_h2d_bytes_total accounting."""
        host = self._cast_stack_x(
            {k: np.take(np.asarray(v), ids, axis=0)
             for k, v in self._host_shards().items()})
        self.transfer_stats.add_h2d_bytes(
            sum(v.nbytes for v in host.values()))
        return {k: jax.device_put(v, stack_leaf_sharding(self.mesh, v))
                for k, v in host.items()}

    def stream_cohort(self, round_idx: int):
        """Host-side cohort gather for the streaming path: the same padded
        sampling as the resident path, but slicing the HOST arrays and
        uploading only the cohort (chunk-multiple padding happens inside
        chunked_weighted_train)."""
        return self._stream_gather(*self._sample_padded_np(round_idx))

    def _stream_gather(self, ids, wmask):
        """The upload half of stream_cohort, split from the sampling:
        this part is what runs on the prefetch thread (_round_args) —
        the SAMPLER must stay on the caller thread because it reseeds
        the process-global numpy RNG (core/sampling.py), which a
        background thread would race.  The wall lands in transfer_stats
        from whichever thread runs it."""
        with obs.span("h2d.upload_cohort", clients=len(ids)), \
                self.transfer_stats.uploading():
            cohort = self._host_gather_upload(ids)
            w = np.take(np.asarray(self.data.client_num_samples,
                                   np.float32), ids) * wmask
            self.transfer_stats.add_h2d_bytes(w.nbytes)
            weights = jax.device_put(w, client_sharding(self.mesh))
        return cohort, weights

    # -- block-streamed round (stream_block) ---------------------------------
    def _block_step_impl(self, variables, sums, block, weights, rngs):
        """One block's contribution: shard_map the engine's linear sums
        (whatever pytree _shard_sums returns) and fold them into the
        round accumulators (donated)."""
        specs = {k: stack_leaf_spec(self.mesh, v) for k, v in block.items()}
        csh = P(self.client_axes)
        bsums = jax.shard_map(
            self._shard_sums, mesh=self.mesh,
            in_specs=(P(), specs, csh, csh), out_specs=P())(
                variables, block, weights, rngs)
        return jax.tree.map(lambda a, b: a + b, sums, bsums)

    def _block_finalize_impl(self, variables, server_state, sums, agg_rng):
        avg, loss = self._finalize_from_sums(variables, sums)
        new_variables, server_state = self.server_update(
            avg, variables, server_state, agg_rng)
        return new_variables, server_state, {"train_loss": loss}

    def _upload_block(self, ids_blk, w_blk, rngs_blk):
        """Host-gather + async device_put of one client block (the
        double-buffer unit), via the shared _host_gather_upload pipeline.
        Runs on the prefetch thread when the pipeline is on; the wall
        lands in transfer_stats either way.  The span is produced from
        whichever thread uploads, so on the pipelined path it lands on
        the worker's trace row, interleaved with the round loop's
        block_step spans — the overlap is visible directly."""
        with obs.span("h2d.upload_block", clients=len(ids_blk)), \
                self.transfer_stats.uploading():
            block = self._host_gather_upload(ids_blk)
            self.transfer_stats.add_h2d_bytes(
                np.asarray(w_blk).nbytes + np.asarray(rngs_blk).nbytes)
            weights = jax.device_put(w_blk, client_sharding(self.mesh))
            rngs = jax.device_put(rngs_blk, client_sharding(self.mesh))
        return block, weights, rngs

    def _pad_to_block(self, ids, wmask):
        """Pad the shard-padded cohort to a stream_block multiple with
        zero-weight repeated-id lanes, and return the per-round block
        spans [(start, stop), ...]."""
        B = self.stream_block
        pad = (-len(ids)) % B
        if pad:       # pad to a block multiple with zero-weight lanes
            ids = np.concatenate([ids, np.repeat(ids[:1], pad)])
            wmask = np.concatenate([wmask, np.zeros(pad, np.float32)])
        spans = [(s, s + B) for s in range(0, len(ids), B)]
        return ids, wmask, spans

    def _block_fetcher(self, ids, w_all, crngs, spans):
        """Block iterator for the streamed rounds: the background
        double-buffered upload pipeline (prefetch.py), or the strictly
        synchronous inline path under prefetch=False (--no_prefetch).
        Both deliver blocks in span order via get(); use as a context
        manager so an aborted round joins the worker and drops
        undelivered buffers."""
        def produce(span):
            s, e = span
            return self._upload_block(ids[s:e], w_all[s:e], crngs[s:e])

        cls = Prefetcher if self.prefetch else InlineFetcher
        return cls(produce, spans, stats=self.transfer_stats)

    def _round_blockstream(self, variables, server_state, round_idx, rng):
        """Block-streamed round: `stream_block`-client blocks cross
        host→device while the jitted block step accumulates
        Σ w·v / Σ w / Σ w·loss on device; one finalize divides and
        applies the server update.  Uploads are double-buffered on a
        background thread (_block_fetcher): the host gather + cast +
        device_put of block k+1 runs while the device trains on block k,
        so round wall approaches max(upload, compute) instead of their
        sum — transfer_stats records the per-round upload/compute walls
        and overlap_fraction.  Aggregation is linear, so the result
        equals the whole-cohort streaming round up to float summation
        order (oracle-pinned in tests/test_parallel.py) and is BITWISE
        prefetch-knob-independent (tests/test_prefetch.py); the
        per-client rngs are the SAME (jax.random.split prefixes are
        stable, and zero-weight pad lanes contribute exactly 0).

        Device data memory is O(2 · stream_block · shard bytes) — the
        cohort axis is unbounded by HBM (block inputs are donated to the
        block step, so retired blocks never stack).  The cost: the
        cohort's bytes cross host→device EVERY round (the resident/
        streaming paths upload once), so this path pays off when the
        cohort does not fit HBM at all, and its round time is bounded
        below by upload bandwidth."""
        ids, wmask = self._sample_padded_np(round_idx)
        ids, wmask, spans = self._pad_to_block(ids, wmask)
        w_all = (np.take(np.asarray(self.data.client_num_samples,
                                    np.float32), ids) * wmask)
        rng, agg_rng = jax.random.split(rng)
        crngs = np.asarray(jax.random.split(rng, len(ids)))
        self.transfer_stats.round_start()
        try:
            with obs.span("round.blockstream", round=int(round_idx),
                          clients=len(ids), blocks=len(spans)):
                sums = jax.device_put(self._zero_sums(variables),
                                      replicated_sharding(self.mesh))
                with self._block_fetcher(ids, w_all, crngs, spans) as fetch:
                    for i, _ in enumerate(spans):
                        args = fetch.get()
                        # dispatch wall only (the jit call is async);
                        # the device wall shows up as the NEXT get()'s
                        # wait when uploads outpace compute
                        with obs.span("round.block_step", block=i):
                            sums = self._block_step(variables, sums, *args)
                with obs.span("round.block_finalize"):
                    return self._block_finalize(variables, server_state,
                                                sums, agg_rng)
        finally:
            self.transfer_stats.round_end()

    # NOTE: a fully on-device multi-round path (`run_scanned`: whole blocks
    # of rounds as one lax.scan program, in-program fold-in sampling) was
    # built and CUT after chip measurement: at ms-scale rounds (LR/MNIST,
    # 1000 clients, 10/round — the regime where amortizing per-round
    # dispatch should pay if it ever does) the jitted per-round loop ran
    # 2.56 ms/round vs 23.8 ms/round scanned (tools/profile_bench.py
    # exp_SCAN, v5e, 2026-07-31; PERF.md).  The in-scan cohort gather +
    # shard_map compile far worse than the host-dispatched round program,
    # and per-round dispatch is not a bottleneck at any measured scale.
    # -- driver loop ----------------------------------------------------------
    def _sample_padded_np(self, round_idx: int):
        """Sample the round's cohort and pad to a mesh-size multiple
        (pad_ids — the one padding policy shared by the resident,
        streaming, and GAN mesh paths)."""
        return pad_ids(self.sampler.sample(round_idx), self.n_shards)

    def sample_padded(self, round_idx: int):
        ids, wmask = self._sample_padded_np(round_idx)
        return jnp.asarray(ids), jnp.asarray(wmask)

    def _prepare_server_state(self, server_state):
        # via host: a checkpoint-restored state arrives COMMITTED to one
        # local device, and a committed->global device_put would need
        # cross-host transfers (unsupported on the gloo CPU backend);
        # every process holds the full replicated value, so the numpy
        # round-trip makes the placement purely process-local
        sh = replicated_sharding(self.mesh)
        return jax.tree.map(
            lambda a: jax.device_put(np.asarray(a), sh), server_state)

    # the base FedAvgEngine.run drives the loop through these two hooks
    def _prepare_variables(self, variables: Pytree) -> Pytree:
        if self.batch_axes and not self.allow_batch_stats and any(
                k != "params" for k in variables):
            raise ValueError(
                "model carries a stats collection "
                f"({[k for k in variables if k != 'params']}) and the mesh "
                "has a 'batch' axis: plain BatchNorm would normalize by "
                "shard-local statistics.  Use per-sample normalization "
                "(GroupNorm/LayerNorm), or sync_batch_norm(axis_name="
                "'batch') (models/norms.py) and pass "
                "allow_batch_stats=True")
        return jax.device_put(variables, replicated_sharding(self.mesh))

    def _round_args(self, round_idx: int) -> tuple:
        if self.stream_block is not None:
            # block-streamed rounds gather their own blocks on the fly
            return (round_idx,)
        if self.streaming:
            # double-buffered round uploads: round r+1's host gather +
            # cast + device_put (_stream_gather) runs on a background
            # thread (AsyncValue) while round r computes — the HOST side
            # of the upload no longer serializes with the round loop.
            # SAMPLING stays on THIS thread either way: the sampler
            # reseeds the process-global numpy RNG, which a background
            # thread would race (and the knob must not change cohorts).
            # With prefetch=False the gather runs inline here, the old
            # synchronous path, recorded as consumer wait (unhidden).
            # Two cohorts live on device, bounded.  The base run()
            # exposes its round budget via _rounds_limit — no gather
            # past the final round, and the last buffer is released.
            # No per-round stats windows here (the round body runs in
            # the caller's loop, out of this hook's sight; a window
            # opened here would span into the NEXT round) — the
            # streaming path reports cumulative walls only; per-round
            # records are a block-stream feature.
            pre = getattr(self, "_prefetched", None)
            if pre is not None and pre[0] != round_idx:
                # stale prefetch (an aborted run retried, or rounds
                # replayed out of order): JOIN the in-flight upload
                # before gathering anew — letting it run unobserved
                # would put a third cohort on device (the documented
                # bound is two).  Its error is logged and dropped
                # (superseded — a fresh gather follows); Exception
                # only, so a Ctrl-C during the join still aborts.
                if isinstance(pre[1], AsyncValue):
                    try:
                        pre[1].result()
                    except Exception:
                        log.warning("discarding failed stale prefetch "
                                    "for round %d", pre[0], exc_info=True)
                pre = None
                self._prefetched = None
            if pre is not None:
                if isinstance(pre[1], AsyncValue):
                    try:
                        args = pre[1].result()
                    except BaseException:
                        # never cache a failed gather: a resumed run
                        # hitting this round again must re-gather
                        # fresh, not re-raise the stale exception
                        self._prefetched = None
                        raise
                else:
                    args = pre[1]
            else:
                with self.transfer_stats.waiting():   # unhidden gather
                    args = self.stream_cohort(round_idx)
            limit = getattr(self, "_rounds_limit", None)
            if limit is None or round_idx + 1 < limit:
                nxt = round_idx + 1
                if self.prefetch:
                    nxt_ids, nxt_wmask = self._sample_padded_np(nxt)
                    self._prefetched = (
                        nxt, AsyncValue(self._stream_gather, nxt_ids,
                                        nxt_wmask,
                                        stats=self.transfer_stats))
                else:
                    with self.transfer_stats.waiting():
                        self._prefetched = (nxt, self.stream_cohort(nxt))
            else:
                self._prefetched = None
            return args
        stack, stack_w = self._device_stack()
        ids, wmask = self.sample_padded(round_idx)
        return (stack, stack_w, ids, wmask)


class MeshFedProxEngine(MeshFedAvgEngine):
    """FedProx on the mesh: the proximal term lives in the trainer's loss
    (reference keeps the same aggregator, fedprox/ mirrors fedavg/)."""

    _family_stem = "fedprox"

    def __init__(self, trainer, data, cfg, **kw):
        if trainer.prox_mu <= 0:
            # don't mutate the caller's (possibly shared) trainer — other
            # engines built on it would silently gain the proximal term
            import copy
            trainer = copy.copy(trainer)
            trainer.prox_mu = cfg.prox_mu
        super().__init__(trainer, data, cfg, **kw)


class MeshFedOptEngine(MeshFedAvgEngine):
    """Server-optimizer FL: pseudo-gradient w_global − w_avg fed to an optax
    server optimizer (FedOptAggregator.py:94-123, optrepo.py:11-39).  The
    optimizer state persists across rounds in server_state."""

    _family_stem = "fedopt"

    def __init__(self, trainer, data, cfg, **kw):
        self.server_tx = make_server_optimizer(
            cfg.server_optimizer, cfg.server_lr, cfg.server_momentum)
        super().__init__(trainer, data, cfg, **kw)

    def server_init(self, variables):
        return self.server_tx.init(variables["params"])

    def server_update(self, avg_variables, global_variables, server_state, rng):
        pseudo_grad = jax.tree.map(lambda g, a: g - a,
                                   global_variables["params"],
                                   avg_variables["params"])
        updates, server_state = self.server_tx.update(
            pseudo_grad, server_state, global_variables["params"])
        new_params = jax.tree.map(lambda p, u: p + u,
                                  global_variables["params"], updates)
        new_vars = dict(avg_variables)   # stats collections take the average
        new_vars["params"] = new_params
        return new_vars, server_state


class MeshFedNovaEngine(MeshFedAvgEngine):
    """FedNova on the mesh — normalized averaging (algorithms/fednova.py,
    reference fednova.py:50-200): d = Σᵢ pᵢ(g−wᵢ)/τᵢ, w_new = g − τ_eff·d
    with τ_eff = Σᵢ pᵢτᵢ.  All three reductions are linear, so the whole
    aggregation stays two psum tiers like FedAvg; the only extra device
    state is one weighted τ accumulator in the chunk-scan carry."""

    _family_stem = "fednova"

    @staticmethod
    def _split(v):
        return v["params"], {k: x for k, x in v.items() if k != "params"}

    def _shard_sums(self, variables, cohort, weights, client_rngs):
        """FedNova's linear sums: (Σ w·(g−v)/τ, Σ w·stats, Σ w, Σ w·τ,
        Σ w·loss) — same structure contract as the FedAvg triple, so the
        whole-cohort shard body AND the block-streamed round drive it
        through the shared _finalize_from_sums."""
        axes = self.mesh.axis_names
        variables = pvary_tree(variables, axes)
        local_vars = cast_local(variables, self.local_dtype)
        epochs = self.cfg.epochs
        trainer = self.trainer
        ch_cohort, ch_w, ch_r = pad_and_chunk(
            cohort, weights, client_rngs, self.chunk)

        from fedml_tpu.algorithms.fednova import fednova_tau

        def one(shard, crng):
            v, loss, _n = trainer.local_train(local_vars, shard, crng,
                                              epochs)
            return v, loss, fednova_tau(shard, epochs, self.batch_axes)

        g_params, _ = self._split(local_vars)

        def chunk_body(carry, xs):
            dflat, rflat, den, tsum, lsum = carry
            cs, cw, cr = xs
            cs = self._restore_chunk_x(cs)      # flat_stack (engine.py)
            vs, losses, taus = jax.vmap(one)(cs, cr)
            v_params, v_rest = self._split(vs)
            # params: Σ w·(g − v)/τ  (zero-weight pad lanes contribute 0)
            # — folded into flat f32 carries like chunked_weighted_train
            # (flatten_carry_f32: one 1-D buffer per carry, no per-leaf
            # relayout copies across scan trips)
            coef = cw / jnp.maximum(taus, 1.0)
            d_chunk = jax.tree.map(
                lambda g, v: jnp.einsum(
                    "k,k...->...", coef,
                    g[None].astype(jnp.float32) - v.astype(jnp.float32)),
                g_params, v_params)
            dflat = dflat + flatten_carry_f32(d_chunk)[0]
            # stats collections: plain weighted mean, like FedAvg
            rflat = rflat + flatten_carry_f32(
                weighted_sum_tree(cw, v_rest))[0]
            return (dflat, rflat, den + jnp.sum(cw),
                    tsum + jnp.sum(cw * taus),
                    lsum + jnp.sum(losses * cw)), None

        zp, zr = self._split(jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), variables))
        zpf, d_spec = flatten_carry_f32(zp)
        zrf, r_spec = flatten_carry_f32(zr)
        zpf, zrf = pvary_tree(zpf, axes), pvary_tree(zrf, axes)
        zf = pvary_tree(jnp.float32(0), axes)
        (dflat, rflat, den, tsum, lsum), _ = jax.lax.scan(
            chunk_body, (zpf, zrf, zf, zf, zf), (ch_cohort, ch_w, ch_r))
        dsum = unflatten_carry_f32(dflat, d_spec)
        rest_num = unflatten_carry_f32(rflat, r_spec)
        return (jax.lax.psum(dsum, axes), jax.lax.psum(rest_num, axes),
                jax.lax.psum(den, axes), jax.lax.psum(tsum, axes),
                jax.lax.psum(lsum, axes))

    def _zero_sums(self, variables):
        zp, zr = self._split(jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), variables))
        return (zp, zr, jnp.float32(0), jnp.float32(0), jnp.float32(0))

    def _finalize_from_sums(self, variables, sums):
        dsum, rest_num, den, tsum, lsum = sums
        tau_eff = tsum / den
        gp, grest = self._split(variables)
        new_params = jax.tree.map(
            lambda g, d: (g.astype(jnp.float32)
                          - tau_eff * d / den).astype(g.dtype), gp, dsum)
        new = {"params": new_params,
               **jax.tree.map(lambda s, ref: (s / den).astype(ref.dtype),
                              rest_num, grest)}
        return new, lsum / den


class MeshRobustEngine(MeshFedAvgEngine):
    """Byzantine-robust FedAvg on the mesh.

    defense="norm_clip" (the reference's clip+weak-DP,
    robust_aggregation.py:38-55, FedAvgRobustAggregator.py:176-206) stays
    collective-only: per-client clipping inside the shard, then the psum.

    defense in {"krum", "multi_krum", "median", "trimmed_mean"} needs
    ORDER STATISTICS over the whole cohort's parameter vectors, which a
    weighted psum cannot express: each shard flattens its clients' trained params to a
    [k_local, P] f32 matrix (P padded to the ops/aggregate tile),
    all_gathers it over ICI into the replicated [K, P] cohort matrix, and
    applies the defense there (krum = one MXU gram matrix, median/trimmed
    = a sort along the client axis).  Memory bound: K·P·4 bytes per
    device — fine for the LR/CNN models these defenses are used with;
    past that, `stream_block` switches to the two-phase beyond-HBM path
    (_round_blockstream_orderstat below).  Cohort size must divide
    evenly over the mesh (zero-weight pad lanes have no principled place
    in a median), enforced at construction."""

    def _program_family_name(self, streaming: bool, stream_block) -> str:
        # the audit taxonomy's names: the resident order-stat round is
        # "robust_orderstat", the two-phase beyond-HBM path
        # "robust_blockstream" (norm_clip shares the resident program
        # shape and books under the same family)
        return ("robust_blockstream" if stream_block is not None
                else "robust_orderstat")

    def __init__(self, trainer, data, cfg, defense: str = "norm_clip",
                 n_byzantine: int = 0, multi_krum_m: Optional[int] = None,
                 param_block_bytes: int = 128 << 20, **kw):
        if defense not in ("norm_clip", "krum", "multi_krum", "median",
                           "trimmed_mean"):
            raise ValueError(f"unknown defense {defense!r}")
        self.defense = defense
        self.n_byzantine = n_byzantine
        self.multi_krum_m = robust_ops.default_multi_krum_m(
            min(cfg.client_num_per_round, data.client_num), n_byzantine,
            multi_krum_m)
        self.param_block_bytes = param_block_bytes
        super().__init__(trainer, data, cfg, **kw)
        if defense != "norm_clip" and self.batch_axes:
            # the order-stat scatter offsets index CLIENT rows per shard;
            # a batch axis would duplicate rows at distinct offsets
            raise ValueError(f"defense {defense!r} does not support a "
                             f"'batch' mesh axis (norm_clip does)")
        if defense != "norm_clip":
            K = min(cfg.client_num_per_round, data.client_num)
            if K % self.n_shards:
                raise ValueError(
                    f"defense {defense!r} needs the cohort ({K}) to divide "
                    f"evenly over the mesh ({self.n_shards} shards): order "
                    "statistics cannot ignore padded lanes")
            if self.stream_block is not None:
                if K % self.stream_block:
                    raise ValueError(
                        f"defense {defense!r} with stream_block needs the "
                        f"cohort ({K}) to be a block multiple "
                        f"({self.stream_block}): order statistics cannot "
                        "ignore padded lanes")
                if jax.process_count() > 1:
                    # phase 1 offloads each block's client-sharded flats
                    # with np.asarray — non-addressable across processes.
                    # Fail at construction like the other unsupported
                    # combinations, not mid-round after training work.
                    raise ValueError(
                        f"defense {defense!r} with stream_block is "
                        "single-process only: the host [K, P] matrix "
                        "offload needs every client shard addressable")
                # two-phase beyond-HBM path (VERDICT r4 #3): phase 1
                # trains client blocks and lands each block's flattened
                # params on HOST; phase 2 re-streams the [K, P] matrix
                # PARAMETER-major through the mesh for exact order stats
                # accumulators AND block inputs donated, same rationale
                # as the linear _block_step (O(2·block) device bound)
                self._block_step_flats = obs_programs.instrument(
                    self.program_family,
                    jax.jit(self._block_step_flats_impl,
                            donate_argnums=(1, 2, 3, 4)))
                # phase-2 [K, Pb] slices are uploaded fresh per call and
                # consumed exactly once — donate them, so a retired
                # slice's device memory recycles instead of stacking
                # next to the in-flight one (the O(K·Pb) bound).  Gated
                # on the donate flag (unlike the pre-existing always-
                # donated sums) so donate=False stays a complete
                # escape hatch and the bitwise donate-A/B pin really
                # compiles these programs both ways
                self._colstat = obs_programs.instrument(
                    self.program_family,
                    jax.jit(self._colstat_impl,
                            donate_argnums=(0,) if self.donate else ()))
                self._gram = obs_programs.instrument(
                    self.program_family,
                    jax.jit(self._gram_impl,
                            donate_argnums=(0,) if self.donate else ()))
                # new_flat (argnum 3) is engine-internal and dead after
                # the finalize — donated with the flag too
                self._orderstat_finalize = obs_programs.instrument(
                    self.program_family,
                    jax.jit(self._orderstat_finalize_impl,
                            donate_argnums=(0, 1, 2, 3)
                            if self.donate else (2,)))
                self.round_fn = self._round_blockstream_orderstat

    def client_transform(self, client_variables, weight, global_variables):
        if self.defense != "norm_clip":
            return client_variables
        out = dict(client_variables)
        out["params"] = robust_ops.norm_diff_clip(
            client_variables["params"], global_variables["params"],
            self.cfg.norm_bound)
        return out

    def server_update(self, avg_variables, global_variables, server_state, rng):
        if self.defense == "norm_clip" and self.cfg.stddev > 0:
            out = dict(avg_variables)
            out["params"] = robust_ops.add_weak_dp_noise(
                avg_variables["params"], rng, self.cfg.stddev)
            return out, server_state
        return avg_variables, server_state

    def _shard_body(self, variables, cohort, weights, client_rngs):
        if self.defense == "norm_clip":
            return super()._shard_body(variables, cohort, weights,
                                       client_rngs)
        from fedml_tpu.ops.aggregate import (flatten_stacked_tree,
                                             unflatten_to_tree)
        axes = self.mesh.axis_names
        rep_vars = variables
        variables = pvary_tree(variables, axes)
        local_vars = cast_local(variables, self.local_dtype)
        k_local = weights.shape[0]
        # the shared chunked loop, additionally emitting each client's
        # flattened trained params (prox term etc. included — one code
        # path with the norm_clip/FedAvg engines)
        num, den, lsum, flats = chunked_weighted_train(
            self.trainer, local_vars, cohort, weights, client_rngs,
            self.cfg.epochs, vary_axes=axes, chunk_cap=self.chunk,
            emit_flat_params=True, restore_x=self._restore_chunk_x)
        rest_num = {k: v for k, v in num.items() if k != "params"}
        # [n_chunks, chunk, P] -> this shard's clients; drop the in-chunk
        # pad lanes (they sit at the STATIC tail of the local stack)
        flats = flats.reshape(-1, flats.shape[-1])[:k_local]
        # replicated [K, P] cohort matrix: scatter this shard's rows into
        # zeros and psum — one collective, and unlike all_gather the
        # result is TYPED replicated (which the out_specs check needs)
        off = jnp.int32(0)
        for ax in axes:
            off = off * self.mesh.shape[ax] + jax.lax.axis_index(ax)
        full = jnp.zeros((k_local * self.n_shards, flats.shape[-1]),
                         flats.dtype)
        full = jax.lax.dynamic_update_slice(
            full, flats, (off * k_local, jnp.int32(0)))
        flats = jax.lax.psum(full, axes)
        if self.defense == "krum":
            i = robust_ops.krum_select_flat(flats, self.n_byzantine)
            new_flat = flats[i]
        elif self.defense == "multi_krum":
            idx = robust_ops.multi_krum_select_flat(
                flats, self.n_byzantine, self.multi_krum_m)
            new_flat = jnp.mean(flats[idx], axis=0)
        elif self.defense == "median":
            new_flat = jnp.median(flats, axis=0)
        else:                                 # trimmed_mean
            n = flats.shape[0]
            k = min(max(self.n_byzantine, 1), (n - 1) // 2)
            s = jnp.sort(flats, axis=0)
            new_flat = jnp.mean(s[k:n - k], axis=0)
        _, spec = flatten_stacked_tree(
            jax.tree.map(lambda a: a[None], rep_vars["params"]))
        new_params = unflatten_to_tree(new_flat, spec)
        rest_num = jax.lax.psum(rest_num, axes)
        den = jax.lax.psum(den, axes)
        grest = {k: v for k, v in rep_vars.items() if k != "params"}
        new = {"params": new_params,
               **jax.tree.map(lambda s, ref: (s / den).astype(ref.dtype),
                              rest_num, grest)}
        loss = jax.lax.psum(lsum, axes) / den
        return new, loss

    # -- block-streamed order statistics (VERDICT r4 #3) ---------------------
    # The linear engines stream CLIENT-major: blocks of clients cross
    # H2D and fold into O(P) sums.  Order statistics cannot fold, but
    # they CAN transpose: phase 1 streams client blocks through local
    # training and lands each block's flattened params on host — the
    # [K, P] cohort matrix lives in HOST RAM, never HBM; phase 2 streams
    # that matrix back PARAMETER-major in [K, Pb] slices, each sharded
    # over the mesh's param columns, where the defense is exact:
    #   median/trimmed_mean — per-column sort (no cross-column, and the
    #     column values are bitwise the resident path's, so the result
    #     is bitwise-equal to the in-HBM defense);
    #   krum — the Gram matrix G = Σ_b X_b X_bᵀ accumulates over param
    #     slices (one MXU matmul per slice + a psum), pairwise distances
    #     and the argmin score need only G [K, K].
    # Device memory: O(stream_block·P) in phase 1, O(K·Pb) in phase 2 —
    # both knobs, neither grows with K·P.  The reference's robust path
    # (robust_aggregation.py:32-55) is norm-clip only; this bounds the
    # framework's own beyond-reference defenses at reference-beating
    # cohort scale (SCALING.md "Order statistics beyond HBM").

    def _block_step_flats_impl(self, variables, sums, block, weights, rngs):
        """Phase-1 block step: train one client block, psum its linear
        stats sums into the (donated) accumulators, and emit the block's
        flattened trained params [B, P] client-sharded for host offload."""
        specs = {k: stack_leaf_spec(self.mesh, v) for k, v in block.items()}
        csh = P(self.client_axes)
        axes = self.mesh.axis_names

        def body(variables, cohort, w, r):
            v = pvary_tree(variables, axes)
            local_vars = cast_local(v, self.local_dtype)
            num, den, lsum, flats = chunked_weighted_train(
                self.trainer, local_vars, cohort, w, r, self.cfg.epochs,
                vary_axes=axes, chunk_cap=self.chunk,
                emit_flat_params=True, restore_x=self._restore_chunk_x)
            flats = flats.reshape(-1, flats.shape[-1])[:w.shape[0]]
            rest = {k: x for k, x in num.items() if k != "params"}
            return (jax.lax.psum(rest, axes), jax.lax.psum(den, axes),
                    jax.lax.psum(lsum, axes)), flats

        bsums, flats = jax.shard_map(
            body, mesh=self.mesh, in_specs=(P(), specs, csh, csh),
            out_specs=((P(), P(), P()), csh))(variables, block, weights,
                                              rngs)
        return jax.tree.map(lambda a, b: a + b, sums, bsums), flats

    def _zero_rest_sums(self, variables):
        rest = {k: v for k, v in variables.items() if k != "params"}
        return (jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                             rest), jnp.float32(0), jnp.float32(0))

    def _param_sharding(self):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, P(None, self.client_axes))

    def _colstat_impl(self, xb):
        """Per-column defense on one [K, Pb] param slice (columns sharded
        over the mesh; a sort is column-local, so no collectives)."""
        def body(x):
            if self.defense == "median":
                return jnp.median(x, axis=0)
            n = x.shape[0]
            k = min(max(self.n_byzantine, 1), (n - 1) // 2)
            s = jnp.sort(x, axis=0)
            return jnp.mean(s[k:n - k], axis=0)

        return jax.shard_map(
            body, mesh=self.mesh, in_specs=(P(None, self.client_axes),),
            out_specs=P(self.client_axes))(xb)

    def _gram_impl(self, xb):
        """One param slice's Gram contribution X_b X_bᵀ: the [K, Pb]
        slice is column-sharded, each shard's matmul runs on the MXU,
        one psum replicates the [K, K] partial."""
        def body(x):
            return jax.lax.psum(
                jnp.dot(x, x.T, preferred_element_type=jnp.float32),
                self.client_axes)

        return jax.shard_map(
            body, mesh=self.mesh, in_specs=(P(None, self.client_axes),),
            out_specs=P())(xb)

    def _orderstat_finalize_impl(self, variables, server_state, sums,
                                 new_flat, agg_rng):
        from fedml_tpu.ops.aggregate import (flatten_stacked_tree,
                                             unflatten_to_tree)
        rest_num, den, lsum = sums
        _, spec = flatten_stacked_tree(
            jax.tree.map(lambda a: a[None], variables["params"]))
        grest = {k: v for k, v in variables.items() if k != "params"}
        new = {"params": unflatten_to_tree(new_flat, spec),
               **jax.tree.map(lambda s, ref: (s / den).astype(ref.dtype),
                              rest_num, grest)}
        new, server_state = self.server_update(new, variables,
                                               server_state, agg_rng)
        return new, server_state, {"train_loss": lsum / den}

    def _krum_scores_from_gram(self, G: np.ndarray) -> np.ndarray:
        """core/robust.py::krum_scores_flat, from the Gram matrix
        (numpy: G is [K, K] — host-trivial next to the matmuls)."""
        sq = np.diag(G)
        d2 = np.maximum(sq[:, None] + sq[None, :] - 2.0 * G, 0.0)
        n = G.shape[0]
        k = max(n - self.n_byzantine - 2, 1)
        np.fill_diagonal(d2, np.inf)
        return np.sort(d2, axis=1)[:, :k].sum(axis=1)

    def _round_blockstream_orderstat(self, variables, server_state,
                                     round_idx, rng):
        """Two-phase block-streamed robust round (see class comment
        above).  Bitwise-equal to the resident defense for median/
        trimmed_mean (same values, same per-column ops); krum matches up
        to Gram summation order in the distance ties."""
        if self.defense == "norm_clip":      # linear — base path streams it
            return super()._round_blockstream(variables, server_state,
                                              round_idx, rng)
        ids, wmask = self._sample_padded_np(round_idx)
        assert wmask.all(), "order statistics cannot ignore padded lanes"
        K = len(ids)
        w_all = np.take(np.asarray(self.data.client_num_samples,
                                   np.float32), ids) * wmask
        rng, agg_rng = jax.random.split(rng)
        crngs = np.asarray(jax.random.split(rng, K))
        self.transfer_stats.round_start()
        try:
            with obs.span("round.blockstream_orderstat",
                          round=int(round_idx), clients=K,
                          defense=self.defense):
                return self._blockstream_orderstat_body(
                    variables, server_state, ids, w_all, crngs, agg_rng)
        finally:
            self.transfer_stats.round_end()

    def _blockstream_orderstat_body(self, variables, server_state, ids,
                                    w_all, crngs, agg_rng):
        B, K = self.stream_block, len(ids)
        sums = jax.device_put(self._zero_rest_sums(variables),
                              replicated_sharding(self.mesh))
        # phase 1: client-major blocks through the prefetch pipeline
        # (double-buffered background uploads — the np.asarray pull of
        # block k's flats overlaps block k+1's gather+upload), each
        # block's flats landing in the host matrix as compute proceeds
        X = None
        spans = [(s, s + B) for s in range(0, K, B)]
        with self._block_fetcher(ids, w_all, crngs, spans) as fetch:
            for start, stop in spans:
                sums, flats = self._block_step_flats(variables, sums,
                                                     *fetch.get())
                if X is None:
                    X = np.empty((K, flats.shape[1]), np.float32)
                X[start:stop] = np.asarray(flats)
                # np.asarray forced completion; drop the device buffer
                # NOW — holding it across the next block step would
                # stack [B, P] generations and break the O(block)
                # device bound
                flats.delete()
        # phase 2: parameter-major slices, Pb sized to param_block_bytes
        # of device footprint and mesh-divisible.  Only the FINAL short
        # slice is zero-padded (into its own [K, pb] buffer at upload
        # time — never np.pad the whole host matrix, which would
        # transiently double the very footprint this path exists to
        # bound); pad columns are sliced off the result.
        P_flat = X.shape[1]
        unit = self.n_shards
        pb = max(1, self.param_block_bytes // (K * 4) // unit) * unit
        pb = min(pb, -(-P_flat // unit) * unit)
        n_slices = -(-P_flat // pb)

        def slice_padded(s):
            # phase-2 H2D is upload wall too (the [K, P] matrix crosses
            # back slice by slice), and it runs INLINE on the round
            # loop, so it is simultaneously consumer wait — recording
            # both keeps overlap_fraction honest: this traversal is
            # unhidden transfer, not compute (the OSB256 metric)
            with self.transfer_stats.uploading(), \
                    self.transfer_stats.waiting():
                xb = X[:, s * pb:(s + 1) * pb]
                if xb.shape[1] < pb:
                    buf = np.zeros((K, pb), np.float32)
                    buf[:, :xb.shape[1]] = xb
                    xb = buf
                self.transfer_stats.add_h2d_bytes(K * pb * 4)
                return jax.device_put(xb, self._param_sharding())

        if self.defense in ("krum", "multi_krum"):
            G = np.zeros((K, K), np.float32)
            for s in range(n_slices):
                G += np.asarray(self._gram(slice_padded(s)))
            scores = self._krum_scores_from_gram(G)
            if self.defense == "krum":
                new_flat = jnp.asarray(X[int(np.argmin(scores))])
            else:
                idx = np.argsort(scores)[:self.multi_krum_m]
                new_flat = jnp.asarray(
                    np.mean(X[idx], axis=0, dtype=np.float32))
        else:
            out = np.empty(n_slices * pb, np.float32)
            for s in range(n_slices):
                out[s * pb:(s + 1) * pb] = np.asarray(
                    self._colstat(slice_padded(s)))
            new_flat = jnp.asarray(out[:P_flat])
        return self._orderstat_finalize(variables, server_state, sums,
                                        new_flat, agg_rng)
