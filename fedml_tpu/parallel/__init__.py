"""parallel — the mesh/collective federated engine (L1 of the rebuild).

The reference's "distributed" layer is one OS process per logical client
exchanging pickled state dicts over MPI point-to-point sends
(fedml_core/distributed/communication/mpi/com_manager.py:13-98); even its
server-side aggregation is a Python dict-of-tensors loop on CPU
(fedml_api/distributed/fedavg/FedAVGAggregator.py:59-88).

TPU-native, clients are a *mesh axis*: per-client datasets live HBM-sharded
across devices, local SGD runs as vmap-of-scan inside `shard_map`, and the
sample-weighted FedAvg aggregation is literally

    psum(w_i * n_i) / psum(n_i)

over ICI.  Hierarchical FL maps onto a 2-D mesh — inner `psum` over the
intra-silo axis (ICI), outer `psum` over the cross-silo axis (DCN) — and
decentralized gossip is `lax.ppermute` neighbor exchange over a mesh ring.
"""
from fedml_tpu.parallel.mesh import (make_mesh, make_mesh_batch,
                                     client_sharding, replicated_sharding,
                                     shard_cohort)
from fedml_tpu.parallel.engine import (MeshFedAvgEngine, MeshFedNovaEngine,
                                       MeshFedOptEngine, MeshFedProxEngine,
                                       MeshRobustEngine)
from fedml_tpu.parallel.hierarchical import MeshHierarchicalEngine
from fedml_tpu.parallel.gossip import MeshGossipEngine
from fedml_tpu.parallel.multihost import (HostChannel, MultihostContext,
                                          MultihostRunner, init_multihost,
                                          make_global_mesh,
                                          make_hierarchical_host_mesh,
                                          make_local_mesh, spawn_cluster)
