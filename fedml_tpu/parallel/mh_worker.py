"""Multihost worker entry — one rank of a launched cluster.

    python -m fedml_tpu.parallel.mh_worker CONFIG.json

Reads its rank/world from the FEDML_MH_* env (set by
tools/launch_multihost.py / spawn_cluster), builds a synthetic
LR workload, drives MultihostRunner for the configured residency
mode(s), and prints ONE JSON line per rank:

    {"rank", "world", "n_blocks", "digests": {mode: md5},
     "rounds_per_sec", "carry_allreduce_bytes_per_round", ...}

Used by bench.py --mode multihost (the weak-scaling sweep) and
tests/test_multihost_spmd.py (the 2-vs-1-process bitwise pin, the
crash-of-one-rank naming case).  Not a test file itself.

Config keys (all optional; defaults in DEFAULTS):
    clients, spc, dim, classes, k_per_round, n_blocks, rounds, warmup,
    seed, modes ["streaming","resident"], local_devices, lr,
    channel_timeout_s, die_rank/die_at_round (crash injection: that
    rank hard-exits rc=3 at the end of that round), jax_distributed,
    eval (bool: report final test_acc from rank 0)
"""
import json
import os
import sys
import time

DEFAULTS = {
    "clients": 16, "spc": 24, "dim": 16, "classes": 10,
    "k_per_round": 8, "n_blocks": None, "rounds": 3, "warmup": 1,
    "seed": 0, "modes": ["streaming", "resident"], "local_devices": 1,
    "lr": 0.1, "channel_timeout_s": 60.0, "die_rank": None,
    "die_at_round": None, "jax_distributed": False, "eval": False,
}


def _setup_jax(cfg: dict) -> None:
    """Platform/device-count/compile-cache config — BEFORE any jax
    backend init (the init_multihost contract)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
              f"{cfg['local_devices']}")
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    cache = os.path.expanduser("~/.cache/fedml_tpu_jax_tests")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:
        pass


def build_case(cfg: dict):
    """Synthetic separable-LR federated case — same shape as
    tests/multihost_case.py but parameterized and package-local (the
    bench worker must not import tests/)."""
    import numpy as np
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.federated import (FederatedData,
                                          build_client_shards,
                                          build_eval_shard)
    from fedml_tpu.models import create_model
    from fedml_tpu.parallel.engine import MeshFedAvgEngine
    from fedml_tpu.parallel.multihost import make_local_mesh
    from fedml_tpu.utils.config import FedConfig

    C, spc, dim, classes = (cfg["clients"], cfg["spc"], cfg["dim"],
                            cfg["classes"])
    bs = min(8, spc)
    rs = np.random.RandomState(7)
    n = C * spc
    w = rs.randn(dim, classes)
    x = rs.randn(n, dim).astype(np.float32)
    y = np.argmax(x @ w + 0.2 * rs.randn(n, classes),
                  axis=1).astype(np.int64)
    idx = {i: np.arange(i * spc, (i + 1) * spc) for i in range(C)}
    data = FederatedData(
        train_data_num=n, test_data_num=n,
        train_global=build_eval_shard(x, y, n),
        test_global=build_eval_shard(x, y, n),
        client_shards=build_client_shards(x, y, idx, bs),
        client_num_samples=np.full(C, spc, np.float32),
        test_client_shards=None, class_num=classes)
    fedcfg = FedConfig(client_num_in_total=C,
                       client_num_per_round=cfg["k_per_round"],
                       comm_round=cfg["rounds"], epochs=1,
                       batch_size=bs, lr=cfg["lr"], seed=cfg["seed"],
                       frequency_of_the_test=10_000)
    model = create_model("lr", output_dim=classes)

    def make_engine(streaming: bool):
        return MeshFedAvgEngine(ClientTrainer(model, lr=fedcfg.lr),
                                data, fedcfg, mesh=make_local_mesh(),
                                streaming=streaming)

    return make_engine


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fedml_tpu.parallel.mh_worker CONFIG.json",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        cfg = {**DEFAULTS, **json.load(f)}
    _setup_jax(cfg)
    import jax

    from fedml_tpu.parallel.multihost import (HostChannel,
                                              MultihostContext,
                                              MultihostRunner,
                                              init_multihost,
                                              variables_digest)
    ctx = MultihostContext.from_env() or MultihostContext.single()
    if cfg["jax_distributed"] and ctx.jax_coordinator:
        init_multihost(coordinator_address=ctx.jax_coordinator,
                       num_processes=ctx.world, process_id=ctx.rank,
                       required=True)
    make_engine = build_case(cfg)
    n_blocks = cfg["n_blocks"] or ctx.world

    def on_round_end(round_idx: int) -> None:
        if (cfg["die_rank"] == ctx.rank
                and cfg["die_at_round"] == round_idx):
            print(f"rank {ctx.rank}: injected crash at round "
                  f"{round_idx}", file=sys.stderr, flush=True)
            os._exit(3)

    # ONE channel for the whole worker (both residency modes ride it;
    # re-binding the coordinator port between modes would race peers)
    channel = HostChannel(ctx, timeout_s=cfg["channel_timeout_s"])
    out = {"rank": ctx.rank, "world": ctx.world, "n_blocks": n_blocks,
           "digests": {}, "per_mode": {}}
    try:
        for mode in cfg["modes"]:
            if mode not in ("streaming", "resident"):
                raise SystemExit(f"unknown residency mode {mode!r}")
            engine = make_engine(streaming=(mode == "streaming"))
            runner = MultihostRunner(
                engine, ctx, n_blocks=n_blocks, channel=channel,
                timeout_s=cfg["channel_timeout_s"],
                on_round_end=on_round_end)
            t0 = time.perf_counter()
            variables = runner.run(rounds=cfg["rounds"])
            wall = time.perf_counter() - t0
            rep = runner.report(warmup_rounds=cfg["warmup"])
            rep["total_wall_s"] = wall
            out["digests"][mode] = variables_digest(variables)
            out["per_mode"][mode] = rep
            if cfg["eval"] and ctx.rank == 0:
                out.setdefault("eval", {})[mode] = \
                    engine.evaluate(variables)["test_acc"]
        # headline timing: the streaming mode when run, else the first
        head = ("streaming" if "streaming" in out["per_mode"]
                else next(iter(out["per_mode"])))
        out["rounds_per_sec"] = out["per_mode"][head]["rounds_per_sec"]
        out["carry_allreduce_bytes_per_round"] = \
            out["per_mode"][head]["carry_allreduce_bytes_per_round"]
        out["jax"] = jax.__version__
        print(json.dumps(out), flush=True)
    finally:
        channel.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
