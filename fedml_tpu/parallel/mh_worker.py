"""Multihost worker entry — one rank of a launched cluster.

    python -m fedml_tpu.parallel.mh_worker CONFIG.json

Reads its rank/world from the FEDML_MH_* env (set by
tools/launch_multihost.py / spawn_cluster), builds a synthetic
LR workload, drives MultihostRunner for the configured residency
mode(s), and prints ONE JSON line per rank:

    {"rank", "world", "n_blocks", "digests": {mode: md5},
     "rounds_per_sec", "carry_allreduce_bytes_per_round", ...}

Used by bench.py --mode multihost (the weak-scaling sweep) and
tests/test_multihost_spmd.py (the 2-vs-1-process bitwise pin, the
crash-of-one-rank naming case).  Not a test file itself.

Config keys (all optional; defaults in DEFAULTS):
    clients, spc, dim, classes, k_per_round, n_blocks, rounds, warmup,
    seed, modes ["streaming","resident"], local_devices, lr,
    channel_timeout_s, die_rank/die_at_round (crash injection: that
    rank hard-exits rc=3 at the end of that round), jax_distributed,
    eval (bool: report final test_acc from rank 0)

ISSUE 14 (elastic) keys:
    elastic (bool: ElasticRunner/ElasticChannel — rank death triggers a
    view change + block re-adoption instead of cluster teardown; a
    respawned rank with FEDML_MH_REJOIN=1 in its env rejoins the run),
    hang_rank/hang_at_round/hang_s (hang injection: that rank pauses
    its heartbeats and sleeps hang_s at the end of that round — the
    SIGSTOP shape; the coordinator must evict it via heartbeat timeout
    and the evicted rank exits rc=4 when it wakes into a closed
    channel), hb_timeout_s/hb_interval_s (elastic failure detector).

ISSUE 16 (compressed carry) keys:
    carry_codec ("f32" default escape hatch | "int8" | "int8_ef"),
    carry_chunk (f32 elements per quantization scale), and
    overlap_exchange (bool: pipeline each block's encoded carry under
    the remaining blocks' compute).
"""
import json
import os
import sys
import time

DEFAULTS = {
    "clients": 16, "spc": 24, "dim": 16, "classes": 10,
    "k_per_round": 8, "n_blocks": None, "rounds": 3, "warmup": 1,
    "seed": 0, "modes": ["streaming", "resident"], "local_devices": 1,
    "lr": 0.1, "channel_timeout_s": 60.0, "die_rank": None,
    "die_at_round": None, "jax_distributed": False, "eval": False,
    "elastic": False, "hang_rank": None, "hang_at_round": None,
    "hang_s": 20.0, "hb_timeout_s": 2.0, "hb_interval_s": 0.25,
    "round_sleep_s": 0.0, "round_sleep_mode": None,
    # ISSUE 16: compressed + overlapped carry exchange.  carry_codec
    # f32|int8|int8_ef (f32 = the bitwise escape hatch), carry_chunk =
    # f32 elements per quantization scale, overlap_exchange pipelines
    # each block's encoded carry under the remaining blocks' compute
    "carry_codec": "f32", "carry_chunk": None,
    "overlap_exchange": False,
    # ISSUE 18: serve_cluster (dict | None) routes the worker into the
    # fused serving cluster instead of the training engines — this
    # rank binds a reactor on its endpoint port and serves live-socket
    # uplinks into its registry-shard lanes, folding partials
    # cross-host at each commit barrier.  Keys (all optional):
    # population, commits, warmup_commits, buffer_k, row_dim,
    # connections, ingest_pool, window_deadline_s, timeout_s,
    # ports [per-rank endpoint list] | base_port (port = base + rank),
    # chaos {wire-fault dict}, chaos_seed, die_rank/die_at_commit
    # (crash injection: that rank hard-exits rc=3 after that many
    # commits — the survivors' next exchange evicts it), slo (bool),
    # sparse_uplink (bool — ISSUE 19: accept sparse_topk frames via
    # the decode_sparse -> jitted scatter-fold path).
    "serve_cluster": None,
}


def _setup_jax(cfg: dict) -> None:
    """Platform/device-count/compile-cache config — BEFORE any jax
    backend init (the init_multihost contract)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
              f"{cfg['local_devices']}")
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    cache = os.path.expanduser("~/.cache/fedml_tpu_jax_tests")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:
        pass


def build_case(cfg: dict):
    """Synthetic separable-LR federated case — same shape as
    tests/multihost_case.py but parameterized and package-local (the
    bench worker must not import tests/)."""
    import numpy as np
    from fedml_tpu.core.trainer import ClientTrainer
    from fedml_tpu.data.federated import (FederatedData,
                                          build_client_shards,
                                          build_eval_shard)
    from fedml_tpu.models import create_model
    from fedml_tpu.parallel.engine import MeshFedAvgEngine
    from fedml_tpu.parallel.multihost import make_local_mesh
    from fedml_tpu.utils.config import FedConfig

    C, spc, dim, classes = (cfg["clients"], cfg["spc"], cfg["dim"],
                            cfg["classes"])
    bs = min(8, spc)
    rs = np.random.RandomState(7)
    n = C * spc
    w = rs.randn(dim, classes)
    x = rs.randn(n, dim).astype(np.float32)
    y = np.argmax(x @ w + 0.2 * rs.randn(n, classes),
                  axis=1).astype(np.int64)
    idx = {i: np.arange(i * spc, (i + 1) * spc) for i in range(C)}
    data = FederatedData(
        train_data_num=n, test_data_num=n,
        train_global=build_eval_shard(x, y, n),
        test_global=build_eval_shard(x, y, n),
        client_shards=build_client_shards(x, y, idx, bs),
        client_num_samples=np.full(C, spc, np.float32),
        test_client_shards=None, class_num=classes)
    fedcfg = FedConfig(client_num_in_total=C,
                       client_num_per_round=cfg["k_per_round"],
                       comm_round=cfg["rounds"], epochs=1,
                       batch_size=bs, lr=cfg["lr"], seed=cfg["seed"],
                       frequency_of_the_test=10_000)
    model = create_model("lr", output_dim=classes)

    def make_engine(streaming: bool):
        return MeshFedAvgEngine(ClientTrainer(model, lr=fedcfg.lr),
                                data, fedcfg, mesh=make_local_mesh(),
                                streaming=streaming)

    return make_engine


def _serve_cluster_main(ctx, cfg: dict) -> int:
    """ISSUE 18: one host of the fused serving cluster.  Builds the
    elastic channel (world > 1), runs run_cluster_serve on this rank's
    endpoint port, and prints ONE JSON line — the same contract the
    training route honors, so spawn_cluster_report parses both.  A
    rank with crash injection armed exits rc=3 WITHOUT a JSON line
    (the launcher's blame report names it; the survivors' reports are
    the evidence)."""
    import hashlib

    from fedml_tpu.parallel.multihost import ElasticChannel
    from fedml_tpu.scale.cluster import run_cluster_serve

    sc = dict(cfg["serve_cluster"])
    channel = None
    crashed = False
    if ctx.world > 1:
        # config digest covers the WHOLE worker config — a skewed rank
        # is rejected by name at hello, exactly as the training route
        digest = hashlib.md5(json.dumps(
            cfg, sort_keys=True).encode()).hexdigest()
        channel = ElasticChannel(
            ctx, n_items=ctx.world, config_digest=digest,
            timeout_s=cfg["channel_timeout_s"],
            hb_interval_s=cfg["hb_interval_s"],
            hb_timeout_s=cfg["hb_timeout_s"])
    ports = sc.get("ports")
    port = (int(ports[ctx.rank]) if ports
            else int(sc.get("base_port", 54300)) + ctx.rank)
    crash_at = (sc.get("die_at_commit")
                if sc.get("die_rank") == ctx.rank else None)
    try:
        report = run_cluster_serve(
            int(sc.get("population", 4096)),
            commits=int(sc.get("commits", 8)),
            warmup_commits=int(sc.get("warmup_commits", 2)),
            buffer_k=int(sc.get("buffer_k", 16)),
            row_dim=int(sc.get("row_dim", 256)),
            port=port, partition=(ctx.rank, ctx.world),
            channel=channel, elastic=ctx.world > 1,
            n_connections=int(sc.get("connections", 64)),
            ingest_pool=int(sc.get("ingest_pool", 2)),
            sparse_uplink=bool(sc.get("sparse_uplink", False)),
            window_deadline_s=float(sc.get("window_deadline_s", 10.0)),
            timeout_s=float(sc.get("timeout_s", 600.0)),
            chaos=sc.get("chaos"),
            chaos_seed=int(sc.get("chaos_seed", 0)),
            crash_at_commit=crash_at,
            slo_window=bool(sc.get("slo", ctx.rank == 0)))
        crashed = bool(crash_at is not None
                       and report.get("elastic", {})
                                .get("crashed_at_commit") is not None)
    finally:
        if channel is not None and not crashed:
            channel.close()
    if crashed:
        print(f"rank {ctx.rank}: injected crash at commit {crash_at}",
              file=sys.stderr, flush=True)
        os._exit(3)
    print(json.dumps({"rank": ctx.rank, "world": ctx.world,
                      "serve_cluster": report}), flush=True)
    return 0


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fedml_tpu.parallel.mh_worker CONFIG.json",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        cfg = {**DEFAULTS, **json.load(f)}
    _setup_jax(cfg)
    import hashlib

    import jax

    from fedml_tpu.parallel.multihost import (DeadRankError,
                                              ElasticChannel,
                                              ElasticRunner,
                                              HostChannel,
                                              MultihostContext,
                                              MultihostRunner,
                                              init_multihost,
                                              variables_digest)
    ctx = MultihostContext.from_env() or MultihostContext.single()
    if cfg["jax_distributed"] and ctx.jax_coordinator:
        init_multihost(coordinator_address=ctx.jax_coordinator,
                       num_processes=ctx.world, process_id=ctx.rank,
                       required=True)
    make_engine = build_case(cfg)
    n_blocks = cfg["n_blocks"] or ctx.world
    rejoining = (os.environ.get("FEDML_MH_REJOIN") == "1"
                 and ctx.rank != 0)
    obs_root = os.environ.get("FEDML_OBS_DIR")
    if obs_root:
        # per-RANK obs namespace, same scheme as the cli (ISSUE 17):
        # co-spawned workers handed one dir would race each other's
        # exports, and a rejoining incarnation reuses its rank id —
        # namespace it by pid so both incarnations' traces survive.
        # Enabling obs here also arms the telemetry piggybacks and the
        # coordinated-dump fan-out; with the env unset the wire stays
        # byte-identical to the pre-observatory channel.
        from fedml_tpu import obs
        sub = f"rank{ctx.rank}"
        if os.environ.get("FEDML_MH_REJOIN") == "1":
            sub = f"rank{ctx.rank}-pid{os.getpid()}"
        obs.configure(os.path.join(obs_root, sub))

    if cfg["serve_cluster"]:
        # ISSUE 18: the fused serving cluster — no training engines,
        # no residency modes; the rank serves live sockets instead
        return _serve_cluster_main(ctx, cfg)

    current_mode = {"mode": None}

    def on_round_end(round_idx: int) -> None:
        if cfg["round_sleep_s"] > 0 and (
                cfg["round_sleep_mode"] is None
                or cfg["round_sleep_mode"] == current_mode["mode"]):
            # pacing for the rejoin pins: synthetic rounds finish in
            # milliseconds, far faster than a respawned process can
            # boot jax — a per-round sleep holds the run open so the
            # rejoin handshake lands mid-run, deterministically
            # (round_sleep_mode scopes it to the run being rejoined)
            time.sleep(float(cfg["round_sleep_s"]))
        if (cfg["die_rank"] == ctx.rank
                and cfg["die_at_round"] == round_idx
                and not rejoining):
            print(f"rank {ctx.rank}: injected crash at round "
                  f"{round_idx}", file=sys.stderr, flush=True)
            os._exit(3)
        if (cfg["hang_rank"] == ctx.rank
                and cfg["hang_at_round"] == round_idx
                and not rejoining):
            # the SIGSTOP shape without stopping the OS process (a
            # truly stopped child never exits, which would wedge the
            # launcher): heartbeats pause, the rank goes silent for
            # hang_s, and the coordinator must evict it via heartbeat
            # timeout — waking into the closed channel exits rc=4
            print(f"rank {ctx.rank}: injected hang at round "
                  f"{round_idx} for {cfg['hang_s']:.0f}s",
                  file=sys.stderr, flush=True)
            channel.hb_paused = True
            time.sleep(float(cfg["hang_s"]))
            channel.hb_paused = False

    # ONE channel for the whole worker (both residency modes ride it;
    # re-binding the coordinator port between modes would race peers).
    # The elastic config digest covers the WHOLE worker config — any
    # skewed rank (or stale rejoiner) is rejected by name at hello.
    if cfg["elastic"]:
        digest = hashlib.md5(json.dumps(
            cfg, sort_keys=True).encode()).hexdigest()
        channel = ElasticChannel(
            ctx, n_items=n_blocks, config_digest=digest,
            timeout_s=cfg["channel_timeout_s"],
            hb_interval_s=cfg["hb_interval_s"],
            hb_timeout_s=cfg["hb_timeout_s"],
            rejoin=rejoining)
    else:
        channel = HostChannel(ctx, timeout_s=cfg["channel_timeout_s"])
    out = {"rank": ctx.rank, "world": ctx.world, "n_blocks": n_blocks,
           "elastic": bool(cfg["elastic"]),
           "rejoined": bool(rejoining),
           "digests": {}, "per_mode": {}}
    modes = list(cfg["modes"])
    for mode in modes:
        if mode not in ("streaming", "resident"):
            raise SystemExit(f"unknown residency mode {mode!r}")
    rejoin_state = None
    if cfg["elastic"] and rejoining:
        # handshake BEFORE building any engine: the SNAPSHOT's run tag
        # names which residency-mode run the coordinator is in — a
        # respawned process must resume THAT run, not replay the mode
        # list from the top (the sequential runs share one channel, so
        # rejoining the wrong one would cross-wire the exchanges)
        blob, resume_round, tag = channel.rejoin_handshake()
        if tag in modes:
            skipped, modes = modes[:modes.index(tag)], \
                modes[modes.index(tag):]
            if skipped:
                print(f"rank {ctx.rank}: rejoined into {tag!r}; "
                      f"skipping completed mode(s) {skipped}",
                      file=sys.stderr, flush=True)
        rejoin_state = (blob, resume_round)
    try:
        for mi, mode in enumerate(modes):
            current_mode["mode"] = mode
            engine = make_engine(streaming=(mode == "streaming"))
            codec_kw = {"carry_codec": cfg["carry_codec"],
                        "carry_chunk": cfg["carry_chunk"],
                        "overlap_exchange": cfg["overlap_exchange"]}
            if cfg["elastic"]:
                runner = ElasticRunner(
                    engine, ctx, n_blocks=n_blocks, channel=channel,
                    timeout_s=cfg["channel_timeout_s"],
                    hb_interval_s=cfg["hb_interval_s"],
                    hb_timeout_s=cfg["hb_timeout_s"],
                    run_tag=mode,
                    on_round_end=on_round_end, **codec_kw)
            else:
                runner = MultihostRunner(
                    engine, ctx, n_blocks=n_blocks, channel=channel,
                    timeout_s=cfg["channel_timeout_s"],
                    on_round_end=on_round_end, **codec_kw)
            t0 = time.perf_counter()
            try:
                if cfg["elastic"]:
                    # only the FIRST runner of a respawned process
                    # resumes mid-run; later modes start as a member
                    variables = runner.run(
                        rounds=cfg["rounds"], rejoin=False,
                        rejoin_state=(rejoin_state if mi == 0
                                      else None))
                else:
                    variables = runner.run(rounds=cfg["rounds"])
            except DeadRankError as e:
                if (cfg["hang_rank"] == ctx.rank
                        and not rejoining):
                    # the injected hang got this rank evicted — the
                    # intended outcome; exit distinctly so the
                    # launcher's blame report shows rc=4, not a crash
                    print(f"rank {ctx.rank}: evicted after injected "
                          f"hang: {e}", file=sys.stderr, flush=True)
                    return 4
                raise
            wall = time.perf_counter() - t0
            rep = runner.report(warmup_rounds=cfg["warmup"])
            rep["total_wall_s"] = wall
            out["digests"][mode] = variables_digest(variables)
            out["per_mode"][mode] = rep
            if cfg["eval"] and ctx.rank == 0:
                out.setdefault("eval", {})[mode] = \
                    engine.evaluate(variables)["test_acc"]
        # headline timing: the streaming mode when run, else the first
        head = ("streaming" if "streaming" in out["per_mode"]
                else next(iter(out["per_mode"])))
        out["rounds_per_sec"] = out["per_mode"][head]["rounds_per_sec"]
        out["carry_allreduce_bytes_per_round"] = \
            out["per_mode"][head]["carry_allreduce_bytes_per_round"]
        for k in ("carry_codec", "carry_compression_ratio",
                  "carry_wire_sent_bytes_per_round",
                  "carry_payload_bytes_per_round",
                  "carry_raw_bytes_per_round", "overlap_fraction"):
            out[k] = out["per_mode"][head][k]
        if ctx.rank == 0:
            # cluster observatory (ISSUE 17): the coordinator's barrier
            # ledger + cluster SLO verdict ride the worker doc — both
            # are always-on local bookkeeping, so the bench straggler
            # block and the spawned test pins read them without
            # enabling obs
            from fedml_tpu.obs import cluster as cluster_mod
            out["straggler"] = cluster_mod.straggler_summary()
            out["cluster_slo"] = cluster_mod.cluster_slo_report()
        out["jax"] = jax.__version__
        print(json.dumps(out), flush=True)
    finally:
        channel.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
