"""Mesh construction + sharding helpers.

Replaces the reference's process/placement machinery: `mpirun -np W+1` +
gpu_mapping.yaml rank→GPU tables (fedml_api/distributed/utils/
gpu_mapping.py:8-39).  Here "placement" is a `jax.sharding.Mesh` and a
`PartitionSpec`; the runtime below (XLA) moves the bytes.

Axis conventions used throughout the framework:

  "clients"  — the federated data-parallel axis (cohort dimension K).
  "silo"     — the cross-silo / DCN tier for hierarchical FL (2-D meshes).

Multi-host note: on a real pod these helpers take `jax.devices()` spanning
hosts; ICI carries the "clients" psum within a slice and DCN the "silo"
reductions, exactly the two-tier layout of SURVEY.md §2.5.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any

CLIENT_AXIS = "clients"
SILO_AXIS = "silo"


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = CLIENT_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over `n_devices` (default: all local devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(n_silos: int, per_silo: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> Mesh:
    """2-D (silo × clients) mesh for hierarchical FL (SURVEY.md §2.5:
    'psum within ICI slice, DCN cross-slice')."""
    devs = list(devices) if devices is not None else jax.devices()
    per_silo = per_silo if per_silo is not None else len(devs) // n_silos
    devs = devs[: n_silos * per_silo]
    grid = np.array(devs).reshape(n_silos, per_silo)
    return Mesh(grid, (SILO_AXIS, CLIENT_AXIS))


def pvary_tree(tree: Pytree, axis_names) -> Pytree:
    """Mark a replicated pytree as varying over `axis_names` inside
    shard_map (needed before per-shard scans/vmaps mutate it, else the
    vma type-check rejects the scan carry)."""
    return jax.tree.map(
        lambda a: jax.lax.pcast(a, axis_names, to="varying"), tree)


def client_sharding(mesh: Mesh) -> NamedSharding:
    """Shard a [K, ...] cohort/stack along its leading (client) axis over
    every mesh axis — on a 2-D mesh clients are split over silo×clients."""
    return NamedSharding(mesh, P(mesh.axis_names))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_cohort(mesh: Mesh, cohort: Pytree) -> Pytree:
    """Place a host-side cohort {x,y,mask}[K,...] (+weights [K]) onto the
    mesh, leading axis split across devices. K must divide evenly — callers
    pad the cohort with zero-weight clients otherwise (pad_cohort)."""
    sh = client_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), cohort)


def pad_cohort(cohort: dict, weights: np.ndarray, multiple: int):
    """Pad cohort to a multiple of the mesh size with zero-weight dummy
    clients (mask=0 ⇒ their local_train is a no-op and weight 0 drops them
    from the psum numerator and denominator)."""
    K = int(weights.shape[0])
    pad = (-K) % multiple
    if pad == 0:
        return cohort, weights
    def pad_leaf(a):
        z = np.zeros((pad,) + tuple(a.shape[1:]), a.dtype)
        return np.concatenate([np.asarray(a), z], axis=0)
    cohort = {k: pad_leaf(v) for k, v in cohort.items()}
    weights = np.concatenate([np.asarray(weights),
                              np.zeros(pad, np.asarray(weights).dtype)])
    return cohort, weights
