"""Mesh construction + sharding helpers.

Replaces the reference's process/placement machinery: `mpirun -np W+1` +
gpu_mapping.yaml rank→GPU tables (fedml_api/distributed/utils/
gpu_mapping.py:8-39).  Here "placement" is a `jax.sharding.Mesh` and a
`PartitionSpec`; the runtime below (XLA) moves the bytes.

Axis conventions used throughout the framework:

  "clients"  — the federated data-parallel axis (cohort dimension K).
  "silo"     — the cross-silo / DCN tier for hierarchical FL (2-D meshes).
  "batch"    — per-client sample parallelism (each client's per-step batch
               split over devices, grads psum'd per step): the scaling
               axis once chips outnumber the cohort (PERF.md v4-128
               projection break #1/#2).

Multi-host note: on a real pod these helpers take `jax.devices()` spanning
hosts; ICI carries the "clients" psum within a slice and DCN the "silo"
reductions, exactly the two-tier layout of SURVEY.md §2.5.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any

CLIENT_AXIS = "clients"
SILO_AXIS = "silo"
BATCH_AXIS = "batch"


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = CLIENT_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over `n_devices` (default: all local devices)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(n_silos: int, per_silo: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> Mesh:
    """2-D (silo × clients) mesh for hierarchical FL (SURVEY.md §2.5:
    'psum within ICI slice, DCN cross-slice')."""
    devs = list(devices) if devices is not None else jax.devices()
    per_silo = per_silo if per_silo is not None else len(devs) // n_silos
    devs = devs[: n_silos * per_silo]
    grid = np.array(devs).reshape(n_silos, per_silo)
    return Mesh(grid, (SILO_AXIS, CLIENT_AXIS))


def make_mesh_batch(n_client_shards: int, n_batch: int,
                    devices: Optional[Sequence] = None) -> Mesh:
    """2-D (clients × batch) mesh: the cohort splits over the first axis
    and each client's per-step batch over the second.  This is the layout
    for chips > cohort (PERF.md projection break #2): with K clients and
    N = K·b chips, every client trains on b devices at once."""
    devs = list(devices) if devices is not None else jax.devices()
    devs = devs[: n_client_shards * n_batch]
    grid = np.array(devs).reshape(n_client_shards, n_batch)
    return Mesh(grid, (CLIENT_AXIS, BATCH_AXIS))


def pvary_tree(tree: Pytree, axis_names) -> Pytree:
    """Mark a replicated pytree as varying over `axis_names` inside
    shard_map (needed before per-shard scans/vmaps mutate it, else the
    vma type-check rejects the scan carry)."""
    return jax.tree.map(
        lambda a: jax.lax.pcast(a, axis_names, to="varying"), tree)


def client_axes(mesh: Mesh) -> tuple:
    """The mesh axes that shard the CLIENT dimension — every axis except
    "batch" (which shards within-client samples instead)."""
    return tuple(a for a in mesh.axis_names if a != BATCH_AXIS)


def client_sharding(mesh: Mesh) -> NamedSharding:
    """Shard a [K, ...] cohort/stack along its leading (client) axis over
    the client axes — on a silo×clients mesh clients split over both; a
    "batch" axis never shards the client dim (replicated there)."""
    return NamedSharding(mesh, P(client_axes(mesh)))


def _splits_batch(mesh: Mesh, leaf) -> bool:
    """Whether a stack leaf's per-step sample dim (axis 2) splits over the
    "batch" axis.  A non-dividing sample dim falls back to replication
    along "batch" — still numerically correct (each shard then holds the
    full batch and the trainer's S/C_g normalization makes the per-step
    psum a mean over identical contributions), just without the split."""
    return (BATCH_AXIS in mesh.axis_names and np.ndim(leaf) >= 3
            and np.shape(leaf)[2] % mesh.shape[BATCH_AXIS] == 0)


def stack_leaf_sharding(mesh: Mesh, leaf) -> NamedSharding:
    """Per-leaf sharding for a client data stack {x,y,mask}[C,B,bs,...]:
    the client dim over the client axes and — when the mesh has a "batch"
    axis — the per-step sample dim (axis 2) over it.  Weight/[C] leaves
    fall back to client_sharding."""
    ca = client_axes(mesh)
    if _splits_batch(mesh, leaf):
        return NamedSharding(mesh, P(ca, None, BATCH_AXIS))
    return NamedSharding(mesh, P(ca))


def stack_leaf_spec(mesh: Mesh, leaf) -> P:
    """shard_map PartitionSpec matching stack_leaf_sharding."""
    if _splits_batch(mesh, leaf):
        return P(client_axes(mesh), None, BATCH_AXIS)
    return P(client_axes(mesh))


def shard_stack(mesh: Mesh, stack: dict) -> dict:
    """device_put a client data stack with per-leaf stack_leaf_sharding."""
    return {k: jax.device_put(v, stack_leaf_sharding(mesh, v))
            for k, v in stack.items()}


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_cohort(mesh: Mesh, cohort: Pytree) -> Pytree:
    """Place a host-side cohort {x,y,mask}[K,...] (+weights [K]) onto the
    mesh, leading axis split across devices. K must divide evenly — callers
    pad the cohort with zero-weight clients otherwise (pad_cohort)."""
    sh = client_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), cohort)


def pad_cohort(cohort: dict, weights: np.ndarray, multiple: int):
    """Pad cohort to a multiple of the mesh size with zero-weight dummy
    clients (mask=0 ⇒ their local_train is a no-op and weight 0 drops them
    from the psum numerator and denominator)."""
    K = int(weights.shape[0])
    pad = (-K) % multiple
    if pad == 0:
        return cohort, weights
    def pad_leaf(a):
        z = np.zeros((pad,) + tuple(a.shape[1:]), a.dtype)
        return np.concatenate([np.asarray(a), z], axis=0)
    cohort = {k: pad_leaf(v) for k, v in cohort.items()}
    weights = np.concatenate([np.asarray(weights),
                              np.zeros(pad, np.asarray(weights).dtype)])
    return cohort, weights
