"""Sharded client registry — O(1)-per-round server-side client state.

The FedML paper's target regime (arXiv:2007.13518) is millions of
intermittent clients.  Up to PR 9 the server tracked them in Python
containers: the virtual-time scheduler kept `free`/`dead` sets and an
`in_flight` dict (one Python int/object per client — ~100 ns and ~60 B
apiece, times a million, touched every wave), and `AsyncServerManager`
kept an `_outstanding` dict.  This module replaces all of them with ONE
struct-of-arrays registry, sharded into fixed-width numpy blocks:

    participation   uint32   commits this client contributed to
    quarantined     uint32   admission-pipeline rejections (ISSUE 9)
    last_staleness  float32  staleness of the last admitted uplink
    last_seen       int64    server version of the last admitted uplink
    outstanding     int64    version of the in-flight dispatch (-1 idle)
    status          uint8    FREE / IN_FLIGHT / CRASHED / DEAD / BANNED

29 bytes per client — well under the ~100 B/client acceptance bound,
and NO per-client Python objects: a round touches only its cohort's
rows (vectorized fancy indexing), so per-round cost is O(cohort), not
O(population).

Shards are allocated LAZILY: a shard materializes the first time one of
its clients deviates from the default row (FREE, never seen).  A
10M-client registry where only 10k clients ever participated holds
10k-clients' worth of shards, not 10M — the memory-growth property
pinned in tests/test_scale.py.  Aggregate counters (in-flight / dead /
eligible per shard) are maintained incrementally so scheduler decisions
("any free client?", "how many dead?") are O(1) reads, never scans.

Checkpointing: `state()` emits a SHAPE-STABLE stacked snapshot
([n_shards, shard_size] per field, defaults filled in for unallocated
shards) so orbax templates from a fresh registry always match a saved
one; `load_state()` re-sparsifies — shards that round-trip as all
default stay unallocated.  Memory is accounted in the
`registry_bytes` / `registry_clients_total` obs gauges.
"""
from __future__ import annotations

import threading

import numpy as np

from fedml_tpu import obs

# status codes (uint8)
FREE = 0          # dispatchable, sampler-eligible
IN_FLIGHT = 1     # dispatched, result pending
CRASHED = 2       # crashed mid-round, awaiting rejoin
DEAD = 3          # crashed with no rejoin — gone for good
BANNED = 4        # operator/defense ban — never sampled again

_FIELDS = (
    ("participation", np.uint32, 0),
    ("quarantined", np.uint32, 0),
    ("last_staleness", np.float32, 0.0),
    ("last_seen", np.int64, -1),
    ("outstanding", np.int64, -1),
    ("status", np.uint8, FREE),
)
BYTES_PER_CLIENT = sum(np.dtype(d).itemsize for _, d, _v in _FIELDS)

DEFAULT_SHARD_SIZE = 1 << 16


class ClientRegistry:
    """Sharded per-client counters with O(1) aggregate reads.

    Thread-safe: the async messaging server mutates it from recv/pool
    threads while the deadline watchdog reads it — every mutation takes
    the registry lock (scalar touches are one uncontended acquire).
    The virtual-time scheduler is single-threaded and pays the same
    uncontended cost."""

    def __init__(self, n_clients: int, shard_size: int | None = None,
                 quarantine_ban_threshold: int = 0):
        """`quarantine_ban_threshold` > 0 auto-BANs a client whose
        quarantine counter reaches it (excluded from sampling forever).
        0 (default) keeps the PR-9 contract — a quarantined sender
        returns to the pool and redispatches, so one false positive
        can never exile an honest client and the admission screen's
        reject-but-keep-teaching loop keeps working; repeat offenders
        are the operator's call via the counter or the threshold."""
        if n_clients < 1:
            raise ValueError(f"n_clients must be >= 1, got {n_clients}")
        if shard_size is None:
            # small fleets get one exact-width shard; big ones tile at
            # the fixed width so shard scratch stays bounded
            shard_size = min(DEFAULT_SHARD_SIZE, n_clients)
        if shard_size < 1:
            raise ValueError(f"shard_size must be >= 1, got {shard_size}")
        self.n_clients = int(n_clients)
        self.shard_size = int(shard_size)
        self.quarantine_ban_threshold = int(quarantine_ban_threshold)
        self.n_shards = -(-self.n_clients // self.shard_size)
        self._lock = threading.RLock()
        self._shards: dict[int, dict[str, np.ndarray]] = {}
        # aggregate counters — O(1) scheduler reads
        self.count_in_flight = 0
        self.count_crashed = 0
        self.count_dead = 0
        self.count_banned = 0
        # eligible (= FREE) clients per shard; unallocated shards are
        # all-FREE by construction.  The stratified sampler's shard
        # allocation reads this vector instead of scanning statuses.
        self._elig = np.minimum(
            self.shard_size,
            self.n_clients - np.arange(self.n_shards) * self.shard_size
        ).astype(np.int64)
        self._m_clients = obs.gauge("registry_clients_total")
        self._m_bytes = obs.gauge("registry_bytes")
        self._m_clients.set(self.n_clients)
        self._m_bytes.set(0)

    # -- shard plumbing ------------------------------------------------------
    def _shard_len(self, s: int) -> int:
        return min(self.shard_size, self.n_clients - s * self.shard_size)

    def _alloc(self, s: int) -> dict[str, np.ndarray]:
        sh = self._shards.get(s)
        if sh is None:
            n = self._shard_len(s)
            sh = {name: np.full(n, dv, dtype=dt)
                  for name, dt, dv in _FIELDS}
            self._shards[s] = sh
            self._m_bytes.set(self.nbytes)
        return sh

    @property
    def nbytes(self) -> int:
        """Allocated registry bytes (the `registry_bytes` gauge)."""
        return sum(a.nbytes for sh in self._shards.values()
                   for a in sh.values())

    @property
    def bytes_per_client(self) -> float:
        """Allocated bytes over the FULL population — the sub-linear
        memory headline (<= BYTES_PER_CLIENT even fully allocated)."""
        return self.nbytes / self.n_clients

    @property
    def count_free(self) -> int:
        return (self.n_clients - self.count_in_flight - self.count_crashed
                - self.count_dead - self.count_banned)

    def _check(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n_clients):
            raise IndexError(
                f"client id out of range [0, {self.n_clients}): "
                f"{ids[(ids < 0) | (ids >= self.n_clients)][:4]}")
        return ids

    def contains(self, cid: int) -> bool:
        return 0 <= int(cid) < self.n_clients

    def _check_scalar(self, cid) -> int:
        cid = int(cid)
        if not 0 <= cid < self.n_clients:
            raise IndexError(f"client id {cid} out of range "
                             f"[0, {self.n_clients})")
        return cid

    # status -> aggregate-counter attribute (FREE tracks via _elig)
    _COUNTER = {IN_FLIGHT: "count_in_flight", CRASHED: "count_crashed",
                DEAD: "count_dead", BANNED: "count_banned"}

    def _set_status_scalar(self, cid: int, status: int) -> tuple:
        """One client's status transition — the per-arrival fast path
        (no array building, no grouping).  Caller holds _lock.  Returns
        (shard dict, local index).  BANNED is STICKY: no lifecycle
        transition leaves it (only unban()/load_state) — otherwise a
        redispatch or rejoin racing a ban would silently re-admit the
        client the ban was supposed to exile."""
        s, loc = divmod(cid, self.shard_size)
        sh = self._alloc(s)
        old = int(sh["status"][loc])
        if old == BANNED and status != BANNED:
            return sh, loc
        if old != status:
            a = self._COUNTER.get(old)
            if a is not None:
                setattr(self, a, getattr(self, a) - 1)
            a = self._COUNTER.get(status)
            if a is not None:
                setattr(self, a, getattr(self, a) + 1)
            self._elig[s] += int(status == FREE) - int(old == FREE)
            sh["status"][loc] = status
        return sh, loc

    def _field_of(self, ids: np.ndarray, name: str,
                  dtype, default) -> np.ndarray:
        out = np.full(ids.shape, default, dtype=dtype)
        for s in np.unique(ids // self.shard_size):
            sh = self._shards.get(int(s))
            sel = (ids // self.shard_size) == s
            if sh is not None:
                out[sel] = sh[name][ids[sel] - int(s) * self.shard_size]
        return out

    def _update_elig(self, s: int, old_status: np.ndarray,
                     new_status: np.ndarray) -> None:
        self._elig[s] += (int(np.count_nonzero(new_status == FREE))
                          - int(np.count_nonzero(old_status == FREE)))

    def _set_status(self, ids: np.ndarray, status: int) -> None:
        """Vectorized status transition; keeps the aggregate +
        per-shard eligibility counters exact.  Deduplicates (a repeated
        id must count once — the status cell stores it once) and skips
        BANNED rows (sticky, like the scalar path)."""
        ids = np.unique(ids)
        for s in np.unique(ids // self.shard_size):
            s = int(s)
            sh = self._alloc(s)
            loc = ids[(ids // self.shard_size) == s] - s * self.shard_size
            if status != BANNED:
                loc = loc[sh["status"][loc] != BANNED]
                if not loc.size:
                    continue
            old = sh["status"][loc]
            for st, attr in ((IN_FLIGHT, "count_in_flight"),
                             (CRASHED, "count_crashed"),
                             (DEAD, "count_dead"),
                             (BANNED, "count_banned")):
                delta = (int(status == st) * loc.size
                         - int(np.count_nonzero(old == st)))
                setattr(self, attr, getattr(self, attr) + delta)
            sh["status"][loc] = status
            new = sh["status"][loc]
            self._update_elig(s, old, new)

    # -- lifecycle transitions (the scheduler/manager write API) -------------
    def note_dispatch(self, ids, version: int) -> None:
        """Clients handed work at `version`: FREE -> IN_FLIGHT."""
        with self._lock:
            ids = self._check(ids)
            if not ids.size:
                return
            self._set_status(ids, IN_FLIGHT)
            for s in np.unique(ids // self.shard_size):
                s = int(s)
                sh = self._alloc(s)
                loc = ids[(ids // self.shard_size) == s] - s * self.shard_size
                # only rows the (ban-sticky) transition actually moved
                loc = loc[sh["status"][loc] == IN_FLIGHT]
                sh["outstanding"][loc] = np.int64(version)

    def note_dispatch_one(self, cid: int, version: int) -> None:
        """Scalar twin of note_dispatch — the per-lane hot path (no
        array build, no shard grouping)."""
        with self._lock:
            cid = self._check_scalar(cid)
            sh, loc = self._set_status_scalar(cid, IN_FLIGHT)
            if int(sh["status"][loc]) == IN_FLIGHT:   # ban is sticky
                sh["outstanding"][loc] = version

    def note_return(self, cid: int) -> int:
        """An uplink (or a quarantine decision) returned this client to
        the pool: IN_FLIGHT -> FREE.  Returns the version it was
        dispatched at (-1 if it was never in flight)."""
        with self._lock:
            cid = self._check_scalar(cid)
            sh, loc = self._set_status_scalar(cid, FREE)
            v = int(sh["outstanding"][loc])
            sh["outstanding"][loc] = -1
            return v

    def note_contribution(self, cid: int, staleness: float,
                          version: int) -> None:
        """An ADMITTED uplink: bump participation, record staleness and
        the server version that folded it."""
        with self._lock:
            cid = self._check_scalar(cid)
            s, loc = divmod(cid, self.shard_size)
            sh = self._alloc(s)
            sh["participation"][loc] += 1
            sh["last_staleness"][loc] = staleness
            sh["last_seen"][loc] = version

    def note_push(self, cid: int, staleness: float,
                  version: int) -> None:
        """A PUSH-mode uplink (live-socket serving, scale/cluster.py):
        the client contributed without a server dispatch, so there is
        no IN_FLIGHT marker to retire — participation/staleness/
        last_seen update exactly as note_contribution, status stays
        untouched."""
        with self._lock:
            cid = self._check_scalar(cid)
            s, loc = divmod(cid, self.shard_size)
            sh = self._alloc(s)
            sh["participation"][loc] += 1
            sh["last_staleness"][loc] = staleness
            sh["last_seen"][loc] = version

    def note_quarantine(self, cid: int) -> bool:
        """Count one admission rejection; returns True when the client
        crossed `quarantine_ban_threshold` and was auto-BANNED (never
        sampled again)."""
        with self._lock:
            cid = self._check_scalar(cid)
            s, loc = divmod(cid, self.shard_size)
            sh = self._alloc(s)
            sh["quarantined"][loc] += 1
            if (self.quarantine_ban_threshold > 0
                    and int(sh["quarantined"][loc])
                    >= self.quarantine_ban_threshold):
                self._set_status_scalar(cid, BANNED)
                return True
            return False

    def note_crash(self, cid: int, rejoins: bool) -> None:
        """Crash mid-round: IN_FLIGHT/FREE -> CRASHED (a rejoin event is
        scheduled) or DEAD (gone for good)."""
        with self._lock:
            cid = self._check_scalar(cid)
            sh, loc = self._set_status_scalar(
                cid, CRASHED if rejoins else DEAD)
            sh["outstanding"][loc] = -1

    def note_rejoin(self, cid: int) -> None:
        with self._lock:
            self._set_status_scalar(self._check_scalar(cid), FREE)

    def ban(self, ids) -> None:
        """Operator/defense ban: excluded from eligibility until an
        explicit unban() — sticky against every lifecycle transition."""
        with self._lock:
            self._set_status(self._check(ids), BANNED)

    def unban(self, ids) -> None:
        """Explicit operator reversal of ban() — the ONLY way out of
        BANNED (lifecycle transitions skip banned rows)."""
        with self._lock:
            ids = np.unique(self._check(ids))
            for s in np.unique(ids // self.shard_size):
                s = int(s)
                sh = self._alloc(s)
                loc = ids[(ids // self.shard_size) == s] - s * self.shard_size
                loc = loc[sh["status"][loc] == BANNED]
                self.count_banned -= int(loc.size)
                self._elig[s] += int(loc.size)
                sh["status"][loc] = FREE

    # -- read API ------------------------------------------------------------
    def status_of(self, ids) -> np.ndarray:
        with self._lock:
            return self._field_of(self._check(ids), "status", np.uint8, FREE)

    def outstanding_of(self, ids) -> np.ndarray:
        with self._lock:
            return self._field_of(self._check(ids), "outstanding",
                                  np.int64, -1)

    def participation(self, ids) -> np.ndarray:
        with self._lock:
            return self._field_of(self._check(ids), "participation",
                                  np.uint32, 0)

    def last_staleness(self, ids) -> np.ndarray:
        with self._lock:
            return self._field_of(self._check(ids), "last_staleness",
                                  np.float32, 0.0)

    def quarantines(self, ids) -> np.ndarray:
        with self._lock:
            return self._field_of(self._check(ids), "quarantined",
                                  np.uint32, 0)

    def total_participation(self) -> int:
        with self._lock:
            return int(sum(int(sh["participation"].sum(dtype=np.int64))
                           for sh in self._shards.values()))

    def outstanding_ids(self) -> np.ndarray:
        """Ids with a dispatch in flight — allocated shards only
        (unallocated shards are idle by construction)."""
        with self._lock:
            out = []
            for s in sorted(self._shards):
                sh = self._shards[s]
                loc = np.flatnonzero(sh["outstanding"] >= 0)
                if loc.size:
                    out.append(loc + s * self.shard_size)
            return (np.concatenate(out) if out
                    else np.zeros((0,), np.int64))

    def free_ids(self, limit: int) -> np.ndarray:
        """First `limit` FREE ids in ascending order.  Unallocated
        shards are all-FREE, so the scan touches at most
        O(limit + allocated shards) entries — never the population."""
        out: list[np.ndarray] = []
        got = 0
        with self._lock:
            for s in range(self.n_shards):
                if got >= limit:
                    break
                base = s * self.shard_size
                sh = self._shards.get(s)
                if sh is None:
                    take = min(self._shard_len(s), limit - got)
                    out.append(np.arange(base, base + take, dtype=np.int64))
                else:
                    loc = np.flatnonzero(sh["status"] == FREE)[:limit - got]
                    out.append(loc.astype(np.int64) + base)
                got += len(out[-1])
        return (np.concatenate(out) if out else np.zeros((0,), np.int64))

    def eligible_per_shard(self) -> np.ndarray:
        """[n_shards] FREE counts (incrementally maintained — an O(S)
        copy, never an O(N) scan)."""
        with self._lock:
            return self._elig.copy()

    def eligible_mask(self, shard: int) -> np.ndarray:
        """Bool eligibility over one shard's clients (the reservoir
        sampler's per-shard stream); O(shard_size) scratch."""
        with self._lock:
            sh = self._shards.get(int(shard))
            if sh is None:
                return np.ones(self._shard_len(int(shard)), bool)
            return sh["status"] == FREE

    def eligible(self, ids) -> np.ndarray:
        return self.status_of(ids) == FREE

    def eligible_in_shard(self, shard: int, loc: np.ndarray) -> np.ndarray:
        """Eligibility of LOCAL indices within one shard — the
        rejection sampler's fast path (no id grouping)."""
        with self._lock:
            sh = self._shards.get(int(shard))
            if sh is None:
                return np.ones(loc.shape, bool)
            return sh["status"][loc] == FREE

    # -- run-boundary + checkpoint protocol ----------------------------------
    def reset_transient(self) -> None:
        """Start-of-run reset: IN_FLIGHT/CRASHED/DEAD -> FREE with
        outstanding cleared (a fresh run re-pools every client; a
        resumed run restarts the event clock but keeps participation /
        staleness / quarantine history).  BANNED survives — a ban is
        state, not schedule."""
        with self._lock:
            for s, sh in self._shards.items():
                old = sh["status"].copy()
                transient = np.isin(old, (IN_FLIGHT, CRASHED, DEAD))
                sh["status"][transient] = FREE
                sh["outstanding"][:] = -1
                self._update_elig(s, old, sh["status"])
            self.count_in_flight = 0
            self.count_crashed = 0
            self.count_dead = 0

    def state(self) -> dict:
        """Shape-stable orbax snapshot: every field stacked to
        [n_shards, shard_size] (defaults filled in for unallocated
        shards and the last shard's tail), plus the geometry — a fresh
        registry's template always matches a saved one."""
        with self._lock:
            out = {"n_clients": np.asarray(self.n_clients, np.int64),
                   "shard_size": np.asarray(self.shard_size, np.int64)}
            for name, dt, dv in _FIELDS:
                stacked = np.full((self.n_shards, self.shard_size), dv,
                                  dtype=dt)
                for s, sh in self._shards.items():
                    stacked[s, :sh[name].shape[0]] = sh[name]
                out[name] = stacked
            return out

    def load_state(self, state: dict) -> None:
        """Restore from `state()`, re-sparsifying: shards whose saved
        rows are all default stay unallocated."""
        n = int(state["n_clients"])
        ssz = int(state["shard_size"])
        if (n, ssz) != (self.n_clients, self.shard_size):
            raise ValueError(
                f"registry shape mismatch: checkpoint ({n} clients, "
                f"shard {ssz}) vs configured ({self.n_clients}, "
                f"{self.shard_size})")
        with self._lock:
            self._shards.clear()
            self.count_in_flight = self.count_crashed = 0
            self.count_dead = self.count_banned = 0
            self._elig = np.minimum(
                self.shard_size,
                self.n_clients - np.arange(self.n_shards) * self.shard_size
            ).astype(np.int64)
            for s in range(self.n_shards):
                nrow = self._shard_len(s)
                rows = {name: np.asarray(state[name][s][:nrow], dtype=dt)
                        for name, dt, _dv in _FIELDS}
                if all(np.all(rows[name] == dv)
                       for name, _dt, dv in _FIELDS):
                    continue                      # default shard: stay lazy
                sh = self._alloc(s)
                for name in rows:
                    np.copyto(sh[name], rows[name])
                st = sh["status"]
                self.count_in_flight += int(np.count_nonzero(
                    st == IN_FLIGHT))
                self.count_crashed += int(np.count_nonzero(st == CRASHED))
                self.count_dead += int(np.count_nonzero(st == DEAD))
                self.count_banned += int(np.count_nonzero(st == BANNED))
                self._elig[s] = int(np.count_nonzero(st == FREE))
            self._m_bytes.set(self.nbytes)
