"""Streaming cohort sampling over the sharded client registry.

`ClientSampler` (core/sampling.py) draws uniform cohorts by permuting
the whole population — exact reference semantics, O(N) per draw, and
(before PR 10) it reseeded the GLOBAL numpy RNG and built a Python
`range(N)` list.  At a million clients the server needs cohort draws
that (a) never materialize the population, (b) respect an eligibility
mask from the registry (banned/dead/crashed/in-flight clients are not
candidates; repeat-quarantined clients auto-BAN past the registry's
`quarantine_ban_threshold` — below it a quarantined sender returns to
the pool, the PR-9 redispatch contract), and (c) stay pure functions
of (seed, round) like
every other stochastic stream in this repo (comm/chaos.py,
async_/adversary.py convention: identical traces per seed, two seeds
differ).

Three modes:

    uniform     the degenerate anchor: ClientSampler.sample_fast (the
                non-mutating exact twin of the reference draw) filtered
                by eligibility — with every client eligible this
                reproduces the existing ClientSampler cohorts BITWISE,
                which is what pins the new spine to the old sampler.
    reservoir   one-pass weighted-key reservoir (Efraimidis–Spirakis
                with uniform weights): per shard, draw one uniform key
                per eligible client and keep the global top-k.
                O(population) draws per cohort but O(shard + k) MEMORY
                — the "streaming" property; exactly uniform over the
                eligible set.
    stratified  per-shard quotas proportional to the registry's
                incrementally-maintained eligible counts (largest-
                remainder rounding, deterministic tie-break), then
                rejection-sampled ids inside each chosen shard.  O(k)
                EXPECTED per cohort — per-round cost independent of the
                population, the serve spine's default.  Falls back to a
                full-shard draw when a shard is too depleted for
                rejection to converge.

All randomness comes from `np.random.default_rng([seed, round, shard])`
streams — no global state, no cross-shard coupling, so a shard's draw
is reproducible in isolation (tests/test_scale.py pins determinism,
two-seeds-differ, and chi-square uniformity at fixed seed).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.scale.registry import ClientRegistry

SAMPLER_MODES = ("uniform", "reservoir", "stratified")

# stratified draws touch at most this many shards per round: per-draw
# cost stays O(k + subset) however large the population, and the
# seeded mass-weighted subset rotation keeps the long-run inclusion
# probability uniform (chi-square-pinned in tests/test_scale.py)
MAX_STRATA_PER_DRAW = 8


class StreamingCohortSampler:
    """Seeded per-round cohort draws over a ClientRegistry."""

    def __init__(self, registry: ClientRegistry, cohort_size: int,
                 seed: int = 0, mode: str = "reservoir"):
        if mode not in SAMPLER_MODES:
            raise ValueError(f"unknown sampler mode {mode!r} "
                             f"(choose one of {SAMPLER_MODES})")
        if cohort_size < 1:
            raise ValueError(f"cohort_size must be >= 1, got {cohort_size}")
        self.registry = registry
        self.cohort_size = int(cohort_size)
        self.seed = int(seed)
        self.mode = mode
        self._uniform = ClientSampler(registry.n_clients, cohort_size)
        # peak per-draw scratch bytes (keys + candidate ids) — the
        # O(shard + k) memory claim, asserted in tests/test_scale.py
        self.peak_scratch_bytes = 0

    def _note_scratch(self, *arrays: np.ndarray) -> None:
        b = sum(a.nbytes for a in arrays)
        if b > self.peak_scratch_bytes:
            self.peak_scratch_bytes = b

    # -- the one public draw -------------------------------------------------
    def sample(self, round_idx: int,
               k: Optional[int] = None) -> np.ndarray:
        """[<=k] int64 eligible client ids for this round.  Fewer than
        k come back only when fewer are eligible."""
        k = self.cohort_size if k is None else int(k)
        reg = self.registry
        elig = reg.eligible_per_shard()
        total = int(elig.sum())
        if total <= k:
            # degenerate full participation over the eligible set
            out = reg.free_ids(total)
            self._note_scratch(out)
            return out
        if self.mode == "uniform":
            draw = self._uniform.sample_fast(round_idx, k=k)
            keep = reg.eligible(draw)
            out = draw[keep][:k]
            if out.size < k:
                # top up from the id-ordered free pool, skipping clients
                # the draw already took (rare: heavy ineligibility)
                pool = reg.free_ids(k + draw.size)
                out = np.concatenate(
                    [out, np.setdiff1d(pool, out, assume_unique=False)])[:k]
            return out.astype(np.int64)
        if self.mode == "reservoir":
            return self._reservoir(round_idx, k, elig)
        return self._stratified(round_idx, k, elig)

    # -- reservoir: exact uniform, O(shard + k) memory -----------------------
    def _reservoir(self, round_idx: int, k: int,
                   elig: np.ndarray) -> np.ndarray:
        reg = self.registry
        best_keys = np.empty(0, np.float64)
        best_ids = np.empty(0, np.int64)
        for s in range(reg.n_shards):
            if elig[s] == 0:
                continue
            rng = np.random.default_rng([self.seed, round_idx, s])
            mask = reg.eligible_mask(s)
            keys = rng.random(mask.shape[0])
            ids = np.flatnonzero(mask) + s * reg.shard_size
            keys = keys[mask]
            self._note_scratch(keys, ids, best_keys, best_ids)
            cat_k = np.concatenate([best_keys, keys])
            cat_i = np.concatenate([best_ids, ids])
            if cat_k.size > k:
                top = np.argpartition(cat_k, cat_k.size - k)[-k:]
                best_keys, best_ids = cat_k[top], cat_i[top]
            else:
                best_keys, best_ids = cat_k, cat_i
        # deterministic output order: by key descending (the reservoir's
        # arrival-independent canonical order)
        order = np.argsort(-best_keys, kind="stable")
        return best_ids[order].astype(np.int64)

    # -- stratified: O(k) expected, proportional to eligible counts ----------
    def _stratified(self, round_idx: int, k: int,
                    elig: np.ndarray) -> np.ndarray:
        reg = self.registry
        total = int(elig.sum())
        active = np.flatnonzero(elig)
        if active.size > MAX_STRATA_PER_DRAW:
            # seeded shard-subset rotation, mass-weighted: this round
            # draws only from MAX_STRATA shards, the next from another
            # seeded subset — per-round cost decouples from the shard
            # count while long-run coverage stays proportional
            rng0 = np.random.default_rng([self.seed, round_idx, 1 << 20])
            p = elig[active] / total
            sub = active[rng0.choice(active.size, MAX_STRATA_PER_DRAW,
                                     replace=False, p=p)]
            masked = np.zeros_like(elig)
            masked[sub] = elig[sub]
            elig = masked
            total = int(elig.sum())
        exact = elig * (k / total)
        quota = np.floor(exact).astype(np.int64)
        quota = np.minimum(quota, elig)
        short = k - int(quota.sum())
        if short > 0:
            # largest-remainder rounding with shard-id tie-break, capped
            # at each shard's eligible count
            frac = np.where(elig > quota, exact - quota, -1.0)
            for s in np.argsort(-frac, kind="stable"):
                if short == 0:
                    break
                if quota[s] < elig[s]:
                    quota[s] += 1
                    short -= 1
        out = []
        for s in np.flatnonzero(quota):
            s = int(s)
            rng = np.random.default_rng([self.seed, round_idx, s])
            out.append(self._draw_in_shard(rng, s, int(quota[s]),
                                           int(elig[s])))
        ids = (np.concatenate(out) if out else np.zeros((0,), np.int64))
        return np.sort(ids).astype(np.int64)

    def _draw_in_shard(self, rng: np.random.Generator, s: int, q: int,
                       m: int) -> np.ndarray:
        """q distinct eligible ids from shard s (m eligible there).
        Rejection sampling against the status array — O(q) expected
        when the shard is mostly eligible; a depleted shard (< 50%
        eligible, or rejection failing to converge) falls back to one
        materialized O(shard) choice."""
        reg = self.registry
        base = s * reg.shard_size
        n = min(reg.shard_size, reg.n_clients - base)
        if q >= m or m < max(2 * q, n // 2):
            mask = reg.eligible_mask(s)
            ids = np.flatnonzero(mask) + base
            self._note_scratch(mask, ids)
            if q >= ids.size:
                return ids.astype(np.int64)
            return np.sort(ids[rng.choice(ids.size, q, replace=False)])
        got = np.zeros(0, np.int64)
        for _ in range(8):
            need = q - got.size
            loc = rng.integers(0, n, size=2 * need + 8)
            self._note_scratch(loc, got)
            loc = np.unique(loc)
            cand = base + loc[reg.eligible_in_shard(s, loc)]
            got = np.unique(np.concatenate([got, cand]))
            if got.size >= q:
                # keep a seeded subset so overshoot stays unbiased
                return np.sort(got[rng.choice(got.size, q, replace=False)])
        mask = reg.eligible_mask(s)            # pathological: materialize
        ids = np.flatnonzero(mask) + base
        return np.sort(ids[rng.choice(ids.size, min(q, ids.size),
                                      replace=False)])
