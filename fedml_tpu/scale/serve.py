"""Virtual-time serve simulation — the million-client heavy-traffic
bench behind `bench.py --mode serve`.

What it measures: the SERVER's cross-device round hot path at
production populations — cohort sampling over the sharded registry,
per-uplink registry bookkeeping, the streaming fold, and the O(P)
commit — under a trace-driven arrival process in virtual time.  Client
compute is out of scope by design (updates are a rotating pool of
pre-generated rows): the north-star question here is whether the
serving spine sustains committed-updates/sec while server memory stays
sub-linear in population (ISSUE 10 acceptance: registry <= ~100
bytes/client at 1M, no per-client Python objects on the hot path).

The loop (one process, no threads — the virtual clock comes from the
arrival process):

    arrivals  λ(t) from scale/arrivals.py yields uplink landing times
    dispatch  when in-flight drops below `concurrency`, the streaming
              cohort sampler draws a batch over the registry's
              eligibility mask and `note_dispatch` marks it (vectorized)
    ingest    each arrival pops the oldest in-flight client (a numpy
              ring, no deque of Python tuples), `note_return` yields its
              dispatched version -> staleness, the row folds into the
              streaming AsyncBuffer (the PR-6 jitted fold), and
              `note_contribution` updates the client's counters
    commit    buffer full -> the O(P) stream commit, version += 1
    faults    a seeded dropout stream crashes dispatches (no fold);
              crashed clients rejoin at the next commit — eligibility
              masks breathe, like the lifecycle model

Determinism: sampler draws, the row pool, dropout and arrival times are
all `default_rng([seed, ...])` streams — one seed, one trace.
"""
from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from fedml_tpu import obs
from fedml_tpu.scale.arrivals import (ArrivalConfig, ArrivalProcess,
                                      make_arrivals)
from fedml_tpu.scale.registry import ClientRegistry
from fedml_tpu.scale.sampler import StreamingCohortSampler


def rss_bytes() -> int:
    """Resident set size of this process (0 where /proc is absent)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def run_serve_sim(population: int, *, commits: int = 30,
                  warmup_commits: int = 2, buffer_k: int = 32,
                  concurrency: Optional[int] = None, row_dim: int = 1024,
                  sampler_mode: str = "stratified",
                  arrival: Optional[ArrivalConfig] = None,
                  dropout_prob: float = 0.0, banned_frac: float = 0.0,
                  seed: int = 0, partition: tuple = (0, 1),
                  channel=None) -> dict:
    """Drive `commits` streaming commits at `population` simulated
    clients; returns the serve report (committed-updates/sec, registry
    memory, RSS, virtual-time stats).

    Host-sharded mode (ISSUE 13): `partition=(rank, world)` makes this
    process own ONLY its client-id range of the population — its
    registry shards, sampler and in-flight ring cover population/world
    clients (the PR-10 id-range partition, executed across processes).
    Each commit folds the partial streaming aggregates upward: the
    local (acc, wsum) allgathers over `channel`
    (parallel/multihost.py HostChannel), every rank sums the P-sized
    partials in RANK ORDER (deterministic — the two-level fold
    contract), and the identical commit applies everywhere — the
    report's `committed_digest` must agree across ranks.  Commit
    cadence is the synchronization point: every rank performs exactly
    `commits` commits, so the allgathers pair up; a dead rank raises
    the channel's DeadRankError naming it."""
    import jax.numpy as jnp
    from fedml_tpu.async_.staleness import (AsyncBuffer,
                                            make_stream_commit_fn)

    if commits <= warmup_commits:
        raise ValueError(f"commits ({commits}) must exceed "
                         f"warmup_commits ({warmup_commits})")
    rank, world = int(partition[0]), int(partition[1])
    if not 0 <= rank < world:
        raise ValueError(f"partition rank {rank} outside world {world}")
    if world > 1 and channel is None:
        raise ValueError("world > 1 needs a HostChannel to fold the "
                         "partial aggregates upward")
    # this process's client-id range [lo, hi): registry/sampler/ring
    # are all range-local — nothing population-sized is shared
    lo = rank * population // world
    hi = (rank + 1) * population // world
    local_population = hi - lo
    concurrency = (concurrency if concurrency is not None
                   else 4 * buffer_k)
    arrival = arrival if arrival is not None else ArrivalConfig(
        mode="constant", rate=1000.0, seed=seed)
    proc: Optional[ArrivalProcess] = make_arrivals(arrival)

    registry = ClientRegistry(local_population)
    # per-rank streams when sharded (each range's bans/dropouts/rows
    # are its own); the world==1 streams stay EXACTLY the pre-partition
    # ones so every existing seeded trace/pin is unchanged
    rng = np.random.default_rng(
        [seed, 2] if world == 1 else [seed, 2, rank])
    if banned_frac > 0.0:
        # seeded ineligibility (defense bans / opted-out devices): the
        # sampler must route around these forever
        n_ban = max(1, int(banned_frac * local_population))
        registry.ban(np.unique(rng.integers(0, local_population,
                                            size=2 * n_ban))[:n_ban])
    sampler = StreamingCohortSampler(registry, buffer_k, seed=seed,
                                     mode=sampler_mode)
    # the commit math: a tiny flat-row "model" through the REAL PR-6
    # streaming buffer + O(P) commit program
    template = {"w": jnp.zeros((row_dim,), jnp.float32)}
    buffer = AsyncBuffer(buffer_k, row_dim, streaming=True)
    commit_fn = make_stream_commit_fn(template, donate=False)
    variables = template
    # rotating pre-generated row pool: the fold reads realistic floats
    # without paying a per-arrival P-sized RNG draw
    pool = rng.standard_normal((64, row_dim)).astype(np.float32)
    drop_rng = np.random.default_rng(
        [seed, 3] if world == 1 else [seed, 3, rank])

    # in-flight FIFO as a numpy ring — ids only; the registry's
    # `outstanding` field carries the dispatched version
    cap = 2 * concurrency + buffer_k
    ring = np.zeros(cap, np.int64)
    head = tail = 0                     # pop at head, push at tail

    version = 0
    admitted = 0
    crashed = 0
    draws = 0        # sampler round index: MONOTONE per draw, never
    #                  reused — the legacy uniform draw is prefix-stable
    #                  in k at a fixed round, so re-sampling one round
    #                  index across refills would re-select the same
    #                  (now in-flight) ids and degrade to id-ordered
    #                  top-ups
    rejoin_at_commit: list[np.ndarray] = []
    arr_iter = (proc.arrivals(0.0, np.random.default_rng(
        [arrival.seed, seed, 1] if world == 1
        else [arrival.seed, seed, 1, rank]))
        if proc is not None else None)
    now = 0.0
    t_wall0 = time.perf_counter()
    t_timed = None
    admitted_at_warmup = 0

    def dispatch(need: int) -> int:
        nonlocal tail, draws
        ids = sampler.sample(draws, k=need)
        draws += 1
        if ids.size == 0:
            return 0
        registry.note_dispatch(ids, version)
        for c in ids:                   # ring push (ids only)
            ring[tail % cap] = c
            tail += 1
        return int(ids.size)

    with obs.span("serve.run", population=population, commits=commits,
                  sampler=sampler_mode, arrival=arrival.mode):
        dispatch(concurrency)
        while version < commits:
            if head == tail and dispatch(buffer_k) == 0:
                raise RuntimeError(
                    f"serve sim starved at version {version}: no "
                    f"eligible clients ({registry.count_free} free)")
            if arr_iter is not None:
                try:
                    now = next(arr_iter)
                except StopIteration:
                    # only TraceArrivals terminates — name the fix
                    raise ValueError(
                        f"arrival trace exhausted after {admitted + crashed}"
                        f" arrivals at commit {version}/{commits}: the "
                        f"trace needs ~commits*buffer_k (+dropout) "
                        f"timestamps") from None
            cid = int(ring[head % cap])
            head += 1
            if dropout_prob > 0.0 and drop_rng.random() < dropout_prob:
                registry.note_crash(cid, rejoins=True)
                crashed += 1
                rejoin_at_commit.append(np.asarray([cid], np.int64))
            else:
                v = registry.note_return(cid)
                staleness = float(version - v)
                full = buffer.add(pool[admitted % 64], 1.0, staleness)
                registry.note_contribution(cid, staleness, version)
                admitted += 1
                if full:
                    with obs.span("serve.commit", version=version,
                                  t_virtual=round(now, 3),
                                  rank=rank):
                        acc, wsum, _w, _s, n_commit, _raw = \
                            buffer.take_stream()
                        if world > 1:
                            # fold the partial aggregates upward: every
                            # rank ships its local (acc, wsum), sums in
                            # RANK ORDER (deterministic), commits the
                            # identical global mix
                            payload = (np.float32(wsum).tobytes()
                                       + np.asarray(acc, np.float32)
                                       .tobytes())
                            docs = channel.allgather(payload)
                            t_wsum = np.float32(0.0)
                            t_acc = np.zeros(row_dim, np.float32)
                            for d in docs:
                                t_wsum = np.float32(
                                    t_wsum + np.frombuffer(
                                        d, "<f4", count=1)[0])
                                t_acc += np.frombuffer(d, "<f4",
                                                       offset=4)
                            acc = jnp.asarray(t_acc)
                            wsum = jnp.float32(t_wsum)
                        variables, _stats = commit_fn(
                            variables, acc, wsum, jnp.float32(1.0))
                    # ISSUE 12: the SLO pack's committed-updates floor
                    obs.counter("async_updates_committed_total").inc(
                        n_commit)
                    version += 1
                    for ids in rejoin_at_commit:
                        for c in ids:
                            registry.note_rejoin(int(c))
                    rejoin_at_commit.clear()
                    if version == warmup_commits:
                        t_timed = time.perf_counter()
                        admitted_at_warmup = admitted
            if (tail - head) <= concurrency - buffer_k:
                with obs.span("serve.dispatch", version=version):
                    dispatch(concurrency - (tail - head))
    wall = time.perf_counter() - (t_timed if t_timed is not None
                                  else t_wall0)
    timed_updates = admitted - (admitted_at_warmup
                                if t_timed is not None else 0)
    # contributor spread (from allocated shards only — O(touched)):
    # a healthy sampler scatters updates across the population; a
    # biased one concentrates them on few clients
    distinct = max_part = 0
    for sh in registry._shards.values():
        part = sh["participation"]
        distinct += int(np.count_nonzero(part))
        max_part = max(max_part, int(part.max()) if part.size else 0)
    from fedml_tpu.parallel.multihost import variables_digest
    return {
        "population": int(population),
        "local_population": int(local_population),
        "partition": [rank, world],
        # the cross-rank agreement pin: host-sharded serve commits the
        # same global mix on every rank (THE one bitwise digest,
        # shared with the multihost pins)
        "committed_digest": variables_digest(variables),
        "carry_allreduce_bytes": int(getattr(channel, "bytes_received",
                                             0) if channel is not None
                                     else 0),
        "commits": int(version),
        "committed_updates": int(admitted),
        "distinct_contributors": distinct,
        "max_client_participation": max_part,
        "committed_updates_per_sec": (timed_updates / wall
                                      if wall > 0 else 0.0),
        "buffer_k": int(buffer_k),
        "concurrency": int(concurrency),
        "row_dim": int(row_dim),
        "sampler_mode": sampler_mode,
        "sampler_peak_scratch_bytes": int(sampler.peak_scratch_bytes),
        "arrival_mode": arrival.mode,
        "virtual_time_s": float(now),
        "mean_arrival_rate": (admitted + crashed) / now if now > 0 else 0.0,
        "registry_bytes": int(registry.nbytes),
        "registry_bytes_per_client": float(registry.bytes_per_client),
        "registry_shards_allocated": len(registry._shards),
        "crashed": int(crashed),
        "banned": int(registry.count_banned),
        "rss_bytes": rss_bytes(),
        "wall_s": float(wall),
        "seed": int(seed),
    }
