"""Virtual-time serve simulation — the million-client heavy-traffic
bench behind `bench.py --mode serve`.

What it measures: the SERVER's cross-device round hot path at
production populations — cohort sampling over the sharded registry,
per-uplink registry bookkeeping, the streaming fold, and the O(P)
commit — under a trace-driven arrival process in virtual time.  Client
compute is out of scope by design (updates are a rotating pool of
pre-generated rows): the north-star question here is whether the
serving spine sustains committed-updates/sec while server memory stays
sub-linear in population (ISSUE 10 acceptance: registry <= ~100
bytes/client at 1M, no per-client Python objects on the hot path).

The loop (one process, no threads — the virtual clock comes from the
arrival process):

    arrivals  λ(t) from scale/arrivals.py yields uplink landing times
    dispatch  when in-flight drops below `concurrency`, the streaming
              cohort sampler draws a batch over the registry's
              eligibility mask and `note_dispatch` marks it (vectorized)
    ingest    each arrival pops the oldest in-flight client (a numpy
              ring, no deque of Python tuples), `note_return` yields its
              dispatched version -> staleness, the row folds into the
              streaming AsyncBuffer (the PR-6 jitted fold), and
              `note_contribution` updates the client's counters
    commit    buffer full -> the O(P) stream commit, version += 1
    faults    a seeded dropout stream crashes dispatches (no fold);
              crashed clients rejoin at the next commit — eligibility
              masks breathe, like the lifecycle model

Determinism: sampler draws, the row pool, dropout and arrival times are
all `default_rng([seed, ...])` streams — one seed, one trace.
"""
from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from fedml_tpu import obs
from fedml_tpu.scale.arrivals import (ArrivalConfig, ArrivalProcess,
                                      make_arrivals)
from fedml_tpu.scale.registry import ClientRegistry
from fedml_tpu.scale.sampler import StreamingCohortSampler


def pack_partial(acc, wsum) -> bytes:
    """One lane/rank partial on the wire: <f4 wsum then the f32 acc
    row.  THE one payload layout — run_serve_sim's fold, the fused
    cluster's fold (scale/cluster.py) and the elastic zero-fill all
    speak it, so the cross-rank digest pins compare the same bytes."""
    return (np.float32(wsum).tobytes()
            + np.asarray(acc, np.float32).tobytes())


def zero_partial(row_dim: int) -> bytes:
    """The deterministic zero payload a not-yet-adopted range folds."""
    return (np.float32(0.0).tobytes()
            + np.zeros(row_dim, np.float32).tobytes())


def fold_partials(docs, row_dim: int):
    """Rank/item-ordered sum of (wsum, acc) payloads — THE one
    cross-rank fold, shared by both transports and by the fused
    serving cluster.  Caller supplies docs already in item order; the
    fold itself adds nothing order-dependent."""
    import jax.numpy as jnp
    t_wsum = np.float32(0.0)
    t_acc = np.zeros(row_dim, np.float32)
    for d in docs:
        t_wsum = np.float32(
            t_wsum + np.frombuffer(d, "<f4", count=1)[0])
        t_acc += np.frombuffer(d, "<f4", offset=4)
    return jnp.asarray(t_acc), jnp.float32(t_wsum)


def rss_bytes() -> int:
    """Resident set size of this process (0 where /proc is absent)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


class _ServeLane:
    """One client-id range's serving state — registry shards, sampler,
    streaming buffer, in-flight ring, and all the seeded streams — run
    as a generator that yields its partial (acc, wsum, n) at every
    commit boundary.  `item` is the range's index in the ORIGINAL
    world-sized partition: the fold is always in item order, so the
    global mix is independent of which process hosts which lane (the
    elastic re-adoption contract — a survivor adopting a dead rank's
    range creates a fresh lane with the dead rank's item index and the
    dead rank's seed streams, restarted from their beginning).

    The single-lane world==1 path walks EXACTLY the pre-lane per-
    arrival op order (dispatch → arrival → pop → crash|fold →
    commit-yield → rejoins → refill), so every existing seeded
    trace/pin survives the refactor."""

    def __init__(self, item: int, lo: int, hi: int, *, world: int,
                 seed: int, buffer_k: int, concurrency: int,
                 row_dim: int, sampler_mode: str,
                 arrival: ArrivalConfig, dropout_prob: float,
                 banned_frac: float, start_version: int = 0):
        import jax.numpy as jnp  # noqa: F401  (jax warmed by caller)
        from fedml_tpu.async_.staleness import AsyncBuffer
        self.item = int(item)
        self.lo, self.hi = int(lo), int(hi)
        self.local_population = self.hi - self.lo
        self.world = int(world)
        self.buffer_k = int(buffer_k)
        self.concurrency = int(concurrency)
        self.dropout_prob = float(dropout_prob)
        self.registry = ClientRegistry(self.local_population)
        # per-range streams when sharded (each range's bans/dropouts/
        # rows are its own); the world==1 streams stay EXACTLY the
        # pre-partition ones so every seeded trace/pin is unchanged
        key = [seed, 2] if world == 1 else [seed, 2, item]
        self.rng = np.random.default_rng(key)
        if banned_frac > 0.0:
            n_ban = max(1, int(banned_frac * self.local_population))
            self.registry.ban(np.unique(self.rng.integers(
                0, self.local_population, size=2 * n_ban))[:n_ban])
        self.sampler = StreamingCohortSampler(self.registry, buffer_k,
                                              seed=seed,
                                              mode=sampler_mode)
        self.buffer = AsyncBuffer(buffer_k, row_dim, streaming=True)
        self.pool = self.rng.standard_normal(
            (64, row_dim)).astype(np.float32)
        self.drop_rng = np.random.default_rng(
            [seed, 3] if world == 1 else [seed, 3, item])
        proc: Optional[ArrivalProcess] = make_arrivals(arrival)
        self.arr_iter = (proc.arrivals(0.0, np.random.default_rng(
            [arrival.seed, seed, 1] if world == 1
            else [arrival.seed, seed, 1, item]))
            if proc is not None else None)
        cap = 2 * self.concurrency + self.buffer_k
        self.cap = cap
        self.ring = np.zeros(cap, np.int64)
        self.head = self.tail = 0
        self.version = int(start_version)
        self.admitted = 0
        self.crashed = 0
        self.draws = 0   # MONOTONE per draw (the PR-10 uniform lesson)
        self.now = 0.0
        self._rejoin_at_commit: list[np.ndarray] = []

    def _dispatch(self, need: int) -> int:
        ids = self.sampler.sample(self.draws, k=need)
        self.draws += 1
        if ids.size == 0:
            return 0
        self.registry.note_dispatch(ids, self.version)
        for c in ids:
            self.ring[self.tail % self.cap] = c
            self.tail += 1
        return int(ids.size)

    def gen(self):
        """Yield (acc, wsum, n_commit) at each commit boundary; the
        driver folds across lanes/ranks and applies the ONE global
        commit."""
        self._dispatch(self.concurrency)
        while True:
            if (self.head == self.tail
                    and self._dispatch(self.buffer_k) == 0):
                raise RuntimeError(
                    f"serve sim starved at version {self.version} "
                    f"(lane {self.item}): no eligible clients "
                    f"({self.registry.count_free} free)")
            if self.arr_iter is not None:
                try:
                    self.now = next(self.arr_iter)
                except StopIteration:
                    # only TraceArrivals terminates — name the fix
                    raise ValueError(
                        f"arrival trace exhausted after "
                        f"{self.admitted + self.crashed} arrivals at "
                        f"commit {self.version}: the trace needs "
                        f"~commits*buffer_k (+dropout) "
                        f"timestamps") from None
            cid = int(self.ring[self.head % self.cap])
            self.head += 1
            if (self.dropout_prob > 0.0
                    and self.drop_rng.random() < self.dropout_prob):
                self.registry.note_crash(cid, rejoins=True)
                self.crashed += 1
                self._rejoin_at_commit.append(
                    np.asarray([cid], np.int64))
            else:
                v = self.registry.note_return(cid)
                staleness = float(self.version - v)
                full = self.buffer.add(self.pool[self.admitted % 64],
                                       1.0, staleness)
                self.registry.note_contribution(cid, staleness,
                                                self.version)
                self.admitted += 1
                if full:
                    acc, wsum, _w, _s, n_commit, _raw = \
                        self.buffer.take_stream()
                    yield acc, wsum, n_commit
                    self.version += 1
                    for ids in self._rejoin_at_commit:
                        for c in ids:
                            self.registry.note_rejoin(int(c))
                    self._rejoin_at_commit.clear()
            if (self.tail - self.head) <= (self.concurrency
                                           - self.buffer_k):
                with obs.span("serve.dispatch", version=self.version):
                    self._dispatch(self.concurrency
                                   - (self.tail - self.head))


def run_serve_sim(population: int, *, commits: int = 30,
                  warmup_commits: int = 2, buffer_k: int = 32,
                  concurrency: Optional[int] = None, row_dim: int = 1024,
                  sampler_mode: str = "stratified",
                  arrival: Optional[ArrivalConfig] = None,
                  dropout_prob: float = 0.0, banned_frac: float = 0.0,
                  seed: int = 0, partition: tuple = (0, 1),
                  channel=None, elastic: bool = False,
                  crash_at_commit: Optional[int] = None) -> dict:
    """Drive `commits` streaming commits at `population` simulated
    clients; returns the serve report (committed-updates/sec, registry
    memory, RSS, virtual-time stats).

    Host-sharded mode (ISSUE 13): `partition=(rank, world)` makes this
    process own ONLY its client-id range of the population — its
    registry shards, sampler and in-flight ring cover population/world
    clients (the PR-10 id-range partition, executed across processes).
    Each commit folds the partial streaming aggregates upward: the
    local (acc, wsum) allgathers over `channel`
    (parallel/multihost.py HostChannel), every rank sums the P-sized
    partials in RANGE (item) ORDER (deterministic — the two-level fold
    contract), and the identical commit applies everywhere — the
    report's `committed_digest` must agree across ranks.  Commit
    cadence is the synchronization point: every rank performs exactly
    `commits` commits, so the allgathers pair up; a dead rank raises
    the channel's DeadRankError naming it.

    Elastic mode (ISSUE 14): pass an `ElasticChannel` (n_items=world)
    and `elastic=True` — a rank dying mid-run no longer kills the
    survivors.  The window where the death lands folds ZERO for the
    dead range (deterministic on every survivor, so the cross-rank
    digest pin holds through the death), and at the NEXT commit
    barrier the view's new owner re-adopts the dead rank's
    registry-shard range as a fresh `_ServeLane` (the dead rank's item
    index and seed streams, restarted — its in-flight uplinks and
    participation counters died with it, which is the honest
    semantics).  `crash_at_commit` is the fault-injection hook: this
    rank abruptly closes its channel after that many commits and
    returns a partial report."""
    import jax.numpy as jnp
    from fedml_tpu.async_.staleness import make_stream_commit_fn

    if commits <= warmup_commits:
        raise ValueError(f"commits ({commits}) must exceed "
                         f"warmup_commits ({warmup_commits})")
    rank, world = int(partition[0]), int(partition[1])
    if not 0 <= rank < world:
        raise ValueError(f"partition rank {rank} outside world {world}")
    if world > 1 and channel is None:
        raise ValueError("world > 1 needs a HostChannel to fold the "
                         "partial aggregates upward")
    if elastic and world > 1 and not hasattr(channel, "exchange"):
        raise ValueError("elastic=True needs an ElasticChannel "
                         "(n_items=world); HostChannel is the "
                         "fail-fast transport")
    concurrency = (concurrency if concurrency is not None
                   else 4 * buffer_k)
    arrival = arrival if arrival is not None else ArrivalConfig(
        mode="constant", rate=1000.0, seed=seed)

    def make_lane(item: int, start_version: int = 0) -> _ServeLane:
        return _ServeLane(
            item, item * population // world,
            (item + 1) * population // world, world=world, seed=seed,
            buffer_k=buffer_k, concurrency=concurrency,
            row_dim=row_dim, sampler_mode=sampler_mode,
            arrival=arrival, dropout_prob=dropout_prob,
            banned_frac=banned_frac, start_version=start_version)

    primary = make_lane(rank)
    lanes: dict[int, _ServeLane] = {rank: primary}
    gens: dict[int, object] = {}
    retired: list[_ServeLane] = []      # lanes the view moved elsewhere
    adopted_items: list[int] = []
    zero_payload = zero_partial(row_dim)

    # the commit math: a tiny flat-row "model" through the REAL PR-6
    # streaming buffer + O(P) commit program
    template = {"w": jnp.zeros((row_dim,), jnp.float32)}
    commit_fn = make_stream_commit_fn(template, donate=False)
    variables = template
    version = 0
    t_wall0 = time.perf_counter()
    t_timed = None
    admitted_at_warmup = 0
    crashed_out = False

    def _pack(acc, wsum) -> bytes:
        return pack_partial(acc, wsum)

    def _fold(docs):
        return fold_partials(docs, row_dim)

    def all_lanes() -> list:
        return list(lanes.values()) + retired

    def registry_lanes() -> list:
        """Lanes for REGISTRY-state aggregation: at most one per item,
        the live lane winning over a retired one — re-adopting an item
        this rank previously retired must not double-count the range's
        registry bytes/bans/contributors.  Work counters (admitted/
        crashed) still sum over all_lanes(): a retired lane's folded
        updates really happened."""
        by_item = {ln.item: ln for ln in retired}
        by_item.update(lanes)
        return list(by_item.values())

    def lanes_admitted() -> int:
        return sum(ln.admitted for ln in all_lanes())

    def clock_lane() -> _ServeLane:
        """The lane whose virtual clock represents this rank NOW: the
        primary while hosted, else any still-hosted lane — a view
        change can retire even the rank's OWN range (the owner map is
        global), and a retired lane's clock freezes."""
        if rank in lanes:
            return lanes[rank]
        return next(iter(lanes.values())) if lanes else primary

    with obs.span("serve.run", population=population, commits=commits,
                  sampler=sampler_mode, arrival=arrival.mode,
                  elastic=elastic):
        gens[rank] = primary.gen()
        while version < commits:
            if crash_at_commit is not None and version == crash_at_commit:
                # fault injection: this rank vanishes mid-run — the
                # survivors' next exchange evicts it and re-adopts its
                # range at their next commit barrier
                if channel is not None:
                    channel.close()
                crashed_out = True
                break
            partials = {}
            for item in sorted(gens):
                acc, wsum, n_commit = next(gens[item])
                partials[item] = (acc, wsum, n_commit)
            with obs.span("serve.commit", version=version,
                          t_virtual=round(clock_lane().now, 3),
                          rank=rank):
                n_committed = sum(p[2] for p in partials.values())
                if world > 1 and elastic:
                    payloads = {item: _pack(acc, wsum)
                                for item, (acc, wsum, _n)
                                in partials.items()}
                    # a re-assigned range we don't host yet folds ZERO
                    # this window (identical bytes on every survivor);
                    # the lane starts at the next barrier below
                    allp, view = channel.exchange(
                        version, payloads,
                        lambda items: {i: zero_payload for i in items})
                    acc, wsum = _fold(allp[item]
                                      for item in range(world))
                elif world > 1:
                    # fail-fast fold, byte-compatible with ISSUE 13:
                    # one (wsum, acc) payload per rank, summed in rank
                    # order
                    acc, wsum, _n = partials[rank]
                    docs = channel.allgather(_pack(acc, wsum))
                    acc, wsum = _fold(docs)
                else:
                    acc, wsum, _n = partials[rank]
                variables, _stats = commit_fn(
                    variables, acc, wsum, jnp.float32(1.0))
            # ISSUE 12: the SLO pack's committed-updates floor
            obs.counter("async_updates_committed_total").inc(
                n_committed)
            version += 1
            if world > 1 and elastic:
                # the commit barrier re-partitions lanes onto the view:
                # exactly ONE host per range — drop lanes the owner map
                # moved elsewhere (double-hosting would race two
                # different partials for one item), adopt ranges it
                # moved here
                for item in list(gens):
                    if view.owner_of(item) != rank:
                        gens.pop(item).close()
                        retired.append(lanes.pop(item))
                for item in view.assigned(rank):
                    if item not in lanes:
                        lanes[item] = make_lane(item,
                                                start_version=version)
                        gens[item] = lanes[item].gen()
                        adopted_items.append(item)
                        obs.instant("serve.readopt", item=item,
                                    rank=rank, version=version)
            if version == warmup_commits:
                t_timed = time.perf_counter()
                admitted_at_warmup = lanes_admitted()
    wall = time.perf_counter() - (t_timed if t_timed is not None
                                  else t_wall0)
    timed_updates = lanes_admitted() - (admitted_at_warmup
                                        if t_timed is not None else 0)
    # contributor spread (from allocated shards only — O(touched)):
    # a healthy sampler scatters updates across the population; a
    # biased one concentrates them on few clients.  registry_lanes()
    # keeps at most one lane per range, so the sums stay exact even
    # when a retired range is later re-adopted.
    distinct = max_part = 0
    for ln in registry_lanes():
        for sh in ln.registry._shards.values():
            part = sh["participation"]
            distinct += int(np.count_nonzero(part))
            max_part = max(max_part,
                           int(part.max()) if part.size else 0)
    from fedml_tpu.parallel.multihost import variables_digest
    report = {
        "population": int(population),
        "local_population": int(primary.local_population),
        "partition": [rank, world],
        # the cross-rank agreement pin: host-sharded serve commits the
        # same global mix on every rank (THE one bitwise digest,
        # shared with the multihost pins)
        "committed_digest": variables_digest(variables),
        "carry_allreduce_bytes": int(getattr(channel, "bytes_received",
                                             0) if channel is not None
                                     else 0),
        "commits": int(version),
        "committed_updates": int(lanes_admitted()),
        "distinct_contributors": distinct,
        "max_client_participation": max_part,
        "committed_updates_per_sec": (timed_updates / wall
                                      if wall > 0 else 0.0),
        "buffer_k": int(buffer_k),
        "concurrency": int(concurrency),
        "row_dim": int(row_dim),
        "sampler_mode": sampler_mode,
        "sampler_peak_scratch_bytes": int(
            max(ln.sampler.peak_scratch_bytes for ln in all_lanes())),
        "arrival_mode": arrival.mode,
        "virtual_time_s": float(clock_lane().now),
        "mean_arrival_rate": (
            (clock_lane().admitted + clock_lane().crashed)
            / clock_lane().now if clock_lane().now > 0 else 0.0),
        "registry_bytes": int(sum(ln.registry.nbytes
                                  for ln in registry_lanes())),
        "registry_bytes_per_client": float(
            primary.registry.bytes_per_client),
        "registry_shards_allocated": sum(len(ln.registry._shards)
                                         for ln in registry_lanes()),
        "crashed": int(sum(ln.crashed for ln in all_lanes())),
        "banned": int(sum(ln.registry.count_banned
                          for ln in registry_lanes())),
        "rss_bytes": rss_bytes(),
        "wall_s": float(wall),
        "seed": int(seed),
    }
    if elastic:
        report["elastic"] = {
            "lanes": sorted(lanes),
            "adopted_items": adopted_items,
            "retired_items": [ln.item for ln in retired],
            "crashed_at_commit": (crash_at_commit if crashed_out
                                  else None),
            "epoch": (channel.view.epoch
                      if channel is not None
                      and hasattr(channel, "view") else 0),
            "view_changes": (len(channel.view_events)
                             if channel is not None
                             and hasattr(channel, "view_events")
                             else 0),
        }
    return report
