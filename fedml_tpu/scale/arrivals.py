"""Trace-driven arrival processes — the load shapes of a production
federation.

A million-device fleet does not upload at a constant rate: participation
follows the day (devices charge and idle overnight — the diurnal
sinusoid every FL deployment paper plots), spikes on events (a push
notification wakes a flash crowd), and in postmortems is replayed from
recorded traces.  This module is the ONE seeded source of those shapes,
driving two consumers:

* the serve simulation (fedml_tpu/scale/serve.py): arrival times are
  uplink landings in VIRTUAL time — the async buffer ingests at λ(t),
  so committed-updates/sec is measured under a realistic load curve;
* the virtual-time scheduler (async_/scheduler.py `arrivals=`): the
  process modulates dispatch turnaround — at the trough of the diurnal
  cycle the fleet is slower to respond (`slowdown(t) = λ_peak / λ(t)`),
  so staleness and deadline behavior see the load shape too.

Generators are inhomogeneous Poisson processes sampled by THINNING
(Lewis & Shedler): draw candidate gaps at the peak rate, accept with
probability λ(t)/λ_peak — exact for any bounded λ(t), and a pure
function of the seed (identical arrival traces per seed, two seeds
differ; pinned in tests/test_scale.py).  `TraceArrivals` replays an
explicit timestamp array (or a file of timestamps) verbatim.
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

ARRIVAL_MODES = ("none", "constant", "diurnal", "flash", "trace")


@dataclasses.dataclass
class ArrivalConfig:
    """Knobs of the arrival process (CLI --arrival_*)."""
    mode: str = "none"            # none|constant|diurnal|flash|trace
    rate: float = 100.0           # base arrivals/sec (virtual time)
    period_s: float = 86400.0     # diurnal period
    amplitude: float = 0.8        # diurnal swing in [0, 1)
    flash_at_s: float = 300.0     # flash-crowd onset
    flash_duration_s: float = 60.0
    flash_boost: float = 10.0     # rate multiplier inside the burst
    trace_path: Optional[str] = None   # timestamps, one float per line
    seed: int = 0

    def __post_init__(self):
        if self.mode not in ARRIVAL_MODES:
            raise ValueError(f"unknown arrival mode {self.mode!r} "
                             f"(choose one of {ARRIVAL_MODES})")
        if not 0.0 <= self.amplitude < 1.0:
            raise ValueError(f"amplitude must be in [0, 1), got "
                             f"{self.amplitude}")
        if self.rate <= 0.0:
            raise ValueError(f"rate must be > 0, got {self.rate}")


class ArrivalProcess:
    """Base: rate(t) + thinning sampler + the scheduler's slowdown
    factor.  Subclasses define `rate(t)` and `peak_rate`; `seed` (set
    by make_arrivals from ArrivalConfig.seed) seeds `arrivals()` when
    the caller hands no Generator in."""

    peak_rate: float = 1.0
    seed: int = 0

    def rate(self, t: float) -> float:
        raise NotImplementedError

    def rate_fraction(self, t: float) -> float:
        """λ(t) / λ_peak in [0, 1] — the shape of the arrival process
        with its absolute rate divided out.  The connection swarm uses
        this to pace a live-socket fleet along the same diurnal/flash
        profile the virtual-time serve sim replays: offered_rate is
        the fleet's PEAK, and the instantaneous rate follows the
        profile."""
        if self.peak_rate <= 0.0:
            return 0.0
        return max(0.0, min(1.0, self.rate(t) / self.peak_rate))

    def slowdown(self, t: float) -> float:
        """How much slower the fleet responds at virtual time t than at
        peak load: λ_peak / λ(t), floored at 1 (peak = nominal).  The
        scheduler multiplies lifecycle latencies by this — a pure
        function of t, so seeded-determinism pins survive."""
        r = self.rate(t)
        if r <= 0.0:
            return float("inf")
        return max(1.0, self.peak_rate / r)

    def arrivals(self, t0: float = 0.0,
                 rng: Optional[np.random.Generator] = None
                 ) -> Iterator[float]:
        """Yield arrival times > t0, monotonically — the thinning
        sampler.  Deterministic per the generator handed in."""
        rng = rng if rng is not None else np.random.default_rng(self.seed)
        t = float(t0)
        lam = self.peak_rate
        while True:
            t += rng.exponential(1.0 / lam)
            if rng.random() * lam <= self.rate(t):
                yield t


class ConstantArrivals(ArrivalProcess):
    def __init__(self, rate: float):
        self.peak_rate = float(rate)

    def rate(self, t: float) -> float:
        return self.peak_rate


class DiurnalArrivals(ArrivalProcess):
    """λ(t) = base · (1 + a·sin(2πt/period)) — peak base·(1+a),
    trough base·(1−a)."""

    def __init__(self, rate: float, period_s: float, amplitude: float):
        self.base = float(rate)
        self.period = float(period_s)
        self.amplitude = float(amplitude)
        self.peak_rate = self.base * (1.0 + self.amplitude)

    def rate(self, t: float) -> float:
        return self.base * (1.0 + self.amplitude
                            * np.sin(2.0 * np.pi * t / self.period))


class FlashCrowdArrivals(DiurnalArrivals):
    """Diurnal base with a flash-crowd burst: λ multiplied by `boost`
    inside [at, at + duration) — the push-notification stampede."""

    def __init__(self, rate: float, period_s: float, amplitude: float,
                 flash_at_s: float, flash_duration_s: float,
                 flash_boost: float):
        super().__init__(rate, period_s, amplitude)
        self.flash_at = float(flash_at_s)
        self.flash_end = float(flash_at_s) + float(flash_duration_s)
        self.boost = float(flash_boost)
        self.peak_rate = self.base * (1.0 + self.amplitude) * self.boost

    def rate(self, t: float) -> float:
        r = super().rate(t)
        if self.flash_at <= t < self.flash_end:
            r *= self.boost
        return r


class TraceArrivals(ArrivalProcess):
    """Replay an explicit timestamp array verbatim (sorted ascending).
    rate(t) is the empirical rate in a sliding window — only the
    slowdown consumer reads it; `arrivals()` replays exactly."""

    def __init__(self, times, window_s: float = 60.0):
        self.times = np.sort(np.asarray(times, np.float64).reshape(-1))
        if self.times.size == 0:
            raise ValueError("empty arrival trace")
        self.window = float(window_s)
        span = max(float(self.times[-1] - self.times[0]), self.window)
        self.peak_rate = max(self._window_rate(t) for t in self.times)
        self._mean_rate = self.times.size / span

    @classmethod
    def from_file(cls, path: str, **kw) -> "TraceArrivals":
        return cls(np.loadtxt(path, dtype=np.float64, ndmin=1), **kw)

    def _window_rate(self, t: float) -> float:
        lo = np.searchsorted(self.times, t - self.window)
        hi = np.searchsorted(self.times, t, side="right")
        return max(float(hi - lo), 1.0) / self.window

    def rate(self, t: float) -> float:
        if t < self.times[0] or t > self.times[-1]:
            return self._mean_rate
        return self._window_rate(t)

    def arrivals(self, t0: float = 0.0, rng=None) -> Iterator[float]:
        for t in self.times:
            if t > t0:
                yield float(t)


def make_arrivals(cfg: ArrivalConfig) -> Optional[ArrivalProcess]:
    """ArrivalConfig -> process (None for mode 'none'); cfg.seed
    becomes the process's default `arrivals()` stream seed."""
    if cfg.mode == "none":
        return None
    if cfg.mode == "constant":
        proc = ConstantArrivals(cfg.rate)
    elif cfg.mode == "diurnal":
        proc = DiurnalArrivals(cfg.rate, cfg.period_s, cfg.amplitude)
    elif cfg.mode == "flash":
        proc = FlashCrowdArrivals(cfg.rate, cfg.period_s, cfg.amplitude,
                                  cfg.flash_at_s, cfg.flash_duration_s,
                                  cfg.flash_boost)
    elif cfg.trace_path is None:
        raise ValueError("arrival mode 'trace' needs trace_path")
    else:
        proc = TraceArrivals.from_file(cfg.trace_path)
    proc.seed = cfg.seed
    return proc
