"""fedml_tpu.scale — the million-client serving spine (ISSUE 10).

Sharded O(1)-per-round client registry, streaming cohort samplers over
its eligibility mask, on-demand client-shard stores, trace-driven
arrival processes, and the virtual-time serve simulation behind
`bench.py --mode serve`.
"""
from fedml_tpu.scale.arrivals import (ARRIVAL_MODES, ArrivalConfig,
                                      ArrivalProcess, ConstantArrivals,
                                      DiurnalArrivals, FlashCrowdArrivals,
                                      TraceArrivals, make_arrivals)
from fedml_tpu.scale.registry import (BANNED, BYTES_PER_CLIENT, CRASHED,
                                      DEAD, FREE, IN_FLIGHT,
                                      ClientRegistry)
from fedml_tpu.scale.sampler import SAMPLER_MODES, StreamingCohortSampler
from fedml_tpu.scale.serve import run_serve_sim, rss_bytes
from fedml_tpu.scale.shardstore import (GeneratorShardStore,
                                        MaterializedShardStore,
                                        MmapShardStore, ShardStore)

__all__ = [
    "ARRIVAL_MODES", "ArrivalConfig", "ArrivalProcess",
    "ConstantArrivals", "DiurnalArrivals", "FlashCrowdArrivals",
    "TraceArrivals", "make_arrivals",
    "BANNED", "BYTES_PER_CLIENT", "CRASHED", "DEAD", "FREE", "IN_FLIGHT",
    "ClientRegistry",
    "SAMPLER_MODES", "StreamingCohortSampler",
    "run_serve_sim", "rss_bytes",
    "GeneratorShardStore", "MaterializedShardStore", "MmapShardStore",
    "ShardStore",
]
