"""Fused serving cluster (ISSUE 18) — live sockets feeding
registry-sharded lanes across the elastic multi-host tier.

Every scale axis existed separately before this module: the reactor
(PR 11) sustains 10k live connections but single-process, the
`_ServeLane` loop (PRs 13–14) runs cluster-wide but in virtual time
over synthetic arrivals, and the 1M-client registry (PR 10) had never
been fed by a socket.  Here they fuse:

    reactor      one ReactorGroup per host fronts that host's
                 registry-shard range — the uplink path rides the
                 EXISTING `_deliver_frame` chokepoint (chaos filter,
                 FMLR reliability envelope, decode pool), not a fork
    lanes        decoded rows land in per-range ClusterLanes: the
                 streaming AsyncBuffer fold per lane, per-lane FIFO
                 backlog for rows arriving past a full window (socket
                 arrival ORDER never crosses a window boundary)
    fold         at each commit barrier the host takes every hosted
                 lane's partial IN ITEM ORDER and folds cross-host
                 through ElasticChannel exactly as run_serve_sim does —
                 pack_partial/fold_partials are THE shared functions,
                 so the commit-barrier fold order stays a pure function
                 of the block/lane partition
    shed gate    registry/lane pressure feeds the reactor's
                 set_overload_gate: a host whose lanes are saturated
                 (window full AND backlog at cap) rejects new
                 connections at the door instead of accepting uplinks
                 it would drop

Two invariants, both pinned by tests/test_cluster_serve.py:

  * world==1 with the synthetic-arrival serve sim and a reactor-fed
    lane given the SAME row sequence commit byte-identical digests —
    the fusion adds transport, not math;
  * cross-rank digest equality holds with live ingest, because every
    rank folds the identical exchanged payload bytes in item order.

`bench.py --mode cluster` (schema v16) drives this with a multi-target
connswarm fleet striped across the host endpoints.
"""
from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from fedml_tpu import obs
from fedml_tpu.obs import propagate
from fedml_tpu.obs import slo as obs_slo
from fedml_tpu.obs.metrics import quantile_from_cumulative
from fedml_tpu.async_.lifecycle import AsyncMessage, AsyncServerManager
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.scale.registry import ClientRegistry
from fedml_tpu.scale.serve import (fold_partials, pack_partial, rss_bytes,
                                   zero_partial)

log = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")


class ClusterLane:
    """One registry-shard range's live-socket serving state: sharded
    registry over [lo, hi), a streaming AsyncBuffer sized to the
    commit window, and a bounded FIFO backlog for uplinks that arrive
    while the current window is already full.  `item` is the range's
    index in the ORIGINAL world-sized partition — the cross-host fold
    is always in item order, so the global mix is independent of which
    host (or socket) delivered which row when."""

    def __init__(self, item: int, lo: int, hi: int, *, buffer_k: int,
                 row_dim: int, backlog_cap: int,
                 start_version: int = 0):
        from fedml_tpu.async_.staleness import AsyncBuffer
        self.item = int(item)
        self.lo, self.hi = int(lo), int(hi)
        self.local_population = max(1, self.hi - self.lo)
        self.buffer_k = int(buffer_k)
        self.registry = ClientRegistry(self.local_population)
        self.buffer = AsyncBuffer(buffer_k, row_dim, streaming=True)
        self.backlog: deque = deque()
        self.backlog_cap = int(backlog_cap)
        self.version = int(start_version)
        self.admitted = 0
        self.overflow_dropped = 0
        # set the first time ANY uplink routes here (admitted, parked,
        # or dropped): an untouched lane — typically a re-adopted dead
        # host's range with no sockets pointed at it — must not gate
        # the window barrier at full deadline every commit
        self.touched = False

    def full(self) -> bool:
        return self.buffer.count >= self.buffer_k

    def saturated(self) -> bool:
        """Window full AND backlog at cap: this lane cannot absorb
        another uplink without dropping — the shed-gate signal."""
        return (self.buffer.count >= self.buffer_k
                and len(self.backlog) >= self.backlog_cap)


class ClusterServeManager(AsyncServerManager):
    """One host of the fused serving cluster: the PR-11 reactor
    transport + PR-6 decode pool of AsyncServerManager, with the ONE
    insert path (`_ingest_row`) rerouted into per-range ClusterLanes
    instead of the single async buffer.  Commits are NOT triggered
    here — the cross-host driver (run_cluster_serve) closes windows at
    the commit barrier, so a socket burst can never race a partial
    into the wrong window: rows past a full window park in the lane's
    FIFO backlog and drain, in arrival order, into the NEXT window."""

    def __init__(self, row_dim: int, *, population: int,
                 cluster_rank: int = 0, world: int = 1,
                 buffer_k: int = 16, port: int = 54300,
                 n_connections: int = 256, ingest_pool: int = 2,
                 backlog_cap: Optional[int] = None,
                 sparse_uplink: bool = False,
                 reactor_config=None):
        import os as _os
        from fedml_tpu.comm.reactor import ReactorConfig
        if reactor_config is None:
            reactor_config = ReactorConfig(
                reactors=max(2, (_os.cpu_count() or 2)),
                max_connections=max(n_connections + 64, 256),
                stall_timeout_s=30.0,
                shed_on_pressure=True, shed_after_s=2.0)
        self.row_dim = int(row_dim)
        self.population = int(population)
        self.cluster_rank = int(cluster_rank)
        self.world = int(world)
        self._backlog_cap = (int(backlog_cap) if backlog_cap is not None
                             else 4 * int(buffer_k))
        self._lanes: dict[int, ClusterLane] = {}
        self._retired_lanes: list[ClusterLane] = []
        self._hosted: tuple = ()
        self._rr = 0
        self.misrouted = 0
        template = {"w": np.zeros((row_dim,), np.float32)}
        super().__init__(
            template, 1 << 62, buffer_k, 0, n_connections + 1, "TCP",
            staleness_mode="constant", mix=1.0, streaming=True,
            ingest_pool=ingest_pool, decode_into=True,
            sparse_uplink=sparse_uplink, redispatch=False,
            ip_config={0: "127.0.0.1"}, base_port=port,
            force_python_tcp=True, reactor=True,
            reactor_config=reactor_config)
        # window barrier: _ingest_row notifies when a lane fills; the
        # driver waits on it holding the SAME manager lock the insert
        # path times into async_lock_wait_seconds
        self._window_cv = threading.Condition(self._lock)
        self._adopt_locked(self.cluster_rank, 0)
        # satellite (ISSUE 18): registry/lane pressure reaches the
        # reactor's door — before this only decode-pool depth and RSS
        # fed the gate, so a lane-bound host kept accepting uplinks it
        # would drop at the backlog cap
        rg = getattr(self.com_manager, "_rg", None)
        if rg is not None:
            rg.set_overload_gate(self.lane_pressure)

    # -- lane partition ------------------------------------------------------
    def _range_of(self, item: int) -> tuple:
        return (item * self.population // self.world,
                (item + 1) * self.population // self.world)

    def _adopt_locked(self, item: int, start_version: int) -> ClusterLane:
        lo, hi = self._range_of(item)
        lane = ClusterLane(item, lo, hi, buffer_k=self.buffer_k,
                           row_dim=self.row_dim,
                           backlog_cap=self._backlog_cap,
                           start_version=start_version)
        self._lanes[item] = lane
        self._hosted = tuple(sorted(self._lanes))
        return lane

    def adopt(self, item: int, start_version: int) -> None:
        with self._lock:
            if item not in self._lanes:
                self._adopt_locked(item, start_version)
                obs.instant("cluster.readopt", item=item,
                            rank=self.cluster_rank,
                            version=start_version)

    def retire(self, item: int) -> None:
        with self._lock:
            lane = self._lanes.pop(item, None)
            if lane is not None:
                self._retired_lanes.append(lane)
                self._hosted = tuple(sorted(self._lanes))

    def hosted_items(self) -> tuple:
        return self._hosted

    def all_lanes(self) -> list:
        return list(self._lanes.values()) + self._retired_lanes

    # -- shed gate -----------------------------------------------------------
    def lane_pressure(self) -> bool:
        """True while ANY hosted lane is saturated (window full +
        backlog at cap) — installed as the reactor's overload gate, so
        the door sheds instead of the backlog dropping.  Runs on the
        reactor loop thread: reads the hosted snapshot tuple, never
        iterates the mutable dict."""
        lanes = self._lanes
        for item in self._hosted:
            lane = lanes.get(item)
            if lane is not None and lane.saturated():
                return True
        return False

    # -- THE insert path (decode pool + FSM route both land here) ------------
    def _ingest_row(self, sender: int, row: np.ndarray, weight: float,
                    dispatched: int, *, sparse=None) -> None:
        t0 = time.perf_counter()
        self._lock.acquire()
        self._m_lock_wait.inc(time.perf_counter() - t0)
        try:
            if self.done.is_set():
                return                  # late straggler after shutdown
            hosted = self._hosted
            if not hosted:
                self.misrouted += 1
                return                  # view moved every range away
            # a sender inside a hosted range lands in ITS range's lane
            # (registry attribution); anything else — a test fleet's
            # baked sender id, a client whose range another host owns —
            # round-robins across the hosted lanes
            item = (sender % self.population) * self.world \
                // self.population
            lane = self._lanes.get(item)
            if lane is None:
                lane = self._lanes[hosted[self._rr % len(hosted)]]
                self._rr += 1
            lane.touched = True
            staleness = float(lane.version - dispatched)
            if lane.full() or lane.backlog:
                # window closed (or rows already queued behind it):
                # park IN ARRIVAL ORDER for the next window — socket
                # timing must not decide which window a row folds into
                # beyond this FIFO
                if len(lane.backlog) >= lane.backlog_cap:
                    lane.overflow_dropped += 1
                    return
                # row is a borrowed scratch buffer (recycled by the
                # decode pool once we return) — parking needs a copy;
                # the direct fold below does not, AsyncBuffer.add
                # blocks until the fold consumed it.  Sparse pairs are
                # fresh arrays (decode_sparse concatenates), so they
                # park as-is under the same 4-tuple shape.
                lane.backlog.append((sparse if sparse is not None
                                     else row.copy(), float(weight),
                                     staleness, int(sender)))
            else:
                self._admit_locked(lane, sparse if sparse is not None
                                   else row, weight, staleness, sender)
            if lane.full():
                self._window_cv.notify_all()
        finally:
            self._lock.release()

    def _admit_locked(self, lane: ClusterLane, row, weight: float,
                      staleness: float, sender: int) -> None:
        with obs.span("ingest.fold", sender=sender):
            if isinstance(row, tuple):
                # (idx, vals) pairs from a sparse_topk frame (ISSUE
                # 19): the jitted scatter fold, never a dense row
                lane.buffer.add_sparse(row[0], row[1], weight, staleness)
            else:
                lane.buffer.add(row, weight, staleness)
        lane.admitted += 1
        self.staleness_seen.append(staleness)
        self._m_staleness.observe(staleness)
        self._m_occupancy.set(lane.buffer.count)
        lane.registry.note_push(sender % lane.local_population,
                                staleness, lane.version)

    # -- window barrier ------------------------------------------------------
    def wait_window(self, deadline_s: float) -> bool:
        """Block until EVERY hosted lane's window is full, or the
        deadline passes (an adopted lane with no socket traffic must
        not wedge the cluster barrier — it contributes whatever it
        has, possibly zero, which is deterministic on every rank).
        Returns False on a deadline close."""
        deadline = time.perf_counter() + float(deadline_s)
        with self._window_cv:
            while True:
                # only lanes that have EVER seen traffic gate the
                # barrier: a freshly adopted dead-host range with no
                # sockets pointed at it folds zero without pacing
                # every cluster commit at the full deadline
                active = [self._lanes[i] for i in self._hosted
                          if self._lanes[i].touched]
                if active and all(ln.full() for ln in active):
                    return True
                left = deadline - time.perf_counter()
                if left <= 0.0:
                    return False
                self._window_cv.wait(min(left, 0.05))

    def take_partials(self) -> dict:
        """Close the window: per hosted lane IN ITEM ORDER, take the
        streaming partial and drain the backlog into the fresh window
        (FIFO — the order the sockets delivered).  Returns
        {item: (acc, wsum, n)} for the driver's cross-host fold."""
        out = {}
        with self._lock:
            for item in self._hosted:
                lane = self._lanes[item]
                acc, wsum, _w, _s, n, _raw = lane.buffer.take_stream()
                out[item] = (acc, wsum, int(n))
                lane.version += 1
                while lane.backlog and not lane.full():
                    row, w, s, sender = lane.backlog.popleft()
                    self._admit_locked(lane, row, w, s, sender)
                if lane.full():
                    self._window_cv.notify_all()
        return out


# ---------------------------------------------------------------------------
# uplink frame helpers — the swarm's payload and the tests' senders
# ---------------------------------------------------------------------------

def make_uplink_frame(row: np.ndarray, *, sender: int = 1,
                      weight: float = 1.0, version: int = 0,
                      transport: Optional[str] = None) -> bytes:
    """One pre-encoded C2S result frame carrying a flat f32 row under
    the cluster template {"w": row}.  weight rides NUM_SAMPLES; the
    cluster runs constant staleness weights, so the version echo is
    weight-neutral.  `transport` opts the row into a lossy v2 wire
    dtype ("bf16" | "int8" | "sparse_topk" — ISSUE 19); None keeps the
    exact v1 frame."""
    msg = Message(AsyncMessage.MSG_TYPE_C2S_ASYNC_RESULT, sender, 0)
    msg.add_params(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS,
                   {"w": np.asarray(row, np.float32)})
    msg.add_params(AsyncMessage.MSG_ARG_KEY_NUM_SAMPLES, float(weight))
    msg.add_params(AsyncMessage.MSG_ARG_KEY_VERSION, int(version))
    if transport is not None:
        msg.set_wire_transport(AsyncMessage.MSG_ARG_KEY_MODEL_PARAMS,
                               transport)
    propagate.stamp(msg, sender)
    return MessageCodec.encode(msg)


def send_uplinks(host: str, port: int, frames, *,
                 hold_open: Optional[threading.Event] = None,
                 timeout_s: float = 30.0) -> None:
    """Test helper: one blocking socket, frames length-prefixed in
    order (the transport preserves it; with ingest_pool=1 the decode
    pool does too — the world==1 byte-identity pin's premise).  Keeps
    the connection open until `hold_open` is set so the server never
    sees a mid-run hangup."""
    s = socket.create_connection((host, port), timeout=timeout_s)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        for f in frames:
            s.sendall(_LEN.pack(len(f)) + f)
        if hold_open is not None:
            hold_open.wait(timeout=timeout_s)
    finally:
        try:
            s.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# the per-host driver — commit barrier + cross-host fold
# ---------------------------------------------------------------------------

def run_cluster_serve(population: int, *, commits: int,
                      warmup_commits: int = 2, buffer_k: int = 16,
                      row_dim: int = 256, port: int = 54300,
                      partition: tuple = (0, 1), channel=None,
                      elastic: bool = False, n_connections: int = 64,
                      ingest_pool: int = 2,
                      window_deadline_s: float = 20.0,
                      timeout_s: float = 600.0,
                      backlog_cap: Optional[int] = None,
                      sparse_uplink: bool = False,
                      reactor_config=None, chaos: Optional[dict] = None,
                      chaos_seed: int = 0,
                      crash_at_commit: Optional[int] = None,
                      slo_window: bool = False) -> dict:
    """Serve `commits` commit windows of live-socket uplinks on this
    host's registry-shard range, folding lane partials cross-host at
    each commit barrier exactly as run_serve_sim does (same
    pack/fold/zero functions, same ElasticChannel contract, same
    re-adoption semantics).  Returns the host report — committed
    digest, local + cluster-wide committed-updates/sec, admission
    percentiles, and every shed/eviction/drop counter.

    `crash_at_commit` is the chaos arm's fault hook: this host
    abruptly closes its channel after that many commits and returns a
    partial report (the worker process then exits nonzero, and the
    survivors' next exchange evicts it — re-adoption exactly as in the
    virtual-time serve path, except an adopted lane here has no
    sockets pointed at it, so its windows close at the deadline with
    whatever arrived: deterministic zeros on every survivor)."""
    import jax.numpy as jnp
    from fedml_tpu.async_.staleness import make_stream_commit_fn
    from fedml_tpu.comm.chaos import ChaosConfig, ChaosPolicy
    from fedml_tpu.parallel.multihost import variables_digest

    if commits <= warmup_commits:
        raise ValueError(f"commits ({commits}) must exceed "
                         f"warmup_commits ({warmup_commits})")
    rank, world = int(partition[0]), int(partition[1])
    if not 0 <= rank < world:
        raise ValueError(f"partition rank {rank} outside world {world}")
    if world > 1 and channel is None:
        raise ValueError("world > 1 needs a channel to fold the "
                         "partial aggregates upward")
    if elastic and world > 1 and not hasattr(channel, "exchange"):
        raise ValueError("elastic=True needs an ElasticChannel "
                         "(n_items=world)")

    mgr = ClusterServeManager(
        row_dim, population=population, cluster_rank=rank, world=world,
        buffer_k=buffer_k, port=port, n_connections=n_connections,
        ingest_pool=ingest_pool, backlog_cap=backlog_cap,
        sparse_uplink=sparse_uplink, reactor_config=reactor_config)
    if chaos:
        mgr.com_manager.install_chaos(
            ChaosPolicy(ChaosConfig(seed=chaos_seed, **chaos)))
    mgr.run_async()

    slo_eng = None
    if slo_window:
        slo_eng = obs_slo.SloEngine(obs_slo.default_slo_pack(),
                                    dump_min_interval_s=30.0)
        slo_eng.prime()
    hist_adm = obs.histogram("comm_admission_seconds")
    evict = {r: obs.counter("comm_connections_evicted_total",
                            backend="tcp", reason=r)
             for r in ("stall", "rate", "shed", "idle", "protocol",
                       "error")}
    shed = obs.counter("comm_uplinks_shed_total", backend="tcp")
    drained = obs.counter("comm_connections_drained_total", backend="tcp")
    deaths = obs.counter("comm_recv_thread_deaths_total")
    dups = obs.counter("comm_reliable_dups_suppressed_total")
    quar = obs.counter("comm_frames_quarantined_total")
    base = {"evict": {r: c.value for r, c in evict.items()},
            "shed": shed.value, "drained": drained.value,
            "deaths": deaths.value, "dups": dups.value,
            "quar": quar.value, "adm": hist_adm.cumulative()}

    zero_payload = zero_partial(row_dim)
    template = {"w": jnp.zeros((row_dim,), jnp.float32)}
    commit_fn = make_stream_commit_fn(template, donate=False)
    variables = template
    version = 0
    deadline_windows = 0
    empty_commits = 0
    global_wsum = 0.0
    commit_walls: list = []     # per-commit wall time (barrier to barrier)
    commit_wsums: list = []     # per-commit folded GLOBAL weight
    adopted_items: list[int] = []
    crashed_out = False
    t_wall0 = time.perf_counter()
    t_commit_prev = t_wall0
    hard_deadline = t_wall0 + float(timeout_s)
    t_timed = None
    admitted_at_warmup = 0
    global_at_warmup = 0.0
    adm0 = base["adm"]

    def lanes_admitted() -> int:
        return sum(ln.admitted for ln in mgr.all_lanes())

    try:
        with obs.span("cluster.run", population=population,
                      commits=commits, rank=rank, world=world,
                      elastic=elastic):
            while version < commits:
                if time.perf_counter() > hard_deadline:
                    obs.dump_flight("cluster_serve_stall")
                    raise TimeoutError(
                        f"cluster serve stalled: {version}/{commits} "
                        f"commits in {timeout_s}s (rank {rank}/"
                        f"{world}, {lanes_admitted()} admitted)")
                if (crash_at_commit is not None
                        and version == crash_at_commit):
                    # fault injection: this host vanishes mid-run — the
                    # survivors' next exchange evicts it and re-adopts
                    # its range at their next commit barrier
                    if channel is not None:
                        channel.close()
                    crashed_out = True
                    break
                if not mgr.wait_window(window_deadline_s):
                    deadline_windows += 1
                partials = mgr.take_partials()
                with obs.span("cluster.commit", version=version,
                              rank=rank):
                    n_committed = sum(p[2] for p in partials.values())
                    if world > 1 and elastic:
                        payloads = {item: pack_partial(acc, wsum)
                                    for item, (acc, wsum, _n)
                                    in partials.items()}
                        allp, view = channel.exchange(
                            version, payloads,
                            lambda items: {i: zero_payload
                                           for i in items})
                        acc, wsum = fold_partials(
                            (allp[item] for item in range(world)),
                            row_dim)
                    elif world > 1:
                        acc, wsum, _n = partials[rank]
                        docs = channel.allgather(pack_partial(acc, wsum))
                        acc, wsum = fold_partials(docs, row_dim)
                    else:
                        # world==1 folds its single partial DIRECTLY —
                        # no pack/unpack round trip, byte-identical to
                        # the pre-fusion serve path
                        acc, wsum, _n = partials[rank]
                    # an all-empty window (every lane deadline-closed
                    # with zero arrivals, cluster-wide) must not fold
                    # acc/0 NaNs into the model — the folded wsum is
                    # identical on every rank, so the skip is too
                    if float(wsum) > 0.0:
                        variables, _stats = commit_fn(
                            variables, acc, wsum, jnp.float32(1.0))
                    else:
                        empty_commits += 1
                global_wsum += float(wsum)
                t_now = time.perf_counter()
                commit_walls.append(t_now - t_commit_prev)
                t_commit_prev = t_now
                commit_wsums.append(float(wsum))
                obs.counter("async_updates_committed_total").inc(
                    n_committed)
                version += 1
                if world > 1 and elastic:
                    # the commit barrier re-partitions lanes onto the
                    # view — exactly ONE host per range, as in
                    # run_serve_sim
                    for item in list(mgr.hosted_items()):
                        if view.owner_of(item) != rank:
                            mgr.retire(item)
                    for item in view.assigned(rank):
                        if item not in mgr.hosted_items():
                            mgr.adopt(item, version)
                            adopted_items.append(item)
                if version == warmup_commits:
                    t_timed = time.perf_counter()
                    admitted_at_warmup = lanes_admitted()
                    global_at_warmup = global_wsum
                    adm0 = hist_adm.cumulative()
    finally:
        mgr.finish()

    wall = time.perf_counter() - (t_timed if t_timed is not None
                                  else t_wall0)
    timed_updates = lanes_admitted() - (admitted_at_warmup
                                        if t_timed is not None else 0)
    timed_global = global_wsum - (global_at_warmup
                                  if t_timed is not None else 0.0)
    adm1 = hist_adm.cumulative()
    if adm1[-1][1] - adm0[-1][1] <= 0:
        adm0 = base["adm"]          # run outpaced the warmup snapshot
    rg = getattr(mgr.com_manager, "_rg", None)
    report = {
        "population": int(population),
        "partition": [rank, world],
        "port": int(port),
        "committed_digest": variables_digest(variables),
        "commits": int(version),
        "committed_updates": int(lanes_admitted()),
        "committed_updates_per_sec": (timed_updates / wall
                                      if wall > 0 else 0.0),
        "cluster_updates_per_sec": (timed_global / wall
                                    if wall > 0 else 0.0),
        "commit_walls_s": [round(w, 6) for w in commit_walls],
        "commit_wsums": [round(w, 2) for w in commit_wsums],
        "admission_p50_s": quantile_from_cumulative(adm0, adm1, 0.50),
        "admission_p95_s": quantile_from_cumulative(adm0, adm1, 0.95),
        "buffer_k": int(buffer_k),
        "row_dim": int(row_dim),
        "ingest_pool": int(ingest_pool),
        "n_connections": int(n_connections),
        "window_deadline_s": float(window_deadline_s),
        "deadline_windows": int(deadline_windows),
        "empty_commits": int(empty_commits),
        "lane_overflow_dropped": int(sum(ln.overflow_dropped
                                         for ln in mgr.all_lanes())),
        "misrouted": int(mgr.misrouted),
        "open_connections_peak": (int(rg.peak_connections)
                                  if rg is not None else 0),
        "shed_reasons": (dict(rg.shed_reasons) if rg is not None
                         else {}),
        "evicted": {r: c.value - base["evict"][r]
                    for r, c in evict.items()},
        "uplinks_shed": shed.value - base["shed"],
        "connections_drained": drained.value - base["drained"],
        "recv_thread_deaths": deaths.value - base["deaths"],
        "dups_suppressed": dups.value - base["dups"],
        "quarantined": quar.value - base["quar"],
        "registry_bytes": int(sum(ln.registry.nbytes
                                  for ln in mgr.all_lanes())),
        "rss_bytes": rss_bytes(),
        "wall_s": float(wall),
        "chaos_injected": bool(chaos),
        "sparse_uplink": bool(sparse_uplink),
    }
    if elastic:
        report["elastic"] = {
            "lanes": sorted(mgr.hosted_items()),
            "adopted_items": adopted_items,
            "retired_items": [ln.item for ln in mgr._retired_lanes],
            "crashed_at_commit": (crash_at_commit if crashed_out
                                  else None),
            "epoch": (channel.view.epoch
                      if channel is not None
                      and hasattr(channel, "view") else 0),
            "view_changes": (len(channel.view_events)
                             if channel is not None
                             and hasattr(channel, "view_events")
                             else 0),
        }
    if slo_eng is not None:
        slo_eng.evaluate()
        report["slo_arm"] = slo_eng.arm_summary()
    return report
