"""On-demand client-shard stores — cohort stacks without the
all-client stack.

`FederatedData` keeps every client's padded shard in ONE stacked array
set ([C, B, bs, ...]) resident in host RAM/HBM — the right layout up to
the proven 342k-client stack build, and a dead end at millions: the
stack is built (and held) for clients that may never be sampled.  A
ShardStore inverts that: client shards materialize ON DEMAND, per
cohort, so host memory is O(cohort · shard) + a bounded reuse cache,
and the cohort-build cost is amortized across rounds by that cache
(FedJAX's sharded-dataset iterator shape, arXiv:2108.02117 §4).

Every store speaks `FederatedData.cohort`'s contract —
``cohort(ids) -> ({x, y, mask} stacked [K, B, bs, ...], weights [K])``
— so the async scheduler (and anything else that gathers cohorts) takes
either interchangeably, and `prefetcher()` wraps the PR-1 double-buffer
(`parallel/prefetch.py`) around any store so cohort k+1 builds while
the chip trains on k.

Backends:

    MaterializedShardStore   adapter over an existing FederatedData —
                             the oracle the others are pinned against
                             (bitwise, tests/test_scale.py).
    MmapShardStore           the stacked arrays live in .npy files and
                             are opened memory-mapped: a cohort gather
                             touches only the cohort's pages, so RSS is
                             O(touched clients), not O(population).
    GeneratorShardStore      shards are synthesized per client id by a
                             seeded factory — no backing array of any
                             size ever exists (the 1M+ simulation
                             story), deterministic per (seed, client).
"""
from __future__ import annotations

import os
from collections import OrderedDict
from typing import Callable, Optional, Sequence

import numpy as np

from fedml_tpu import obs


class ShardStore:
    """Base: per-client fetch + bounded LRU reuse cache + cohort
    stacking.  Subclasses implement `_fetch(cid) -> {x, y, mask}` (host
    numpy, one client's [B, bs, ...] arrays) and `_weight(cid)`."""

    def __init__(self, n_clients: int, cache_clients: int = 0):
        self.n_clients = int(n_clients)
        self.cache_clients = int(cache_clients)
        self._cache: "OrderedDict[int, dict]" = OrderedDict()
        self._m_hits = obs.counter("shardstore_cache_hits_total")
        self._m_miss = obs.counter("shardstore_cache_misses_total")

    # -- subclass surface ----------------------------------------------------
    def _fetch(self, cid: int) -> dict:
        raise NotImplementedError

    def _weight(self, cid: int) -> float:
        raise NotImplementedError

    # -- the cohort contract -------------------------------------------------
    def client_shard(self, cid: int) -> dict:
        """One client's {x, y, mask}, through the reuse cache."""
        if not 0 <= cid < self.n_clients:
            raise IndexError(f"client id {cid} out of range "
                             f"[0, {self.n_clients})")
        if self.cache_clients > 0:
            hit = self._cache.get(cid)
            if hit is not None:
                self._cache.move_to_end(cid)
                self._m_hits.inc()
                return hit
        self._m_miss.inc()
        shard = self._fetch(cid)
        if self.cache_clients > 0:
            self._cache[cid] = shard
            while len(self._cache) > self.cache_clients:
                self._cache.popitem(last=False)
        return shard

    def cohort(self, client_indices) -> tuple[dict, "object"]:
        """({x, y, mask} device-stacked [K, ...], weights [K]) — the
        FederatedData.cohort contract, built from on-demand shards."""
        import jax.numpy as jnp
        ids = np.asarray(client_indices, np.int64).reshape(-1)
        with obs.span("serve.cohort_build", clients=int(ids.size)):
            shards = [self.client_shard(int(c)) for c in ids]
            stacked = {k: np.stack([s[k] for s in shards])
                       for k in shards[0]} if shards else {}
            w = np.asarray([self._weight(int(c)) for c in ids], np.float32)
        return ({k: jnp.asarray(v) for k, v in stacked.items()},
                jnp.asarray(w))

    def prefetcher(self, cohorts: Sequence, depth: int = 2):
        """Wrap the PR-1 double buffer around this store: one
        `Prefetcher` whose items are cohort id arrays and whose
        produce() is `self.cohort` — cohort k+1 gathers/uploads on the
        background thread while k trains."""
        from fedml_tpu.parallel.prefetch import Prefetcher
        return Prefetcher(self.cohort, list(cohorts), depth=depth,
                          name="shardstore-prefetch")


class MaterializedShardStore(ShardStore):
    """Adapter over an existing FederatedData stack — the bitwise
    oracle (its cohort() must equal data.cohort())."""

    def __init__(self, data, cache_clients: int = 0):
        super().__init__(data.client_num, cache_clients)
        self._data = data

    def _fetch(self, cid: int) -> dict:
        return {k: np.asarray(v[cid])
                for k, v in self._data.client_shards.items()}

    def _weight(self, cid: int) -> float:
        return float(self._data.client_num_samples[cid])

    def cohort(self, client_indices):
        # delegate to the stack's device-side gather — this adapter
        # exists to give materialized data the ShardStore interface
        # (and the oracle cohorts), not to slow it down
        return self._data.cohort(np.asarray(client_indices, np.int64))


class MmapShardStore(ShardStore):
    """Client shards in .npy files opened memory-mapped: the OS pages
    in only the clients a cohort touches.  `build()` writes a
    FederatedData's stack out once; reopening is O(1)."""

    def __init__(self, directory: str, cache_clients: int = 0):
        self.directory = directory
        self._arrays = {}
        for name in ("x", "y", "mask"):
            self._arrays[name] = np.load(
                os.path.join(directory, f"{name}.npy"), mmap_mode="r")
        self._weights = np.load(os.path.join(directory, "weights.npy"))
        super().__init__(self._arrays["mask"].shape[0], cache_clients)

    @classmethod
    def build(cls, data, directory: str,
              cache_clients: int = 0) -> "MmapShardStore":
        os.makedirs(directory, exist_ok=True)
        for name, arr in data.client_shards.items():
            # open_memmap + copy writes without doubling host RAM
            out = np.lib.format.open_memmap(
                os.path.join(directory, f"{name}.npy"), mode="w+",
                dtype=arr.dtype, shape=arr.shape)
            out[:] = arr
            out.flush()
            del out
        np.save(os.path.join(directory, "weights.npy"),
                np.asarray(data.client_num_samples, np.float32))
        return cls(directory, cache_clients)

    def _fetch(self, cid: int) -> dict:
        # np.asarray forces the page-in copy OUT of the mmap so a cached
        # shard never pins mmap pages
        return {k: np.asarray(v[cid]) for k, v in self._arrays.items()}

    def _weight(self, cid: int) -> float:
        return float(self._weights[cid])


class GeneratorShardStore(ShardStore):
    """Shards synthesized per client id — deterministic per (seed,
    client), nothing population-sized ever allocated.  `make_shard`
    takes (client_id, rng) and returns host {x, y, mask} arrays;
    omitted, a small seeded gaussian-image shard is generated (the
    serve simulation's default)."""

    def __init__(self, n_clients: int, seed: int = 0,
                 make_shard: Optional[Callable] = None,
                 batches: int = 2, batch_size: int = 8,
                 sample_shape: tuple = (16,), n_classes: int = 10,
                 cache_clients: int = 0):
        super().__init__(n_clients, cache_clients)
        self.seed = int(seed)
        self._make = make_shard
        self._batches = batches
        self._bs = batch_size
        self._shape = tuple(sample_shape)
        self._classes = n_classes

    def _rng(self, cid: int) -> np.random.Generator:
        return np.random.default_rng([self.seed, cid])

    def _fetch(self, cid: int) -> dict:
        rng = self._rng(cid)
        if self._make is not None:
            return self._make(cid, rng)
        shape = (self._batches, self._bs) + self._shape
        return {
            "x": rng.standard_normal(shape).astype(np.float32),
            "y": rng.integers(0, self._classes,
                              (self._batches, self._bs)).astype(np.int64),
            "mask": np.ones((self._batches, self._bs), np.float32),
        }

    def _weight(self, cid: int) -> float:
        # deterministic per client, independent of _fetch's draw order:
        # a dedicated stream, so weights match whether or not the shard
        # was ever fetched
        return float(np.random.default_rng(
            [self.seed, cid, 1]).integers(1, 40))
