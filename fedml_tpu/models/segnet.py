"""Compact encoder-decoder segmentation net (stand-in for the reference's
DeepLabV3+/MobileNet fedseg backbones — fedml_api/model/cv/ via fedseg).

GroupNorm (batch-independent) keeps the whole model in the params
collection, so FedAvg/vmap treat it like every other model.  Output:
per-pixel class logits [B, H, W, C].
"""
from __future__ import annotations

import flax.linen as nn


class SegEncoderDecoder(nn.Module):
    num_classes: int = 21
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.width
        # encoder: /4 spatial
        e1 = nn.relu(nn.GroupNorm(4)(nn.Conv(w, (3, 3), padding="SAME")(x)))
        d1 = nn.max_pool(e1, (2, 2), strides=(2, 2))
        e2 = nn.relu(nn.GroupNorm(4)(nn.Conv(2 * w, (3, 3),
                                             padding="SAME")(d1)))
        d2 = nn.max_pool(e2, (2, 2), strides=(2, 2))
        b = nn.relu(nn.GroupNorm(4)(nn.Conv(4 * w, (3, 3),
                                            padding="SAME")(d2)))
        # decoder with skip connections
        u1 = nn.ConvTranspose(2 * w, (2, 2), strides=(2, 2))(b)
        u1 = nn.relu(nn.GroupNorm(4)(nn.Conv(2 * w, (3, 3),
                                             padding="SAME")(u1 + e2)))
        u2 = nn.ConvTranspose(w, (2, 2), strides=(2, 2))(u1)
        u2 = nn.relu(nn.GroupNorm(4)(nn.Conv(w, (3, 3),
                                             padding="SAME")(u2 + e1)))
        return nn.Conv(self.num_classes, (1, 1))(u2)
