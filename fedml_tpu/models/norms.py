"""Normalization utilities.

Parity: reference cv/batchnorm_utils.py (462 LoC of manual sync-BN
machinery — callbacks, device broadcasts — for multi-GPU FedSeg).  On TPU
cross-replica BatchNorm needs none of that: flax's BatchNorm takes
`axis_name` and psums batch statistics over that mapped mesh axis.
`sync_batch_norm(...)` pins the convention so models opt in with one
argument; the parameter tree is identical either way, so a model trained
single-device loads onto a mesh unchanged.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

BATCH_AXIS = "clients"   # the mesh axis the engines map over
                         # (parallel/mesh.py CLIENT_AXIS)


def sync_batch_norm(use_running_average: Optional[bool] = None,
                    sync: bool = True,
                    axis_name: str = BATCH_AXIS,
                    momentum: float = 0.9, epsilon: float = 1e-5,
                    dtype: Any = None, **kw) -> nn.BatchNorm:
    """BatchNorm constructor with cross-replica statistics.

    sync=True → statistics psum over `axis_name` (the reference's
    SynchronizedBatchNorm2d); the model must then run under a mapped axis
    of that name (shard_map/pmap) — training it outside one raises
    `unbound axis name` at trace time.  sync=False → plain per-replica BN
    usable anywhere.  Both produce the identical parameter tree, so the
    flag can differ between training and deployment checkpoints."""
    return nn.BatchNorm(use_running_average=use_running_average,
                        axis_name=axis_name if sync else None,
                        momentum=momentum, epsilon=epsilon, dtype=dtype,
                        **kw)
