"""MobileNetV3 (reference fedml_api/model/cv/mobilenet_v3.py, 257 LoC torch).

Inverted-residual bottlenecks with squeeze-excite and hard-swish, in the
published Large/Small configurations.  CIFAR-sized stem (stride 1) to match
the reference's cross-silo CIFAR usage; pass `imagenet_stem=True` for the
224×224 stride-2 stem.  NHWC; depthwise = feature_group_count convolution.
"""
from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


def hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


def hard_swish(x):
    return x * hard_sigmoid(x)


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcite(nn.Module):
    reduce_ch: int

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(_make_divisible(self.reduce_ch))(s))
        s = hard_sigmoid(nn.Dense(c)(s))
        return x * s[:, None, None, :]


class InvertedResidual(nn.Module):
    """expand (1×1) → depthwise (k×k, stride) → [SE] → project (1×1)."""
    kernel: int
    exp_ch: int
    out_ch: int
    use_se: bool
    use_hs: bool
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5)
        act = hard_swish if self.use_hs else nn.relu
        inp = x.shape[-1]
        h = x
        if self.exp_ch != inp:
            h = act(norm()(nn.Conv(self.exp_ch, (1, 1), use_bias=False)(h)))
        h = nn.Conv(self.exp_ch, (self.kernel, self.kernel),
                    strides=self.stride, padding="SAME",
                    feature_group_count=self.exp_ch, use_bias=False)(h)
        h = act(norm()(h))
        if self.use_se:
            h = SqueezeExcite(self.exp_ch // 4)(h)
        h = norm()(nn.Conv(self.out_ch, (1, 1), use_bias=False)(h))
        if self.stride == 1 and inp == self.out_ch:
            h = h + x
        return h


# (kernel, exp, out, SE, HS, stride) — the published V3 configurations
_LARGE = [
    (3, 16, 16, False, False, 1), (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1), (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1), (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2), (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1), (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1), (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2), (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
]
_SMALL = [
    (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1), (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2), (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1),
]


class MobileNetV3(nn.Module):
    num_classes: int = 10
    mode: str = "large"            # "large" | "small"
    width_mult: float = 1.0
    dropout: float = 0.2
    imagenet_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = _LARGE if self.mode == "large" else _SMALL
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5)
        wm = self.width_mult
        stem_stride = 2 if self.imagenet_stem else 1
        x = nn.Conv(_make_divisible(16 * wm), (3, 3), strides=stem_stride,
                    padding="SAME", use_bias=False)(x)
        x = hard_swish(norm()(x))
        for k, exp, out, se, hs, s in cfg:
            x = InvertedResidual(k, _make_divisible(exp * wm),
                                 _make_divisible(out * wm), se, hs, s)(
                                     x, train)
        last = _make_divisible((960 if self.mode == "large" else 576) * wm)
        x = hard_swish(norm()(nn.Conv(last, (1, 1), use_bias=False)(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = hard_swish(nn.Dense(1280 if self.mode == "large" else 1024)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
