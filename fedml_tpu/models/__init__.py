"""Flax model zoo — TPU-native rebuild of reference fedml_api/model/ (§2.6).

`create_model(model_name, output_dim, **kw)` mirrors the reference's factory
(fedml_experiments/distributed/fedavg/main_fedavg.py:359-394).
"""
from __future__ import annotations

from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.models.cnn import CNNOriginalFedAvg, CNNDropOut
from fedml_tpu.models.rnn import RNNOriginalFedAvg, RNNStackOverflow
from fedml_tpu.models.resnet_gn import ResNet18GN
from fedml_tpu.models.resnet_cifar import resnet20, resnet32, resnet44, resnet56
from fedml_tpu.models.mobilenet import MobileNetV1
from fedml_tpu.models.mobilenet_v3 import MobileNetV3
from fedml_tpu.models.efficientnet import EfficientNet
from fedml_tpu.models.vgg import VGG11, VGG16


def create_model(model_name: str, output_dim: int, input_dim: int | None = None,
                 **kw):
    """Model factory keyed by the reference's --model names."""
    name = model_name.lower()
    if name == "lr":
        return LogisticRegression(num_classes=output_dim, flatten=True)
    if name == "cnn":
        return CNNOriginalFedAvg(num_classes=output_dim, **kw)
    if name == "cnn_dropout":
        return CNNDropOut(num_classes=output_dim, **kw)
    if name == "rnn":
        return RNNOriginalFedAvg(vocab_size=kw.pop("vocab_size", 90), **kw)
    if name == "rnn_stackoverflow":
        # vocab follows output_dim (callers pass the dataset's class
        # count, 10,004 for real stackoverflow) — ignoring it built a
        # 10,004-way softmax under reduced-vocab smokes
        return RNNStackOverflow(vocab_size=kw.pop("vocab_size",
                                                  output_dim), **kw)
    if name == "transformer":
        # beyond-reference: causal decoder LM for the next-token tasks
        # (models/transformer.py) — vocab from the dataset's class count
        from fedml_tpu.models.transformer import TransformerLM
        return TransformerLM(vocab_size=output_dim, **kw)
    if name in ("resnet18_gn", "resnet18"):
        return ResNet18GN(num_classes=output_dim, **kw)
    if name == "resnet56":
        return resnet56(num_classes=output_dim, **kw)
    if name == "resnet20":
        return resnet20(num_classes=output_dim, **kw)
    if name == "mobilenet":
        return MobileNetV1(num_classes=output_dim, **kw)
    if name == "mobilenet_v3":
        return MobileNetV3(num_classes=output_dim, **kw)
    if name.startswith("efficientnet"):     # efficientnet-b0 .. -b7
        variant = name.rsplit("-", 1)[-1] if "-" in name else "b0"
        return EfficientNet(num_classes=output_dim, variant=variant, **kw)
    if name == "darts":
        from fedml_tpu.models.darts import DARTS_V2, DartsNetwork
        return DartsNetwork(num_classes=output_dim,
                            genotype=kw.pop("genotype", DARTS_V2), **kw)
    if name in ("vgg11",):
        return VGG11(num_classes=output_dim, **kw)
    if name in ("vgg16",):
        return VGG16(num_classes=output_dim, **kw)
    if name == "segnet":
        from fedml_tpu.models.segnet import SegEncoderDecoder
        return SegEncoderDecoder(num_classes=output_dim, **kw)
    raise ValueError(f"unknown model {model_name!r}")
