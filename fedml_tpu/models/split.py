"""Split-network pairs for SplitNN (reference fedml_api/distributed/split_nn
uses an arbitrary user-provided cut; fedml_experiments feeds it CIFAR CNNs).

`split_mlp` / `split_cnn` return (client_net, server_net): the client half
maps x → activations at the cut, the server half activations → logits
(client.py:24-31 / server.py:40-55).
"""
from __future__ import annotations

import flax.linen as nn


class MLPLower(nn.Module):
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.relu(nn.Dense(self.hidden)(x))


class MLPUpper(nn.Module):
    num_classes: int = 10
    hidden: int = 64

    @nn.compact
    def __call__(self, acts):
        x = nn.relu(nn.Dense(self.hidden)(acts))
        return nn.Dense(self.num_classes)(x)


class CNNLower(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(32, (3, 3), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="SAME")(x))
        return nn.max_pool(x, (2, 2), strides=(2, 2))


class CNNUpper(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, acts):
        x = acts.reshape((acts.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)


def split_mlp(num_classes: int = 10, hidden: int = 128):
    return MLPLower(hidden=hidden), MLPUpper(num_classes=num_classes)


def split_cnn(num_classes: int = 10):
    return CNNLower(), CNNUpper(num_classes=num_classes)
