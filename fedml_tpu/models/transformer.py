"""Decoder-only transformer LM — a BEYOND-reference model family.

The reference's NLP zoo stops at LSTMs (fedml_api/model/nlp/rnn.py); this
adds a small causal transformer for the same next-token tasks
(shakespeare / stackoverflow_nwp), because on TPU the attention matmuls
map onto the MXU far better than a sequential LSTM scan: every position
is one batched matmul instead of a length-T dependency chain.

Interface matches the RNN zoo: tokens [B, T] int -> per-position logits
[B, T, vocab]; the trainer's has_time_axis loss masks padding the same
way.  Sized for federated cross-device work (2 layers, d=128 by
default), not LLM scale — sequence lengths here are 20-80 tokens, so no
long-context machinery is warranted (SURVEY.md §5: the reference has
none to mirror).
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class _Block(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int

    @nn.compact
    def __call__(self, h, mask):
        a = nn.LayerNorm()(h)
        a = nn.MultiHeadDotProductAttention(
            num_heads=self.n_heads, qkv_features=self.d_model,
            deterministic=True)(a, a, mask=mask)
        h = h + a
        f = nn.LayerNorm()(h)
        f = nn.Dense(self.d_ff)(f)
        f = nn.gelu(f)
        f = nn.Dense(self.d_model)(f)
        return h + f


class TransformerLM(nn.Module):
    """Pre-LN causal decoder: embed + learned positions -> N blocks ->
    LN -> vocab projection."""
    vocab_size: int = 10004
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 512
    # LEAF-shakespeare mode: one next-token logit from the final position
    # (same contract as RNNOriginalFedAvg(last_only=True))
    last_only: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(jnp.int32)
        T = x.shape[-1]
        if T > self.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len={self.max_len}; "
                f"construct TransformerLM with a larger max_len")
        h = nn.Embed(self.vocab_size, self.d_model)(x)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (self.max_len, self.d_model))
        h = h + pos[:T].astype(h.dtype)
        causal = np.tril(np.ones((T, T), bool))[None, None]
        for _ in range(self.n_layers):
            h = _Block(self.d_model, self.n_heads, self.d_ff)(
                h, jnp.asarray(causal))
        h = nn.LayerNorm()(h)
        if self.last_only:
            h = h[:, -1]
        return nn.Dense(self.vocab_size)(h)
