"""CIFAR ResNets (reference fedml_api/model/cv/resnet.py — resnet20..56).

3 stages x n BasicBlocks at 16/32/64 channels with BatchNorm, the classic
CIFAR family (resnet56 = n=9 used by the cross-silo benchmarks,
benchmark/README.md:105-107).  BatchNorm running statistics live in the
`batch_stats` collection; FedAvg averages them along with params, exactly as
the reference averages every state_dict key (FedAVGAggregator.py:74-81).
"""
from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5)
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNetCIFAR(nn.Module):
    n_per_stage: int = 9
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5)(x)
        x = nn.relu(x)
        for i, filters in enumerate((16, 32, 64)):
            for j in range(self.n_per_stage):
                strides = 2 if i > 0 and j == 0 else 1
                x = BasicBlock(filters, strides)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def resnet20(num_classes: int = 10, **kw):
    return ResNetCIFAR(n_per_stage=3, num_classes=num_classes, **kw)


def resnet32(num_classes: int = 10, **kw):
    return ResNetCIFAR(n_per_stage=5, num_classes=num_classes, **kw)


def resnet44(num_classes: int = 10, **kw):
    return ResNetCIFAR(n_per_stage=7, num_classes=num_classes, **kw)


def resnet56(num_classes: int = 10, **kw):
    return ResNetCIFAR(n_per_stage=9, num_classes=num_classes, **kw)
