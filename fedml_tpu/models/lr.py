"""Logistic regression (reference fedml_api/model/linear/lr.py:1-11).

The reference applies sigmoid(linear) and trains with CrossEntropyLoss; the
TPU-native version emits raw logits and lets the loss own the nonlinearity
(numerically better, and XLA fuses it into the matmul's epilogue on the MXU).
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    num_classes: int
    flatten: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.flatten:
            x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
