"""MNIST GAN (reference fedml_api/model/cv/mnist_gan.py:1-65: a dense
generator z→784 with tanh and a dense discriminator 784→1) for FedGAN.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    latent_dim: int = 64
    out_dim: int = 784

    @nn.compact
    def __call__(self, z):
        x = nn.relu(nn.Dense(128)(z))
        x = nn.relu(nn.Dense(256)(x))
        return jnp.tanh(nn.Dense(self.out_dim)(x))


class Discriminator(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.leaky_relu(nn.Dense(256)(x), 0.2)
        x = nn.leaky_relu(nn.Dense(128)(x), 0.2)
        return nn.Dense(1)(x)[:, 0]
