"""MobileNetV1 (reference fedml_api/model/cv/mobilenet.py, 209 LoC torch).

Depthwise-separable conv stacks; CIFAR-sized stem (3x3 s1) rather than the
ImageNet 224 stem, matching the reference's cross-silo CIFAR usage
(benchmark/README.md:108-110).  Depthwise = Conv with
feature_group_count=channels, which XLA lowers to efficient TPU convolutions.
"""
from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class DepthwiseSeparable(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5)
        c_in = x.shape[-1]
        x = nn.Conv(c_in, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", feature_group_count=c_in, use_bias=False)(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        x = norm()(x)
        return nn.relu(x)


class MobileNetV1(nn.Module):
    num_classes: int = 10
    alpha: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(f):
            return max(8, int(f * self.alpha))
        x = nn.Conv(c(32), (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5)(x)
        x = nn.relu(x)
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
               (1024, 1)]
        for filters, strides in cfg:
            x = DepthwiseSeparable(c(filters), strides)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
