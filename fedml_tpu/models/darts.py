"""DARTS search space for FedNAS — TPU-native redesign.

Reference behavior: fedml_api/model/cv/darts/{operations.py:4-20,
genotypes.py:1-14, model_search.py:172-296, model.py} — a cell-based
search space with 8 primitives, continuous architecture weights (alphas)
relaxed by softmax over ops per edge, and a discrete-genotype derivation
that keeps the 2 strongest incoming edges per node.

TPU-first deviations (deliberate, documented):
  * GroupNorm replaces BatchNorm.  The search-phase bilevel gradients
    (architect) must differentiate through the network twice; BatchNorm's
    mutable running stats would thread a `batch_stats` collection through
    every `jax.grad` and break functional purity under `vmap` over clients.
    GroupNorm is stateless, per-sample, and the standard TPU substitution
    (the reference itself ships ResNet18-GN for the same reason,
    cv/resnet_gn.py).
  * Architecture weights (alphas) are NOT flax params: `__call__` takes
    them as explicit inputs.  This makes the weight/arch bilevel split a
    plain function-argument split — `jax.grad(..., argnums=...)` — instead
    of pytree surgery on a mixed parameter dict.
  * All 8 primitive branches of a MixedOp are computed and combined with a
    weighted sum (one stacked elementwise op) — on TPU the branches are
    XLA-fused and the MXU-heavy separable convs dominate; no Python-level
    op dispatch survives tracing.
  * NHWC layout throughout (TPU conv layout), vs the reference's NCHW.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

Genotype = namedtuple("Genotype", "normal normal_concat reduce reduce_concat")

# Same 8-primitive vocabulary as the reference (genotypes.py:5-14).
PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)

# The published DARTS-V2 CIFAR genotype (public constant; genotypes.py).
DARTS_V2 = Genotype(
    normal=[("sep_conv_3x3", 0), ("sep_conv_3x3", 1), ("sep_conv_3x3", 0),
            ("sep_conv_3x3", 1), ("sep_conv_3x3", 1), ("skip_connect", 0),
            ("skip_connect", 0), ("dil_conv_3x3", 2)],
    normal_concat=[2, 3, 4, 5],
    reduce=[("max_pool_3x3", 0), ("max_pool_3x3", 1), ("skip_connect", 2),
            ("max_pool_3x3", 1), ("max_pool_3x3", 0), ("skip_connect", 2),
            ("skip_connect", 2), ("max_pool_3x3", 1)],
    reduce_concat=[2, 3, 4, 5],
)


def _gn(C: int) -> nn.Module:
    for g in (8, 4, 2, 1):
        if C % g == 0:
            return nn.GroupNorm(num_groups=g)
    return nn.GroupNorm(num_groups=1)


class ReLUConvGN(nn.Module):
    """relu → conv → norm (reference ReLUConvBN, operations.py:23-35)."""
    C_out: int
    kernel: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = nn.Conv(self.C_out, (self.kernel, self.kernel),
                    strides=self.stride, padding="SAME", use_bias=False)(x)
        return _gn(self.C_out)(x)


class SepConv(nn.Module):
    """Depthwise-separable conv applied twice (operations.py:53-70)."""
    C_out: int
    kernel: int
    stride: int

    @nn.compact
    def __call__(self, x):
        C_in = x.shape[-1]
        for i, s in enumerate((self.stride, 1)):
            x = nn.relu(x)
            x = nn.Conv(C_in, (self.kernel, self.kernel), strides=s,
                        padding="SAME", feature_group_count=C_in,
                        use_bias=False)(x)
            C_next = C_in if i == 0 else self.C_out
            x = nn.Conv(C_next, (1, 1), use_bias=False)(x)
            x = _gn(C_next)(x)
        return x


class DilConv(nn.Module):
    """Dilated depthwise-separable conv (operations.py:38-50)."""
    C_out: int
    kernel: int
    stride: int
    dilation: int = 2

    @nn.compact
    def __call__(self, x):
        C_in = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(C_in, (self.kernel, self.kernel), strides=self.stride,
                    padding="SAME", kernel_dilation=self.dilation,
                    feature_group_count=C_in, use_bias=False)(x)
        x = nn.Conv(self.C_out, (1, 1), use_bias=False)(x)
        return _gn(self.C_out)(x)


class FactorizedReduce(nn.Module):
    """Stride-2 reduction via two offset 1x1 convs (operations.py:81-97)."""
    C_out: int

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        a = nn.Conv(self.C_out // 2, (1, 1), strides=2, use_bias=False)(x)
        b = nn.Conv(self.C_out - self.C_out // 2, (1, 1), strides=2,
                    use_bias=False)(x[:, 1:, 1:, :])
        # offset path loses a row/col at odd sizes; pad back to match
        if b.shape[1] != a.shape[1] or b.shape[2] != a.shape[2]:
            b = jnp.pad(b, ((0, 0), (0, a.shape[1] - b.shape[1]),
                            (0, a.shape[2] - b.shape[2]), (0, 0)))
        return _gn(self.C_out)(jnp.concatenate([a, b], axis=-1))


def _pool(x, kind: str, stride: int):
    w = (1, 3, 3, 1)
    s = (1, stride, stride, 1)
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, w, s, "SAME")
    ones = jnp.ones_like(x)
    num = jax.lax.reduce_window(x, 0.0, jax.lax.add, w, s, "SAME")
    den = jax.lax.reduce_window(ones, 0.0, jax.lax.add, w, s, "SAME")
    return num / den   # count_include_pad=False semantics


class MixedOp(nn.Module):
    """All |PRIMITIVES| branches, combined by softmaxed alphas
    (model_search.py:10-24)."""
    C: int
    stride: int

    @nn.compact
    def __call__(self, x, w):
        s = self.stride
        outs = [
            jnp.zeros_like(x[:, ::s, ::s, :]),                   # none
            _pool(x, "max", s),                                  # max_pool_3x3
            _pool(x, "avg", s),                                  # avg_pool_3x3
            (x if s == 1 else FactorizedReduce(self.C)(x)),      # skip
            SepConv(self.C, 3, s)(x),
            SepConv(self.C, 5, s)(x),
            DilConv(self.C, 3, s)(x),
            DilConv(self.C, 5, s)(x),
        ]
        stacked = jnp.stack(outs, axis=0)                        # [O, N,H,W,C]
        return jnp.tensordot(w, stacked, axes=[[0], [0]])


class SearchCell(nn.Module):
    """One DARTS cell: `steps` intermediate nodes, every node summing a
    MixedOp over all previous states (model_search.py:26-60)."""
    steps: int
    multiplier: int
    C: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1, weights):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C)(s0)
        else:
            s0 = ReLUConvGN(self.C)(s0)
        s1 = ReLUConvGN(self.C)(s1)
        states = [s0, s1]
        offset = 0
        for _ in range(self.steps):
            acc = 0.0
            for j, h in enumerate(states):
                stride = 2 if self.reduction and j < 2 else 1
                acc = acc + MixedOp(self.C, stride)(h, weights[offset + j])
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


def st_gumbel_softmax(logits, rng, tau: float = 1.0):
    """Straight-through Gumbel-softmax over the op axis: hard one-hot on
    the forward pass, soft gradients — the GDAS single-path sampler
    (model_search_gdas.py; arXiv:1910.04465)."""
    g = -jnp.log(-jnp.log(
        jax.random.uniform(rng, logits.shape, minval=1e-20, maxval=1.0)
        ) + 1e-20)
    soft = jax.nn.softmax((logits + g) / tau, axis=-1)
    hard = jax.nn.one_hot(jnp.argmax(soft, axis=-1), logits.shape[-1],
                          dtype=soft.dtype)
    return jax.lax.stop_gradient(hard - soft) + soft


class DartsSearchNetwork(nn.Module):
    """Search-phase supernet (model_search.py:172-231).  Reduction cells at
    layers//3 and 2*layers//3.  `__call__(x, alphas)` with
    alphas = {"normal": [k, O], "reduce": [k, O]} raw logits — or, with
    softmax_weights=False, already-mixed edge weights (the GDAS path
    passes straight-through gumbel samples)."""
    num_classes: int
    C: int = 16
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    stem_multiplier: int = 3
    softmax_weights: bool = True

    @nn.compact
    def __call__(self, x, alphas, train: bool = True):
        del train
        if self.softmax_weights:
            w_normal = jax.nn.softmax(alphas["normal"], axis=-1)
            w_reduce = jax.nn.softmax(alphas["reduce"], axis=-1)
        else:
            w_normal, w_reduce = alphas["normal"], alphas["reduce"]
        C_curr = self.stem_multiplier * self.C
        s = nn.Conv(C_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s0 = s1 = _gn(C_curr)(s)
        C_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                C_curr *= 2
            cell = SearchCell(self.steps, self.multiplier, C_curr,
                              reduction, reduction_prev)
            s0, s1 = s1, cell(s0, s1, w_reduce if reduction else w_normal)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


def num_edges(steps: int = 4) -> int:
    return sum(2 + i for i in range(steps))


def init_alphas(rng: jax.Array, steps: int = 4) -> dict[str, jax.Array]:
    """1e-3 * randn init, as the reference (model_search.py:232-241)."""
    k = num_edges(steps)
    rn, rr = jax.random.split(rng)
    return {"normal": 1e-3 * jax.random.normal(rn, (k, len(PRIMITIVES))),
            "reduce": 1e-3 * jax.random.normal(rr, (k, len(PRIMITIVES)))}


def derive_genotype(alphas: dict[str, Any], steps: int = 4,
                    multiplier: int = 4) -> Genotype:
    """Discretize: per node keep the 2 incoming edges with the strongest
    best-non-'none' op, then that op per edge (model_search.py:258-296)."""
    none_idx = PRIMITIVES.index("none")

    def _parse(w):
        w = np.asarray(jax.nn.softmax(jnp.asarray(w), axis=-1))
        gene, start, n = [], 0, 2
        for _ in range(steps):
            W = w[start:start + n]
            edges = sorted(
                range(n),
                key=lambda j: -max(W[j][k] for k in range(len(PRIMITIVES))
                                   if k != none_idx))[:2]
            for j in sorted(edges):
                k_best = max((k for k in range(len(PRIMITIVES))
                              if k != none_idx), key=lambda k: W[j][k])
                gene.append((PRIMITIVES[k_best], j))
            start += n
            n += 1
        return gene
    concat = list(range(2 + steps - multiplier, steps + 2))
    return Genotype(normal=_parse(alphas["normal"]), normal_concat=concat,
                    reduce=_parse(alphas["reduce"]), reduce_concat=concat)


# ---------------------------------------------------------------------------
# Fixed (derived) network for the FedNAS train phase (cv/darts/model.py)
# ---------------------------------------------------------------------------

_FIXED_OPS = {
    "max_pool_3x3": lambda C, s: (lambda x: _pool(x, "max", s)),
    "avg_pool_3x3": lambda C, s: (lambda x: _pool(x, "avg", s)),
}


class _FixedOp(nn.Module):
    op: str        # `name` is reserved by flax Module
    C: int
    stride: int

    @nn.compact
    def __call__(self, x):
        n, C, s = self.op, self.C, self.stride
        if n == "skip_connect":
            return x if s == 1 else FactorizedReduce(C)(x)
        if n in _FIXED_OPS:
            return _FIXED_OPS[n](C, s)(x)
        if n == "sep_conv_3x3":
            return SepConv(C, 3, s)(x)
        if n == "sep_conv_5x5":
            return SepConv(C, 5, s)(x)
        if n == "dil_conv_3x3":
            return DilConv(C, 3, s)(x)
        if n == "dil_conv_5x5":
            return DilConv(C, 5, s)(x)
        raise ValueError(f"op {n!r} not valid in a derived genotype")


class FixedCell(nn.Module):
    genotype: Any
    C: int
    reduction: bool
    reduction_prev: bool

    @nn.compact
    def __call__(self, s0, s1):
        g = self.genotype
        if self.reduction_prev:
            s0 = FactorizedReduce(self.C)(s0)
        else:
            s0 = ReLUConvGN(self.C)(s0)
        s1 = ReLUConvGN(self.C)(s1)
        ops = g.reduce if self.reduction else g.normal
        concat = g.reduce_concat if self.reduction else g.normal_concat
        states = [s0, s1]
        # ops come in pairs: 2 incoming edges per intermediate node
        for i in range(len(ops) // 2):
            acc = 0.0
            for name, j in ops[2 * i:2 * i + 2]:
                stride = 2 if self.reduction and j < 2 else 1
                acc = acc + _FixedOp(name, self.C, stride)(states[j])
            states.append(acc)
        return jnp.concatenate([states[i] for i in concat], axis=-1)


class DartsNetwork(nn.Module):
    """Train-phase network built from a derived genotype
    (cv/darts/model.py NetworkCIFAR; drop-path omitted — GroupNorm +
    weight decay regularize instead, a documented deviation)."""
    num_classes: int
    genotype: Any
    C: int = 36
    layers: int = 20
    stem_multiplier: int = 3

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        C_curr = self.stem_multiplier * self.C
        s = nn.Conv(C_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s0 = s1 = _gn(C_curr)(s)
        C_curr = self.C
        reduction_prev = False
        for i in range(self.layers):
            reduction = i in (self.layers // 3, 2 * self.layers // 3)
            if reduction:
                C_curr *= 2
            cell = FixedCell(self.genotype, C_curr, reduction, reduction_prev)
            s0, s1 = s1, cell(s0, s1)
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)
