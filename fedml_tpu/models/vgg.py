"""VGG (reference fedml_api/model/cv/vgg.py, 158 LoC torch)."""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

_CFGS = {
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"],
}


class VGG(nn.Module):
    cfg_name: str = "vgg11"
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in _CFGS[self.cfg_name]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME")(x)
                x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(512)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def VGG11(num_classes: int = 10, **kw):
    return VGG(cfg_name="vgg11", num_classes=num_classes, **kw)


def VGG16(num_classes: int = 10, **kw):
    return VGG(cfg_name="vgg16", num_classes=num_classes, **kw)
