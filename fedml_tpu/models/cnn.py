"""FedAvg-paper CNNs (reference fedml_api/model/cv/cnn.py).

CNNOriginalFedAvg: 2x(conv5x5 + maxpool) + fc512 + softmax head — the 1.66M
parameter model of McMahan et al. used for FEMNIST (cnn.py:4-70).
CNNDropOut: the dropout variant (cnn.py:73-142).

Inputs are NHWC (TPU-native layout; the reference is NCHW torch).
"""
from __future__ import annotations

import flax.linen as nn


class CNNOriginalFedAvg(nn.Module):
    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512)(x)
        x = nn.relu(x)
        return nn.Dense(10 if self.only_digits else self.num_classes)(x)


class CNNDropOut(nn.Module):
    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (3, 3), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else self.num_classes)(x)
