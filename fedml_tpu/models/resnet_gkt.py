"""GKT split ResNet pair (reference fedml_api/model/cv/resnet56_gkt/
{resnet_client,resnet_server}.py: an 8-layer client net producing 16-channel
feature maps + local logits, and a 55-layer server net consuming them).

GroupNorm replaces BatchNorm here: the GKT server trains on *uploaded*
feature batches whose statistics are not the client's data distribution, so
running-stat BN is both a correctness hazard and a mutable-collection
complication under jit; GN is the reference's own choice for its federated
ResNet-18 (resnet_gn.py) and is batch-independent.
"""
from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class GNBasicBlock(nn.Module):
    filters: int
    strides: int = 1
    groups: int = 2

    @nn.compact
    def __call__(self, x):
        norm = partial(nn.GroupNorm, num_groups=self.groups)
        residual = x
        y = nn.Conv(self.filters, (3, 3),
                    strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNetClientGKT(nn.Module):
    """resnet_client.py: conv stem + n_blocks at 16ch; returns
    (feature_maps [H,W,16], logits) — the client uploads both."""
    num_classes: int = 10
    n_blocks: int = 3

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.GroupNorm(num_groups=2)(x)
        x = nn.relu(x)
        for _ in range(self.n_blocks):
            x = GNBasicBlock(16)(x)
        feats = x
        pooled = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(pooled)
        return feats, logits


class ResNetServerGKT(nn.Module):
    """resnet_server.py: the deep tail (stages at 16/32/64) consuming the
    client's 16-channel feature maps."""
    num_classes: int = 10
    n_per_stage: int = 6

    @nn.compact
    def __call__(self, feats):
        x = feats
        for i, filters in enumerate((16, 32, 64)):
            for j in range(self.n_per_stage):
                strides = 2 if i > 0 and j == 0 else 1
                x = GNBasicBlock(filters, strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
