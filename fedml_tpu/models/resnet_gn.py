"""ResNet-18 with GroupNorm (reference fedml_api/model/cv/resnet_gn.py +
group_normalization.py), the fed_CIFAR100 model of 'Adaptive Federated
Optimization'.

GroupNorm (not BatchNorm) is the federated-friendly choice: no running stats
to average, and every client step is batch-size independent — which also
means the whole variables pytree is pure params, the cheapest case for
vmap/shard_map over the client axis.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class FusionBarrierGroupNorm(nn.GroupNorm):
    """GroupNorm that sees its input through `lax.optimization_barrier`:
    semantically the identity, but it stops XLA from output-fusing the
    producing convolution with the GN statistics reduces — the dominant
    cost category of the north-star bench trace (PERF.md round-2b).
    Opt-in via ResNet18GN(norm_fusion_barrier=True) until the chip
    measurement (tools/profile_bench.py exp G4) shows which way it cuts."""

    @nn.compact
    def __call__(self, x):
        return super().__call__(jax.lax.optimization_barrier(x))


class BasicBlockGN(nn.Module):
    filters: int
    strides: int = 1
    groups: int = 2
    norm_fusion_barrier: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        gn = (FusionBarrierGroupNorm if self.norm_fusion_barrier
              else nn.GroupNorm)
        norm = partial(gn, num_groups=self.groups)
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet18GN(nn.Module):
    num_classes: int = 100
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    num_filters: int = 64
    groups: int = 2
    norm_fusion_barrier: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        gn = (FusionBarrierGroupNorm if self.norm_fusion_barrier
              else nn.GroupNorm)
        x = nn.Conv(self.num_filters, (3, 3), padding="SAME", use_bias=False)(x)
        x = gn(num_groups=self.groups)(x)
        x = nn.relu(x)
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = BasicBlockGN(self.num_filters * (2 ** i), strides,
                                 self.groups,
                                 self.norm_fusion_barrier)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
