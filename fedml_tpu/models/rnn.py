"""Character/word LSTMs (reference fedml_api/model/nlp/rnn.py).

RNNOriginalFedAvg (rnn.py:4-36): embed(vocab 90 -> 8) + 2xLSTM(256) + dense,
used for shakespeare / fed_shakespeare next-char prediction.
RNNStackOverflow (rnn.py:39-70): embed(10004 -> 96) + LSTM(670) + dense(96)
+ dense(vocab), used for stackoverflow next-word prediction.

Both return per-position logits [B, T, vocab]; the loss masks padding.
`lax.scan`-based nn.RNN keeps the step function static for XLA.
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _lstm(hidden_size: int, h):
    """nn.RNN over an OptimizedLSTMCell with a carry whose shard_map
    variance matches the inputs.

    nn.RNN's default carry is fresh zeros — replicated-typed under
    shard_map, while the scan body's carry output varies with the
    (client-sharded) inputs: a lax.scan carry-type mismatch.  Adding
    `0 * sum(0 * h)` promotes the zeros to h's variance without changing
    a bit (same invariant as core/pytree.tree_vary_noop)."""
    cell = nn.OptimizedLSTMCell(hidden_size)
    carry = cell.initialize_carry(jax.random.PRNGKey(0),
                                  h.shape[:-2] + h.shape[-1:])
    bump = jnp.sum(h * 0)                       # 0.0, but input-varying
    carry = jax.tree.map(lambda a: a + bump.astype(a.dtype), carry)
    return nn.RNN(cell)(h, initial_carry=carry)


class RNNOriginalFedAvg(nn.Module):
    """`last_only=True` is the LEAF-shakespeare mode: one next-char logit
    from the final hidden state (reference rnn.py:30-33); False is the
    fed_shakespeare per-position mode (rnn.py:34-36)."""
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256
    last_only: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        h = _lstm(self.hidden_size, h)
        h = _lstm(self.hidden_size, h)
        if self.last_only:
            h = h[:, -1]
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    vocab_size: int = 10004        # 10000 words + pad/bos/eos/oov
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x.astype(jnp.int32))
        h = _lstm(self.hidden_size, h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)
