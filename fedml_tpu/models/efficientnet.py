"""EfficientNet B0–B7 (reference fedml_api/model/cv/efficientnet.py, 404 LoC
+ efficientnet_utils.py, 584 LoC torch).

MBConv (inverted residual + SE + swish) trunk with the published
width/depth/resolution compound-scaling coefficients.  TPU-first choices:
NHWC layout, `nn.swish` (the native silu XLA fuses), stochastic depth as a
per-example bernoulli on the residual branch (the reference's
drop-connect, efficientnet_utils.py `drop_connect`).
CIFAR-sized stride-1 stem by default; `imagenet_stem=True` for 224 inputs.
"""
from __future__ import annotations

import math
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

# (width_mult, depth_mult, resolution, dropout) — published B0-B7 scaling
PARAMS = {
    "b0": (1.0, 1.0, 224, 0.2), "b1": (1.0, 1.1, 240, 0.2),
    "b2": (1.1, 1.2, 260, 0.3), "b3": (1.2, 1.4, 300, 0.3),
    "b4": (1.4, 1.8, 380, 0.4), "b5": (1.6, 2.2, 456, 0.4),
    "b6": (1.8, 2.6, 528, 0.5), "b7": (2.0, 3.1, 600, 0.5),
}

# (expand, channels, repeats, stride, kernel) — the B0 base architecture
_BASE = [
    (1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3), (6, 112, 3, 1, 5), (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
]


def _round_filters(f: int, wm: float, divisor: int = 8) -> int:
    f = f * wm
    new_f = max(divisor, int(f + divisor / 2) // divisor * divisor)
    if new_f < 0.9 * f:
        new_f += divisor
    return int(new_f)


def _round_repeats(r: int, dm: float) -> int:
    return int(math.ceil(dm * r))


class MBConv(nn.Module):
    expand: int
    out_ch: int
    stride: int
    kernel: int
    drop_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3)
        inp = x.shape[-1]
        mid = inp * self.expand
        h = x
        if self.expand != 1:
            h = nn.swish(norm()(nn.Conv(mid, (1, 1), use_bias=False)(h)))
        h = nn.Conv(mid, (self.kernel, self.kernel), strides=self.stride,
                    padding="SAME", feature_group_count=mid,
                    use_bias=False)(h)
        h = nn.swish(norm()(h))
        # squeeze-excite at 0.25 of the INPUT channels (reference semantics)
        s = jnp.mean(h, axis=(1, 2))
        s = nn.swish(nn.Dense(max(1, inp // 4))(s))
        s = nn.sigmoid(nn.Dense(mid)(s))
        h = h * s[:, None, None, :]
        h = norm()(nn.Conv(self.out_ch, (1, 1), use_bias=False)(h))
        if self.stride == 1 and inp == self.out_ch:
            if train and self.drop_rate > 0.0:    # drop-connect
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(rng, keep, (h.shape[0], 1, 1, 1))
                h = h * mask.astype(h.dtype) / keep
            h = h + x
        return h


class EfficientNet(nn.Module):
    num_classes: int = 10
    variant: str = "b0"
    drop_connect_rate: float = 0.2
    imagenet_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        wm, dm, _res, dropout = PARAMS[self.variant]
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3)
        stem_stride = 2 if self.imagenet_stem else 1
        x = nn.Conv(_round_filters(32, wm), (3, 3), strides=stem_stride,
                    padding="SAME", use_bias=False)(x)
        x = nn.swish(norm()(x))
        blocks = [(e, _round_filters(c, wm), _round_repeats(r, dm), s, k)
                  for e, c, r, s, k in _BASE]
        total = sum(r for _, _, r, _, _ in blocks)
        idx = 0
        for expand, ch, repeats, stride, kernel in blocks:
            for i in range(repeats):
                dr = self.drop_connect_rate * idx / total
                x = MBConv(expand, ch, stride if i == 0 else 1, kernel,
                           drop_rate=dr)(x, train)
                idx += 1
        x = nn.swish(norm()(nn.Conv(_round_filters(1280, wm), (1, 1),
                                    use_bias=False)(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
