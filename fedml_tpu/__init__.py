"""fedml_tpu — a TPU-native federated learning framework.

A ground-up JAX/XLA re-design of the capabilities of FedML (the PyTorch+MPI
reference surveyed in SURVEY.md).  Instead of one OS process per logical
client exchanging pickled state dicts over MPI, clients map to array/mesh-axis
indices: local SGD is a jit-compiled `lax.scan`, cohorts of clients run under
`vmap`/`shard_map` over HBM-sharded partitions, and FedAvg's sample-weighted
aggregation is a weighted tree-mean (a `psum` when sharded over a pod mesh).

Layer map (mirrors SURVEY.md §1, rebuilt TPU-first):

  L5  cli/          entry points (``python -m fedml_tpu.cli.run_fedavg``)
  L4  algorithms/   FedAvg, FedOpt, FedProx, FedNova, robust, hierarchical,
                    decentralized gossip, SplitNN, VFL, FedGKT, FedNAS,
                    TurboAggregate
  L3  models/ data/ flax model zoo + federated dataset loaders (8-tuple
                    contract of the reference)
  L2  core/         ClientTrainer protocol, partitioners, samplers,
                    topology managers, robust aggregation pytree ops
  L1  parallel/     mesh + shard_map federated engine (ICI collectives)
      comm/         host-side message layer (gRPC / in-proc / MQTT) for
                    genuinely remote cross-silo participants
"""

from fedml_tpu import compat as _compat  # noqa: F401  (patches lagging jax
#                                          APIs — jax.shard_map/lax.pcast —
#                                          before any engine module loads)

__version__ = "0.1.0"
