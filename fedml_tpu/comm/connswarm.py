"""Selector-based connection swarm — the client half of the live-
connection bench (ISSUE 11).

Driving 10k live uplinks cannot be done with 10k client threads any
more than serving them can: the swarm is the reactor's mirror image —
ONE event loop owning N non-blocking client sockets that connect
(optionally as a storm: every SYN at once, the push-notification
stampede), keep a paced uplink going (an aggregate offered rate spread
round-robin across the fleet, each frame riding the FMLR envelope so
the server's dedup ledger and ack path see production-shaped traffic),
read-and-discard the acks, and churn (seeded exponential lifetimes →
close + reconnect; a server-side eviction/shed also reconnects — the
flash-crowd arrival shape replayed as connection churn).

Runs in-process (a daemon thread, the test path) or as a subprocess
(`python -m fedml_tpu.comm.connswarm <config.json>`) so the 10k arm
splits its file descriptors across two processes — the container's
`ulimit -n` cannot hold both halves of 10k connections in one.
Everything is seeded: same seed, same connect/churn schedule.
"""
from __future__ import annotations

import dataclasses
import errno
import heapq
import json
import logging
import selectors
import socket
import struct
import sys
import threading
import time
import zlib
from collections import deque
from typing import Optional

import numpy as np

from fedml_tpu.comm import reliability

log = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
_INPROGRESS = (errno.EINPROGRESS, errno.EWOULDBLOCK, errno.EAGAIN)


@dataclasses.dataclass
class SwarmConfig:
    """Knobs of one swarm run.  The pre-encoded uplink frame is passed
    as bytes in-process, or via `frame_path` for the subprocess mode.

    `targets` (ISSUE 18) stripes ONE fleet across N host endpoints —
    a list of [host, port] pairs; sender i dials targets[(i-1) % N],
    and the stats grow a `per_target` block (connects/refused/frames
    per endpoint).  None keeps the single-endpoint (host, port)
    behavior byte-for-byte.  `arrival` (an ArrivalConfig asdict)
    replays the PR-10 diurnal/flash-crowd profile over real sockets:
    offered_rate becomes the fleet's PEAK and the instantaneous rate
    follows λ(t)/λ_peak of the configured process."""
    host: str = "127.0.0.1"
    port: int = 53600
    n_connections: int = 256
    offered_rate: float = 2000.0     # aggregate uplink frames/sec
    ramp_s: float = 1.0              # clean arm: connects spread over this
    storm: bool = False              # storm arm: every connect at t=0
    churn_lifetime_s: float = 0.0    # mean conn lifetime (0 = no churn)
    reconnect_delay_s: float = 0.05
    duration_s: float = 600.0        # subprocess self-termination bound
    seed: int = 0
    frame_path: Optional[str] = None
    tick_s: float = 0.01
    targets: Optional[list] = None   # [[host, port], ...] multi-endpoint
    arrival: Optional[dict] = None   # ArrivalConfig asdict rate profile
    # max banked send budget, in seconds of offered load (the
    # no-post-stall-burst cap).  1.0 = the historical behavior; the
    # cluster bench sets ~0.05 so a fleet that waited out the serving
    # hosts' startup paces at λ(t) instead of dumping a burst
    burst_cap_s: float = 1.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "SwarmConfig":
        return cls(**json.loads(text))


class _CConn:
    __slots__ = ("sock", "fd", "sender", "connected", "pending",
                 "die_at", "mask", "target")

    def __init__(self, sock: socket.socket, sender: int,
                 target: str = ""):
        self.sock = sock
        self.fd = sock.fileno()
        self.sender = sender
        self.connected = False
        self.pending: Optional[memoryview] = None
        self.die_at: Optional[float] = None
        self.mask = 0
        self.target = target


class ConnectionSwarm:
    """One event loop, N client connections, paced enveloped uplinks."""

    def __init__(self, cfg: SwarmConfig, frame: bytes):
        self.cfg = cfg
        self.frame = bytes(frame)
        self._crc = zlib.crc32(self.frame) & 0xFFFFFFFF
        self._rng = np.random.default_rng([cfg.seed, 7])
        self._sel = selectors.DefaultSelector()
        self._conns: dict[int, _CConn] = {}
        self._seq: dict[int, int] = {}       # persists across reconnects
        self._send_ring: deque = deque()     # round-robin uplink order
        self._events: list[tuple[float, int]] = []  # heap: (due, sender),
        #                                             absolute monotonic
        self.stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # one fleet, N endpoints (ISSUE 18): sender i always dials the
        # SAME target — striping is a pure function of the sender id,
        # so reconnects land where the seq ledger expects them
        self._targets = [(str(h), int(p))
                         for h, p in (cfg.targets
                                      or [(cfg.host, cfg.port)])]
        self.stats = {"connects": 0, "reconnects": 0, "refused": 0,
                      "frames_sent": 0, "conn_errors": 0,
                      "per_target": {
                          f"{h}:{p}": {"connects": 0, "refused": 0,
                                       "frames_sent": 0,
                                       "conn_errors": 0}
                          for h, p in self._targets}}
        self._arr = None
        if cfg.arrival:
            from fedml_tpu.scale.arrivals import (ArrivalConfig,
                                                  make_arrivals)
            self._arr = make_arrivals(ArrivalConfig(**cfg.arrival))

    def _target_of(self, sender: int) -> tuple:
        return self._targets[(sender - 1) % len(self._targets)]

    def _tstat(self, conn_or_key) -> dict:
        key = (conn_or_key if isinstance(conn_or_key, str)
               else conn_or_key.target)
        return self.stats["per_target"][key]

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ConnectionSwarm":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="conn-swarm")
        self._thread.start()
        return self

    def join(self, timeout: float = 10.0) -> None:
        self.stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # -- loop ----------------------------------------------------------------
    def run(self) -> None:
        cfg = self.cfg
        t0 = time.monotonic()
        for sender in range(1, cfg.n_connections + 1):
            due = 0.0 if cfg.storm else (
                cfg.ramp_s * sender / cfg.n_connections)
            heapq.heappush(self._events, (t0 + due, sender))
        budget = 0.0
        last = t0
        deadline = t0 + cfg.duration_s
        try:
            while not self.stop.is_set() and time.monotonic() < deadline:
                now = time.monotonic()
                while self._events and self._events[0][0] <= now:
                    _, sender = heapq.heappop(self._events)
                    self._connect(sender, now)
                for key, mask in self._sel.select(timeout=cfg.tick_s):
                    conn = key.data
                    if self._conns.get(conn.fd) is not conn:
                        continue
                    try:
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(conn)
                        # re-check liveness BETWEEN handlers: a failed
                        # handshake (READ|WRITE on a refused connect)
                        # closes + reschedules in the WRITE handler,
                        # and running READ on the corpse would
                        # reschedule the same sender a second time —
                        # doubling the fleet on every refusal
                        if (mask & selectors.EVENT_READ
                                and self._conns.get(conn.fd) is conn):
                            self._on_readable(conn)
                    except OSError:
                        if self._conns.get(conn.fd) is conn:
                            self._drop(conn, error=True)
                now = time.monotonic()
                # arrival-profile pacing (ISSUE 18): offered_rate is
                # the fleet's peak; the instantaneous rate follows the
                # configured diurnal/flash λ(t) shape — real sockets
                # replaying the PR-10 arrival processes
                rate = (cfg.offered_rate if self._arr is None
                        else cfg.offered_rate
                        * self._arr.rate_fraction(now - t0))
                budget = min(budget + rate * (now - last),
                             cfg.offered_rate * cfg.burst_cap_s)
                last = now
                tried = 0
                limit = len(self._send_ring)
                while budget >= 1.0 and tried < limit and self._send_ring:
                    conn = self._send_ring.popleft()
                    tried += 1
                    if self._conns.get(conn.fd) is not conn:
                        continue          # churned away: drop ring entry
                    if conn.connected and conn.pending is None:
                        if self._uplink(conn):
                            budget -= 1.0
                    if self._conns.get(conn.fd) is conn:
                        self._send_ring.append(conn)
                if cfg.churn_lifetime_s > 0.0:
                    self._churn(now)
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            try:
                self._sel.close()
            except OSError:
                pass

    # -- connect / churn -----------------------------------------------------
    def _schedule_reconnect(self, sender: int) -> None:
        if self.stop.is_set():
            return
        delay = self.cfg.reconnect_delay_s * (
            1.0 + float(self._rng.random()))
        heapq.heappush(self._events, (time.monotonic() + delay, sender))

    def _connect(self, sender: int, now: float) -> None:
        host, port = self._target_of(sender)
        tkey = f"{host}:{port}"
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        try:
            rc = s.connect_ex((host, port))
        except OSError:
            s.close()
            self.stats["conn_errors"] += 1
            self._tstat(tkey)["conn_errors"] += 1
            self._schedule_reconnect(sender)
            return
        if rc not in (0,) and rc not in _INPROGRESS:
            s.close()
            self.stats["refused"] += 1
            self._tstat(tkey)["refused"] += 1
            self._schedule_reconnect(sender)
            return
        conn = _CConn(s, sender, target=tkey)
        if self.cfg.churn_lifetime_s > 0.0:
            conn.die_at = now + float(self._rng.exponential(
                self.cfg.churn_lifetime_s))
        try:
            self._sel.register(s, selectors.EVENT_WRITE
                               | selectors.EVENT_READ, conn)
        except (ValueError, OSError):
            # FD pressure / transient selector failure: this sender
            # must NOT silently vanish from the swarm (a run under
            # reduced load would masquerade as n_connections of
            # pressure — the PR-6 dead-client lesson) — count + retry
            s.close()
            self.stats["conn_errors"] += 1
            self._schedule_reconnect(sender)
            return
        conn.mask = selectors.EVENT_WRITE | selectors.EVENT_READ
        self._conns[conn.fd] = conn
        self.stats["connects"] += 1
        self._tstat(conn)["connects"] += 1
        if self._seq.get(sender, 0) > 0:
            self.stats["reconnects"] += 1
        self._send_ring.append(conn)

    def _churn(self, now: float) -> None:
        for conn in list(self._conns.values()):
            if conn.die_at is not None and now >= conn.die_at:
                sender = conn.sender
                self._close(conn)
                self._schedule_reconnect(sender)

    # -- socket events -------------------------------------------------------
    def _on_writable(self, conn: _CConn) -> None:
        if not conn.connected:
            err = conn.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err != 0:
                # refused/reset mid-handshake: the shed gate at work —
                # retry after the reconnect delay (the storm's churn)
                self.stats["refused"] += 1
                self._tstat(conn)["refused"] += 1
                sender = conn.sender
                self._close(conn)
                self._schedule_reconnect(sender)
                return
            conn.connected = True
            try:
                conn.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
            except OSError:
                pass
        if conn.pending is not None:
            n = conn.sock.send(conn.pending)
            conn.pending = (conn.pending[n:] if n < len(conn.pending)
                            else None)
        self._interest(conn)

    def _on_readable(self, conn: _CConn) -> None:
        # acks/nacks: drain and discard — the swarm prices the server,
        # not the client's bookkeeping
        data = conn.sock.recv(1 << 16)
        if not data:
            # server closed us (eviction / shed / drain): reconnect —
            # exactly the churn pressure the storm arm measures
            self.stats["conn_errors"] += 1
            self._drop(conn)

    def _uplink(self, conn: _CConn) -> bool:
        seq = self._seq.get(conn.sender, 0)
        self._seq[conn.sender] = seq + 1
        head = reliability._HEADER.pack(
            reliability.MAGIC, reliability.KIND_DATA, conn.sender, seq,
            self._crc)
        wire = head + self.frame
        buf = _LEN.pack(len(wire)) + wire
        try:
            n = conn.sock.send(buf)
        except (BlockingIOError, InterruptedError):
            n = 0
        except OSError:
            self._drop(conn, error=True)
            return False
        if n < len(buf):
            conn.pending = memoryview(buf)[n:]
        self.stats["frames_sent"] += 1
        self._tstat(conn)["frames_sent"] += 1
        self._interest(conn)
        return True

    # -- bookkeeping ---------------------------------------------------------
    def _interest(self, conn: _CConn) -> None:
        mask = selectors.EVENT_READ
        if conn.pending is not None or not conn.connected:
            mask |= selectors.EVENT_WRITE
        if mask != conn.mask:
            try:
                self._sel.modify(conn.sock, mask, conn)
                conn.mask = mask
            except (KeyError, ValueError, OSError):
                pass

    def _drop(self, conn: _CConn, error: bool = False) -> None:
        if error:
            self.stats["conn_errors"] += 1
        sender = conn.sender
        self._close(conn)
        self._schedule_reconnect(sender)

    def _close(self, conn: _CConn) -> None:
        if self._conns.pop(conn.fd, None) is None:
            return
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass


def main(argv: Optional[list] = None) -> int:
    """Subprocess entry: `python -m fedml_tpu.comm.connswarm cfg.json`.
    Runs until SIGTERM (or duration_s), then prints one JSON stats
    line — the parent torture harness collects it."""
    import signal
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m fedml_tpu.comm.connswarm <config.json>",
              file=sys.stderr)
        return 2
    cfg = SwarmConfig.from_json(open(argv[0]).read())
    if not cfg.frame_path:
        print("subprocess swarm needs frame_path", file=sys.stderr)
        return 2
    frame = open(cfg.frame_path, "rb").read()
    swarm = ConnectionSwarm(cfg, frame)

    def _term(signum, frm):
        swarm.stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    swarm.run()
    print(json.dumps(swarm.stats), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
