"""Message-driven FedAvg — the cross-silo deployment path.

This is the reference's distributed 6-file pattern
(fedml_api/distributed/fedavg/: message_define.py, FedAvgServerManager.py,
FedAvgClientManager.py, FedAVGAggregator.py) collapsed into one module,
running over any comm backend (INPROC for simulation, GRPC/TCP across
machines).  Participants here are genuinely remote — in-mesh cohorts use
fedml_tpu/parallel/ instead (SURVEY.md §7 design stance).

FSM (msg types 1-4, message_define.py:5-10):

  server --S2C_INIT_CONFIG(model, client_idx)--> every client
  client: local_train (jitted) --C2S_SEND_MODEL(model, n)--> server
  server: all received? weighted average; round+1 or finish
          --S2C_SYNC_MODEL(model, client_idx)--> every client
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu import obs
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.sampling import ClientSampler
from fedml_tpu.secure.secagg import SecAggBelowThreshold

log = logging.getLogger(__name__)
Pytree = Any


class MyMessage:
    """Message-type constants (message_define.py:5-33)."""
    MSG_TYPE_S2C_INIT_CONFIG = 1
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = 2
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = 3
    MSG_TYPE_C2S_SEND_STATS_TO_SERVER = 4

    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_LOCAL_LOSS = "local_loss"
    MSG_ARG_KEY_ROUND = "round_idx"
    # ISSUE 20: masked-uplink marker — same contract as the async
    # protocol's key (a secure server rejects plain uploads by name,
    # a plain server rejects masked ones)
    MSG_ARG_KEY_SECAGG = "secagg"


def _to_numpy(tree: Pytree) -> Pytree:
    return jax.tree.map(lambda a: np.asarray(a), tree)


class FedAvgAggregator:
    """Server-side round state (FedAVGAggregator.py:24-108): receive slots,
    all-received barrier, sample-weighted average, deterministic per-round
    client sampling (np.random.seed(round_idx), :90-98).

    `secure` (ISSUE 20) swaps the plaintext slots for the secure data
    plane's SecureAggregator: uploads arrive as masked field rows and
    fold on arrival; aggregate() runs the unmask barrier (with dropout
    reconstruction for absent ranks under a straggler timeout) instead
    of the plaintext tree_weighted_mean.  Slot index i is rank i+1 —
    the same cohort ids the async path and the keyring use."""

    def __init__(self, init_variables: Pytree, worker_num: int,
                 client_num_in_total: int, client_num_per_round: int,
                 secure=None):
        self.variables = _to_numpy(init_variables)
        self.worker_num = worker_num
        self.sampler = ClientSampler(client_num_in_total, client_num_per_round)
        self.model_dict: dict[int, Pytree] = {}
        self.sample_num_dict: dict[int, float] = {}
        self.flag_client_model_uploaded = [False] * worker_num
        self._lock = threading.Lock()
        self.secure = secure
        self.secure_below_threshold = 0
        if secure is not None:
            for r in range(1, worker_num + 1):
                secure.escrow(r)        # shares escrowed before round 0

    def add_local_trained_result(self, index: int, variables: Pytree,
                                 sample_num: float) -> bool:
        with self._lock:
            if self.secure is not None:
                # masked row: fold into the field accumulator, never
                # store plaintext (there is none to store)
                self.secure.fold(index + 1,
                                 np.ascontiguousarray(variables, np.uint32))
            else:
                self.model_dict[index] = variables
                self.sample_num_dict[index] = sample_num
            self.flag_client_model_uploaded[index] = True
            return all(self.flag_client_model_uploaded)

    def aggregate(self, round_idx: int = 0) -> Pytree:
        """Aggregate over every slot that uploaded this round.  With the
        all-received barrier that is all of them; under a straggler
        timeout it is the received subset (sample-weighted, so absent
        clients simply drop out of the mean).

        Secure mode: the received subset IS the survivor set — the
        unmask barrier subtracts the absent ranks' reconstructed masks
        (round_idx is the mask PRG counter, so the caller must pass its
        true round).  Raises SecAggBelowThreshold by name when too few
        survived; the round state is kept so late uploads can still
        close the round."""
        with self._lock:
            got = [i for i in range(self.worker_num)
                   if self.flag_client_model_uploaded[i]]
            if self.secure is not None:
                acc, wsum, _inc = self.secure.commit(
                    int(round_idx), [i + 1 for i in got])
                mean = jnp.asarray(acc, jnp.float32) / jnp.float32(wsum)
                from fedml_tpu.async_.staleness import unflatten_rows
                self.variables = _to_numpy(jax.tree.map(
                    lambda a: a[0],
                    unflatten_rows(mean[None, :], self.variables)))
            else:
                stacked = jax.tree.map(
                    lambda *xs: np.stack(xs),
                    *[self.model_dict[i] for i in got])
                w = np.asarray([self.sample_num_dict[i] for i in got],
                               np.float32)
                self.variables = _to_numpy(
                    tree_weighted_mean(stacked, jnp.asarray(w)))
            self.flag_client_model_uploaded = [False] * self.worker_num
            self.model_dict.clear()
            self.sample_num_dict.clear()
            return self.variables

    def received_count(self) -> int:
        with self._lock:
            return sum(self.flag_client_model_uploaded)

    def client_sampling(self, round_idx: int) -> np.ndarray:
        return self.sampler.sample(round_idx)


class FedAvgServerManager(ServerManager):
    """FedAvgServerManager.py:14-95 over the new comm layer."""

    def __init__(self, aggregator: FedAvgAggregator, comm_round: int,
                 rank: int = 0, size: int = 1, backend: str = "INPROC",
                 on_round_done: Optional[Callable[[int, Pytree], None]] = None,
                 straggler_timeout: Optional[float] = None,
                 model_transport: Optional[str] = None,
                 wire_compress: bool = False, **kw):
        """straggler_timeout: seconds to wait for the full cohort after a
        round's first upload; then aggregate the received subset and move
        on.  None = the reference's hang-forever barrier
        (check_whether_all_receive, FedAVGAggregator.py:50-57).

        model_transport: opt-in lossy wire dtype ("bf16"/"int8", wire
        codec v2) for the DOWNLINK model_params payload only — the
        client→server uploads feed the weighted average and stay exact
        regardless; the synced model is a broadcast the next local round
        re-trains anyway.  None (default) keeps every payload exact.
        wire_compress: zlib the frame head (codec v2)."""
        super().__init__(rank, size, backend, **kw)
        self.aggregator = aggregator
        self.model_transport = model_transport
        self.wire_compress = wire_compress
        self.round_num = comm_round
        self.round_idx = 0
        self.on_round_done = on_round_done
        self.straggler_timeout = straggler_timeout
        self._round_lock = threading.Lock()
        self._watchdog: Optional[threading.Timer] = None
        self.partial_rounds = 0           # observability: timed-out rounds
        # ranks whose uplinks are config-skew quarantined (ISSUE 20):
        # skew is a config property, not a transient, so a quarantined
        # rank is treated as dead for the all-received barrier — without
        # this, one misconfigured client deadlocks the federation
        self._quarantined: set[int] = set()
        self.done = threading.Event()

    def send_init_msg(self) -> None:
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for rank in range(1, self.size):
            self._send_model(rank, MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
                             int(client_indexes[rank - 1]))

    def _send_model(self, receiver: int, msg_type: int, client_idx: int):
        msg = Message(msg_type, self.rank, receiver)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                       self.aggregator.variables)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, client_idx)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
        if self.model_transport:
            msg.set_wire_transport(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                                   self.model_transport)
        msg.wire_compress = self.wire_compress
        self.send_message(msg)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self._handle_model_from_client)

    def _handle_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        upload_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND)
        marker = msg.get(MyMessage.MSG_ARG_KEY_SECAGG)
        secure = self.aggregator.secure is not None
        if secure != (marker is not None):
            # ISSUE 20: plain uplink to a secure server (or masked
            # words to a plain one) — quarantine BY NAME, never fold.
            # The sender's slot can never fill (skew is config, not
            # luck), so mark it dead for the barrier and close the
            # round if everyone else already uploaded — otherwise the
            # all-received barrier waits on this rank forever.
            log.warning(
                "%s server: %s uplink from rank %d quarantined "
                "(--secure_agg config skew between server and client)",
                "secure" if secure else "plain",
                "PLAIN" if secure else "MASKED", sender)
            with self._round_lock:
                self._quarantined.add(sender)
                if not self._quorum_met():
                    return
                last = self._finish_round()
            if last:
                self.finish()
            return
        with self._round_lock:
            if (upload_round is not None
                    and int(upload_round) != self.round_idx):
                return    # straggler from a round already closed by timeout
            all_received = self.aggregator.add_local_trained_result(
                sender - 1, msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS),
                msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES))
            done = all_received or self._quorum_met()
            if self.straggler_timeout is not None and self._watchdog is None \
                    and not done:
                self._arm_watchdog(self.round_idx)
            if not done:
                return
            last = self._finish_round()
        if last:       # finish() outside _round_lock: it joins the receive
            self.finish()   # thread, which may be waiting on that lock

    def _quorum_met(self) -> bool:
        """All non-quarantined slots received (caller holds _round_lock).
        A config-skew-quarantined rank never fills its slot, so the
        all-received barrier discounts it; at least one genuine upload
        is still required — an all-skew cohort has nothing to commit
        (the launcher's overall timeout reports that by name)."""
        got = self.aggregator.received_count()
        return (got > 0
                and got + len(self._quarantined) >= self.aggregator.worker_num)

    def _arm_watchdog(self, armed_round: int) -> None:
        self._watchdog = threading.Timer(
            self.straggler_timeout, self._on_straggler_timeout,
            args=(armed_round,))
        self._watchdog.daemon = True
        self._watchdog.start()

    def _on_straggler_timeout(self, armed_round: int) -> None:
        with self._round_lock:
            self._watchdog = None
            if self.round_idx != armed_round:
                return                      # round completed normally
            # the watchdog is armed only after a first upload, so at least
            # one slot is filled whenever we get here
            self.partial_rounds += 1
            last = self._finish_round()
        if last:
            self.finish()

    def _finish_round(self) -> bool:
        """Aggregate + advance; caller holds _round_lock.  Returns True
        when this was the last round — the caller must then call finish()
        AFTER releasing the lock (finish joins the receive thread, which
        may itself be blocked on _round_lock)."""
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None
        # commit-family delimiter: fedml_tpu/obs/timeline.py windows the
        # FSM deployment's rounds aggregate-to-aggregate, exactly like
        # the async path's async.commit spans
        with obs.span("fsm.aggregate", round=self.round_idx,
                      node="server"):
            try:
                self.aggregator.aggregate(self.round_idx)
            except SecAggBelowThreshold as e:
                # ISSUE 20: the round fails BY NAME — keep it open (the
                # arrived folds survive), re-arm the straggler watchdog,
                # and wait for late uploads to clear the threshold;
                # committing would bake unerasable mask noise into the
                # model
                self.aggregator.secure_below_threshold += 1
                log.warning("secure round %d did not aggregate: %s",
                            self.round_idx, e)
                if self.straggler_timeout is not None:
                    self._arm_watchdog(self.round_idx)
                return False
        if self.on_round_done is not None:
            self.on_round_done(self.round_idx, self.aggregator.variables)
        self.round_idx += 1
        if self.round_idx >= self.round_num:
            self.done.set()
            return True
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        for rank in range(1, self.size):
            self._send_model(rank,
                             MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                             int(client_indexes[rank - 1]))
        return False


class FedAvgClientManager(ClientManager):
    """FedAvgClientManager.py:14-75: on init/sync → update model+dataset,
    train locally (the jitted ClientTrainer hot loop), upload."""

    def __init__(self, trainer, data, epochs: int, rank: int, size: int,
                 backend: str = "INPROC", total_rounds: Optional[int] = None,
                 wire_compress: bool = False, secure=None, **kw):
        """total_rounds: in multi-PROCESS deployments the client must stop
        itself — it counts model syncs (the server sends exactly one per
        round, reference FedAvgClientManager.py:60-66) and finishes after
        uploading the last one.  None (in-process simulation) leaves
        shutdown to the launcher.

        The client's model upload is aggregation-critical (it feeds the
        server's weighted average) and deliberately has NO transport
        knob — it always rides exact; wire_compress only zlibs the
        frame head (lossless)."""
        super().__init__(rank, size, backend, **kw)
        self.wire_compress = wire_compress
        # ISSUE 20: the client's view of the secure data plane (masking
        # only — reads the seed-derived keyring, holds no server state)
        self.secure = secure
        self.secagg_rejected = 0
        self.trainer = trainer
        self.data = data
        self.epochs = epochs
        self.total_rounds = total_rounds
        self.rounds_seen = 0
        self.done = threading.Event()
        self._local_train = jax.jit(
            lambda v, shard, rng: trainer.local_train(
                v, shard, rng, self.epochs),
            static_argnames=())
        self._rng = jax.random.PRNGKey(1000 + rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._handle_sync)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._handle_sync)

    def _handle_sync(self, msg: Message) -> None:
        variables = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        client_idx = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        round_idx = msg.get(MyMessage.MSG_ARG_KEY_ROUND)
        shard = jax.tree.map(lambda a: jnp.asarray(a[client_idx]),
                             self.data.client_shards)
        self._rng, rng = jax.random.split(self._rng)
        # the round's client-side train wall — the stage the timeline
        # analyzer books as `train` when this client's trace is merged
        # with the server's (fedml_tpu/obs/timeline.py)
        with obs.span("fsm.local_train", rank=self.rank,
                      client=client_idx, round=round_idx):
            new_vars, loss, n = self._local_train(
                jax.tree.map(jnp.asarray, variables), shard, rng)
            n.block_until_ready()
        out = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                      self.rank, 0)
        if self.secure is not None:
            # ISSUE 20: quantize + pairwise-mask the weighted flat row;
            # the sample weight rides as the masked trailing word, so
            # NUM_SAMPLES ships a constant 1.0 and per-client sample
            # counts stay private.  A quantizer refusal (fixed-point
            # field overflow — the one bound masking cannot blind)
            # drops the uplink: the straggler timeout carries the round.
            from fedml_tpu.async_.staleness import flatten_vars_row
            try:
                masked = self.secure.client_row(
                    self.rank, int(round_idx or 0),
                    np.asarray(flatten_vars_row(_to_numpy(new_vars)),
                               np.float64),
                    float(n))
            except ValueError as e:
                self.secagg_rejected += 1
                obs.counter("secagg_rejected_uplinks_total").inc()
                log.warning(
                    "secagg client %d: round %d uplink refused at "
                    "quantization (norm-bound enforcement): %s",
                    self.rank, int(round_idx or 0), e)
                self.rounds_seen += 1
                return
            out.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, masked)
            out.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)
            out.add_params(MyMessage.MSG_ARG_KEY_SECAGG,
                           {"round": int(round_idx or 0)})
            out.set_wire_transport(
                MyMessage.MSG_ARG_KEY_MODEL_PARAMS, "secagg",
                scale=self.secure.cfg.scale, p=self.secure.cfg.prime)
        else:
            out.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           _to_numpy(new_vars))
            out.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        out.add_params(MyMessage.MSG_ARG_KEY_LOCAL_LOSS, float(loss))
        if round_idx is not None:       # echo for stale-upload rejection
            out.add_params(MyMessage.MSG_ARG_KEY_ROUND, int(round_idx))
        out.wire_compress = self.wire_compress
        self.send_message(out)
        self.rounds_seen += 1
        if (self.total_rounds is not None
                and self.rounds_seen >= self.total_rounds):
            self.done.set()
            self.finish()


def run_messaging_fedavg(trainer, data, cfg, backend: str = "INPROC",
                         worker_num: Optional[int] = None, **backend_kw):
    """Launch server + workers (threads for INPROC; one rank per process for
    GRPC/TCP — then call the managers directly instead).  Returns the final
    variables after cfg.comm_round rounds."""
    from fedml_tpu.comm.inproc import InProcRouter

    worker_num = worker_num or cfg.client_num_per_round
    size = worker_num + 1
    straggler_timeout = backend_kw.pop("straggler_timeout", None)
    model_transport = backend_kw.pop("model_transport", None)
    wire_compress = backend_kw.pop("wire_compress", False)
    secure_cfg = backend_kw.pop("secure", None)
    router = backend_kw.pop("router", None)
    if backend.upper() == "INPROC" and router is None:
        router = InProcRouter()
    kw = dict(backend_kw)
    if router is not None:
        kw["router"] = router

    init_vars = trainer.init(jax.random.PRNGKey(cfg.seed),
                             jnp.asarray(data.client_shards["x"][0, 0]))
    secagg = None
    if secure_cfg is not None:
        # one shared SecureAggregator (ISSUE 20): the aggregator folds/
        # unmasks, the clients only read the seed-derived keyring
        from fedml_tpu.async_.staleness import flat_dim
        from fedml_tpu.secure.secagg import SecureAggregator
        secagg = SecureAggregator(secure_cfg, range(1, size),
                                  flat_dim(_to_numpy(init_vars)))
    agg = FedAvgAggregator(init_vars, worker_num,
                           cfg.client_num_in_total, worker_num,
                           secure=secagg)
    server = FedAvgServerManager(agg, cfg.comm_round, 0, size, backend,
                                 straggler_timeout=straggler_timeout,
                                 model_transport=model_transport,
                                 wire_compress=wire_compress, **kw)
    clients = [FedAvgClientManager(trainer, data, cfg.epochs, r, size,
                                   backend, wire_compress=wire_compress,
                                   secure=secagg, **kw)
               for r in range(1, size)]
    threads = [c.run_async() for c in clients] + [server.run_async()]
    server.send_init_msg()
    if not server.done.wait(timeout=600):
        for c in clients:
            c.finish()
        server.finish()   # close the server backend too (frees its port)
        raise TimeoutError(
            f"messaging FedAvg did not finish {cfg.comm_round} rounds in "
            f"600s (stalled at round {server.round_idx}; a client likely "
            "died mid-round)")
    for c in clients:
        c.finish()
    for t in threads:
        t.join(timeout=10)
    return jax.tree.map(jnp.asarray, agg.variables)
