"""Reliable-delivery envelope — exactly-once frame ingestion over lossy
transports (ISSUE 8).

FedML's target regime (arXiv:2007.13518) is intermittent, unreliable
cross-device clients, yet the wire layer assumed a clean network: no
frame integrity check, no ack/resend, no duplicate suppression.  That
was survivable while the server drained whole-cohort barriers, but the
ISSUE-6 aggregation-on-arrival path folds every delivered frame straight
into the streaming accumulator — ONE retried or duplicated uplink
silently corrupts the weighted sum.  This module closes the gap with a
thin, v1-compatible envelope around the existing MessageCodec frames:

    FMLR ‖ u8 kind ‖ u32 sender ‖ u64 seq ‖ u32 crc32(inner) ‖ inner

* **seq** is per-(sender, peer) monotonic — the receiver's dedup ledger
  drops replays BEFORE decode, so the streaming accumulator under a
  dup-storm is BITWISE the clean-run accumulator (pinned in
  tests/test_chaos.py).
* **crc32** covers the inner frame — a corrupt frame is quarantined
  (metric + NACK) instead of killing the recv thread.
* **ack/nack** ride the reverse channel (the TCP reply path, the gRPC
  unary response, a dial-back on native/inproc); unacked frames resend
  with jittered exponential backoff from ONE `BackoffPolicy` — the
  same policy object the per-backend connect/send retry loops now draw
  their delays from, replacing the ad-hoc sleeps.

Envelopes only exist when a sender opted in
(`BaseCommManager.enable_reliability`); with reliability disabled (or
the `FEDML_RELIABLE=0` escape hatch) frames are byte-identical to the
pre-envelope build across every codec flavor (pinned in
tests/test_wire_codec.py).  Receivers unwrap FMLR frames regardless of
their own send-side setting, so mixed deployments interoperate in both
directions — the same compatibility stance as wire codec v2.

Delivery semantics, stated honestly: an ACK means *delivered and
deduplicated*, not yet folded — exactly-once INGESTION comes from the
ledger guarding the one `_ingest_row` insert path, and crash durability
from the async server's per-commit orbax checkpoint
(fedml_tpu/async_/lifecycle.py), not from the ack itself.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import random
import struct
import threading
import time
import zlib
from typing import Callable, Optional

import numpy as np

from fedml_tpu import obs

log = logging.getLogger(__name__)

ENV_RELIABLE = "FEDML_RELIABLE"      # "0" = escape hatch: never envelope

MAGIC = b"FMLR"
KIND_DATA = 0
KIND_ACK = 1
KIND_NACK = 2

_HEADER = struct.Struct("<4sBIQI")   # magic, kind, sender, seq, crc
HEADER_LEN = _HEADER.size


def escape_hatch_off() -> bool:
    """True when FEDML_RELIABLE=0 force-disables the envelope process-wide
    (mirrors FEDML_WIRE_V1 / --no_prefetch: one env var back to the
    pre-PR wire behavior)."""
    return os.environ.get(ENV_RELIABLE, "") == "0"


@dataclasses.dataclass
class BackoffPolicy:
    """Jittered exponential backoff — THE retry-delay schedule.  One
    policy object serves the resend thread, the TCP/native connect
    loops, and the gRPC send retry, so "how patient is this federation
    with a flaky peer" is one tunable, not five ad-hoc sleeps.

    delay(attempt) = min(base_s·mult^(attempt-1), max_s) ± jitter —
    jitter is drawn from the policy's own seeded PRNG, so two policies
    with the same seed produce the same schedule (the chaos benches
    stay repeatable)."""
    base_s: float = 0.25
    mult: float = 2.0
    max_s: float = 4.0
    jitter: float = 0.25          # ± fraction of the base delay
    max_attempts: int = 12        # resend gives up (loudly) after this
    seed: int = 0

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()

    def delay(self, attempt: int) -> float:
        d = min(self.base_s * (self.mult ** max(0, attempt - 1)),
                self.max_s)
        if self.jitter <= 0.0:
            return d
        with self._lock:
            u = self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d * (1.0 + u))


class _PeerLedger:
    """Per-sender duplicate ledger: `contig` is the highest seq with
    every predecessor seen; out-of-order arrivals park in `pending`
    until the gap closes, so memory is bounded by the sender's in-flight
    window (plus losses), not the stream length."""

    __slots__ = ("contig", "pending")

    def __init__(self):
        self.contig = -1
        self.pending: set[int] = set()

    def seen(self, seq: int) -> bool:
        return seq <= self.contig or seq in self.pending

    def mark(self, seq: int) -> None:
        if seq == self.contig + 1:
            self.contig += 1
            while (self.contig + 1) in self.pending:
                self.pending.discard(self.contig + 1)
                self.contig += 1
        elif seq > self.contig:
            self.pending.add(seq)


class _Outstanding:
    __slots__ = ("peer", "wire", "attempts", "due")

    def __init__(self, peer: int, wire: bytes, due: float):
        self.peer = peer
        self.wire = wire
        self.attempts = 1
        self.due = due


class ReliableEndpoint:
    """One process's reliability state over one transport: per-peer seq
    assignment + outstanding map on the send side, dedup ledger + CRC
    quarantine + ack emission on the receive side, and a lazy daemon
    resend thread driving the backoff schedule.

    `send_raw(peer, wire)` is the transport's raw frame write (it may
    raise — failures just leave the frame outstanding for the resend
    thread).  `on_wire(data, reply=...)` processes any FMLR frame;
    `reply` (when the transport has a reverse channel, e.g. the TCP
    connection the frame arrived on) short-circuits the ack back the
    way the data came."""

    def __init__(self, rank: int, send_raw: Callable[[int, bytes], None],
                 policy: Optional[BackoffPolicy] = None, name: str = ""):
        self.rank = int(rank)
        self.name = name
        self._send_raw = send_raw
        self.policy = policy if policy is not None else BackoffPolicy()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._seq: dict[int, int] = {}
        self._outstanding: dict[tuple[int, int], _Outstanding] = {}
        self._ledger: dict[int, _PeerLedger] = {}
        self._alive = True
        self._thread: Optional[threading.Thread] = None
        self._m_retries = obs.counter("comm_reliable_retries_total")
        self._m_acks = obs.counter("comm_reliable_acks_total")
        self._m_nacks = obs.counter("comm_reliable_nacks_total")
        self._m_dups = obs.counter("comm_reliable_dups_suppressed_total")
        self._m_quar = obs.counter("comm_frames_quarantined_total")
        self._m_abandoned = obs.counter("comm_reliable_abandoned_total")

    # -- send side -----------------------------------------------------------
    def wrap(self, peer: int, frame: bytes) -> bytes:
        """Envelope `frame` for `peer`: assign the next seq, register it
        outstanding (the resend thread owns it until the ack lands), and
        return the wire bytes.  Callers that transmit themselves (the
        chaos disconnect hook) use this; normal sends go through
        send()."""
        frame = bytes(frame)
        crc = zlib.crc32(frame) & 0xFFFFFFFF
        with self._lock:
            seq = self._seq.get(peer, 0)
            self._seq[peer] = seq + 1
            wire = _HEADER.pack(MAGIC, KIND_DATA, self.rank, seq,
                                crc) + frame
            self._outstanding[(peer, seq)] = _Outstanding(
                peer, wire, time.monotonic() + self.policy.delay(1))
            self._ensure_thread_locked()
            self._cv.notify()
        return wire

    def send(self, peer: int, frame: bytes) -> bytes:
        """wrap + best-effort first transmit.  A transport failure here
        does NOT raise: the frame is already outstanding and the resend
        thread retries it on the backoff schedule — exactly the crash
        window (peer down, server restarting) the envelope exists for."""
        wire = self.wrap(peer, frame)
        try:
            self._send_raw(peer, wire)
        except Exception as e:
            self._m_retries.inc()
            log.debug("%s: first transmit to %d failed (%s); resend "
                      "thread owns it", self.name, peer, e)
        return wire

    def pending(self) -> int:
        with self._lock:
            return len(self._outstanding)

    # -- crash-resume state --------------------------------------------------
    # slack added to restored send seqs: dispatches sent AFTER the last
    # checkpoint but before the crash consumed seqs the checkpoint never
    # saw — restarting exactly at the saved counter would reuse them and
    # the peers' ledgers would suppress the resumed server's first sends
    # (including the send_start re-handshake).  The slack dwarfs any
    # realistic between-checkpoint send count; seqs are u64, so burning
    # 2^16 per crash costs nothing.
    SEQ_RESUME_SLACK = 65536

    def export_seq_state(self, size: int) -> dict:
        """Checkpointable per-peer state for ranks [0, size): the next
        send seq, and the dedup ledger's high-water mark (max seq seen —
        the conservative summary: replays at or below it are suppressed
        after resume; unseen gap seqs below it are suppressed too, which
        LOSES those updates rather than double-folding an already-
        committed one — for FL aggregation loss is benign, corruption is
        not)."""
        with self._lock:
            seq = np.zeros((size,), np.int64)
            for p, s in self._seq.items():
                if 0 <= p < size:
                    seq[p] = s
            seen = np.full((size,), -1, np.int64)
            for p, led in self._ledger.items():
                if 0 <= p < size:
                    seen[p] = max([led.contig] + sorted(led.pending)[-1:])
        return {"seq": seq, "seen": seen}

    def import_seq_state(self, state: dict) -> None:
        """Restore a checkpoint's export_seq_state: send seqs resume
        past the saved counters (plus SEQ_RESUME_SLACK), and each peer's
        ledger watermark suppresses replays of pre-crash deliveries —
        the exactly-once guarantee survives the crash-resume window
        where an ingested frame's ACK died with the old server."""
        seq = np.asarray(state["seq"], np.int64)
        seen = np.asarray(state["seen"], np.int64)
        with self._lock:
            for p in range(seq.shape[0]):
                if seq[p] > 0:
                    self._seq[p] = max(self._seq.get(p, 0),
                                       int(seq[p]) + self.SEQ_RESUME_SLACK)
                if seen[p] >= 0:
                    led = self._ledger.get(p)
                    if led is None:
                        led = self._ledger[p] = _PeerLedger()
                    led.contig = max(led.contig, int(seen[p]))

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until every outstanding frame is acked (or abandoned)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._outstanding:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.1))
        return True

    # -- receive side --------------------------------------------------------
    def on_wire(self, data, reply: Optional[Callable[[bytes], None]] = None
                ) -> Optional[bytes]:
        """Process one FMLR frame.  Returns the inner payload for DATA
        frames that pass CRC and the dedup ledger (the caller then runs
        the normal decode/sink path), None otherwise (ack/nack
        bookkeeping, suppressed duplicate, quarantined corruption)."""
        head = bytes(data[:HEADER_LEN])
        if len(head) < HEADER_LEN:
            self._m_quar.inc()
            log.warning("%s: truncated reliability header (%d bytes) — "
                        "quarantined", self.name, len(head))
            return None
        magic, kind, sender, seq, crc = _HEADER.unpack(head)
        if kind == KIND_ACK:
            with self._lock:
                if self._outstanding.pop((sender, seq), None) is not None:
                    self._m_acks.inc()
                    self._cv.notify_all()
            return None
        if kind == KIND_NACK:
            # the peer SAW the frame but couldn't use it: resend now
            with self._lock:
                ent = self._outstanding.get((sender, seq))
                if ent is not None:
                    ent.due = time.monotonic()
                    self._cv.notify()
            return None
        if kind != KIND_DATA:
            self._m_quar.inc()
            log.warning("%s: unknown envelope kind %d from %d — "
                        "quarantined", self.name, kind, sender)
            return None
        inner = bytes(data[HEADER_LEN:])
        if (zlib.crc32(inner) & 0xFFFFFFFF) != crc:
            # corrupt in flight: quarantine + NACK so the sender resends
            # instead of the recv thread dying mid-decode
            self._m_quar.inc()
            obs.instant("chaos.quarantine", sender=sender, seq=seq,
                        nbytes=len(inner))
            self._control(KIND_NACK, sender, seq, reply)
            self._m_nacks.inc()
            return None
        with self._lock:
            led = self._ledger.get(sender)
            if led is None:
                led = self._ledger[sender] = _PeerLedger()
            dup = led.seen(seq)
            if not dup:
                led.mark(seq)
        if dup:
            # replay (retry storm / injected duplicate): suppress, but
            # RE-ACK — the original ack may be the thing that was lost
            self._m_dups.inc()
            self._control(KIND_ACK, sender, seq, reply)
            return None
        self._control(KIND_ACK, sender, seq, reply)
        return inner

    def _control(self, kind: int, peer: int, seq: int,
                 reply: Optional[Callable[[bytes], None]]) -> None:
        wire = _HEADER.pack(MAGIC, kind, self.rank, seq, 0)
        try:
            if reply is not None:
                reply(wire)
            else:
                self._send_raw(peer, wire)
        except Exception as e:
            # a lost ack is recoverable (the peer resends, the ledger
            # suppresses) — never let it kill the recv path
            log.debug("%s: ack/nack to %d failed (%s)", self.name, peer, e)

    # -- resend thread -------------------------------------------------------
    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._resend_loop, daemon=True,
                name=f"reliable-resend-{self.name}")
            self._thread.start()

    def _resend_loop(self) -> None:
        while True:
            with self._lock:
                if not self._alive:
                    return
                now = time.monotonic()
                due = [e for e in self._outstanding.values()
                       if e.due <= now]
                if not due:
                    nxt = min((e.due for e in
                               self._outstanding.values()),
                              default=now + 0.2)
                    self._cv.wait(timeout=max(0.01, min(nxt - now, 0.2)))
                    continue
                for e in due:
                    e.attempts += 1
                    if e.attempts > self.policy.max_attempts:
                        self._outstanding.pop(
                            (e.peer, _HEADER.unpack(
                                e.wire[:HEADER_LEN])[3]), None)
                        self._m_abandoned.inc()
                        log.warning(
                            "%s: frame to %d abandoned after %d attempts",
                            self.name, e.peer, e.attempts - 1)
                        continue
                    e.due = now + self.policy.delay(e.attempts)
                send_now = [e for e in due
                            if e.attempts <= self.policy.max_attempts]
            for e in send_now:                 # transmit OUTSIDE the lock
                self._m_retries.inc()
                obs.instant("chaos.retry", peer=e.peer,
                            attempt=e.attempts)
                try:
                    self._send_raw(e.peer, e.wire)
                except Exception as ex:
                    log.debug("%s: resend to %d failed (%s)", self.name,
                              e.peer, ex)

    def close(self) -> None:
        with self._lock:
            self._alive = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)
