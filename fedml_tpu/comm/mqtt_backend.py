"""MQTT comm backend — broker-mediated edge/device transport.

Parity: fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-126
(topic scheme: the server publishes `fedml0_<client>` and subscribes
`fedml_<client>`; clients the mirror image).  Payloads are the Message
mobile-parity JSON (brokered devices won't speak the binary frame).

paho-mqtt is optional; when absent the backend falls back to the in-repo
MQTT 3.1.1 wire client (comm/mqtt_wire.py — same frames a real broker
speaks, tested against the in-repo MiniMqttBroker over TCP sockets).
"""
from __future__ import annotations

import logging
import os
import time
import zlib

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message

log = logging.getLogger(__name__)

_TOPIC_S2C = "fedml0_"     # server → client <id>
_TOPIC_C2S = "fedml_"      # client <id> → server


class MqttBackend(BaseCommManager):
    backend_name = "mqtt"
    supports_frame_sink = False      # broker path speaks decoded JSON
    supports_reliability = False     # the broker's QoS is its ack story

    def __init__(self, rank: int, size: int, host: str = "127.0.0.1",
                 port: int = 1883, keepalive: int = 180,
                 client_factory=None):
        """client_factory(client_id=...) -> paho-compatible client; defaults
        to paho.mqtt.Client, falling back to the in-repo wire client
        (mqtt_wire.MiniMqttClient) when paho is absent.  Tests use both:
        an in-memory fake for topic-scheme checks and MiniMqttBroker for
        wire-level round-trips."""
        super().__init__()
        if client_factory is None:
            try:
                import paho.mqtt.client as mqtt
                if hasattr(mqtt, "CallbackAPIVersion"):
                    # paho >= 2.0 requires the callback API version as
                    # the first argument; VERSION1 keeps the v1
                    # on_message signature this backend uses
                    import functools
                    client_factory = functools.partial(
                        mqtt.Client, mqtt.CallbackAPIVersion.VERSION1)
                else:                     # pragma: no cover - env-dependent
                    client_factory = mqtt.Client
            except ImportError:           # pragma: no cover - env-dependent
                from fedml_tpu.comm.mqtt_wire import MiniMqttClient
                log.info("paho-mqtt not installed; using the in-repo "
                         "MQTT 3.1.1 wire client")
                client_factory = MiniMqttClient
        self.rank = rank
        self.size = size
        self._mqtt = client_factory(client_id=f"fedml_tpu_{rank}")
        self._mqtt.on_message = self._on_mqtt_message
        self._mqtt.connect(host, port, keepalive)
        if rank == 0:   # server listens to every client's uplink
            for cid in range(1, size):
                self._mqtt.subscribe(_TOPIC_C2S + str(cid))
        else:
            self._mqtt.subscribe(_TOPIC_S2C + str(rank))
        self._mqtt.loop_start()

    # zlib-compressed JSON payload marker (wire codec v2's frame
    # compression, adapted to the broker path: devices speak JSON, not
    # the binary frame, so the opt-in compression wraps the JSON bytes).
    # JSON payloads always start with '{' — the prefix is unambiguous.
    _ZMAGIC = b"FMLZ"

    def _on_mqtt_message(self, client, userdata, m) -> None:
        self._obs_received(len(m.payload))
        # chaos injection (ISSUE 8): the broker path never reaches
        # _deliver_frame, so the injector's receive faults apply to the
        # JSON payload bytes right here — the same one-policy torture
        # the codec-framed backends get
        chaos = self._chaos
        payloads = (chaos.filter_recv(m.payload) if chaos is not None
                    else (m.payload,))
        for payload in payloads:
            t0 = time.perf_counter()
            try:
                if payload[:4] == self._ZMAGIC:
                    payload = zlib.decompress(payload[4:])
                msg = Message.from_json(payload.decode())
            except Exception as e:
                # corrupt broker payload: quarantine (metric + log),
                # never kill paho's network thread
                self._m_quarantined.inc()
                log.warning("mqtt: undecodable payload (%d bytes) "
                            "quarantined: %s", len(payload), e)
                continue
            # the broker path speaks JSON, not the binary frame, so its
            # deserialize cost lands in the same comm_decode_seconds
            # histogram the codec-framed backends feed (comm/base.py)
            self._m_decode_seconds.observe(time.perf_counter() - t0)
            self._note_frame(msg)   # trace block rides the JSON too
            self._on_message(msg)

    def send_message(self, msg: Message) -> None:
        receiver = msg.get_receiver_id()
        topic = (_TOPIC_S2C + str(receiver) if self.rank == 0
                 else _TOPIC_C2S + str(self.rank))
        if not self._stamp_frame(msg):
            return                  # chaos send gate dropped the frame
        payload = msg.to_json().encode("utf-8")
        if getattr(msg, "wire_compress", False):
            # nested-list JSON weights compress hard (repeated digits);
            # the broker path is the bandwidth-starved edge leg, so the
            # opt-in pays exactly where it matters
            if os.environ.get("FEDML_WIRE_V1", "") in ("", "0"):
                payload = self._ZMAGIC + zlib.compress(payload)
        self._mqtt.publish(topic, payload)
        # count WIRE bytes, matching the receive side's len(m.payload)
        self._obs_sent(len(payload))

    def close(self) -> None:
        self._mqtt.loop_stop()
        self._mqtt.disconnect()
