"""comm — host-side message layer for genuinely-remote participants.

In-mesh federated traffic never touches this package (it's ICI collectives,
fedml_tpu/parallel/).  This layer exists for the reference's cross-silo /
edge deployments where clients are separate processes or machines:
BaseCommunicationManager + Message + Observer
(fedml_core/distributed/communication/, SURVEY.md §2.1) with pluggable
backends — in-process (tests/simulation), gRPC (WAN cross-silo), native TCP
(the C++ transport in fedml_tpu/native/), and MQTT (edge gateway, optional).

Differences from the reference, by design:
  * no 0.3 s polling loops or killable daemon threads
    (mpi/com_manager.py:71-78, mpi_send_thread.py:47-53) — backends push
    into a blocking queue drained by the manager's run loop;
  * one consistent port scheme (the reference binds 50000+rank but dials
    8888+rank — grpc_comm_manager.py:41-61 — a bug SURVEY.md flags);
  * tensors ride a zero-copy binary codec, with the reference's
    JSON-list mode kept for mobile parity (--is_mobile,
    fedavg/utils.py:7-16).
"""
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.comm.base import BaseCommManager, Observer
from fedml_tpu.comm.chaos import ChaosConfig, ChaosPolicy
from fedml_tpu.comm.inproc import InProcBackend, InProcRouter
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.reactor import (FdExhaustionError, ReactorConfig,
                                    ReactorGroup)
from fedml_tpu.comm.reliability import BackoffPolicy, ReliableEndpoint
