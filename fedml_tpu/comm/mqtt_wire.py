"""Minimal MQTT 3.1.1 wire implementation — in-repo broker + client.

paho-mqtt and a broker daemon are absent in this image, which left the
MQTT backend's WIRE behavior untested (round-4 verdict: "topic-scheme
parity is tested; wire-level behavior is not").  This module closes that
gap natively: a small threaded broker and a paho-surface-compatible
client speaking real MQTT 3.1.1 frames (CONNECT/CONNACK, PUBLISH QoS 0,
SUBSCRIBE/SUBACK, PINGREQ/PINGRESP, DISCONNECT) over TCP sockets.

Reference behavior being mirrored: the reference talks to an external
broker through paho (mqtt_comm_manager.py:14-126); its topic scheme and
JSON payloads ride unchanged — MqttBackend falls back to MiniMqttClient
when paho is missing, so `--backend MQTT` works wire-level out of the
box here and against a real broker (mosquitto etc.) via paho elsewhere.

Scope: QoS 0, clean sessions, no retained messages or wills — the
subset the FL topic scheme uses.  Topic filters support '+' (one level)
and a trailing '#' (multi-level), per spec 4.7.
"""
from __future__ import annotations

import logging
import socket
import struct
import sys
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from fedml_tpu.comm.tcp_backend import _read_exact

log = logging.getLogger(__name__)

CONNECT, CONNACK, PUBLISH, SUBSCRIBE, SUBACK = 0x10, 0x20, 0x30, 0x82, 0x90
PINGREQ, PINGRESP, DISCONNECT = 0xC0, 0xD0, 0xE0


def _varint(n: int) -> bytes:
    """MQTT 'remaining length' encoding (spec 2.2.3)."""
    out = bytearray()
    while True:
        d, n = n % 128, n // 128
        out.append(d | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_frame(sock: socket.socket) -> tuple[int, bytes]:
    """Returns (fixed-header byte 1, payload)."""
    h = _read_exact(sock, 1)[0]
    mult, length = 1, 0
    for _ in range(4):
        d = _read_exact(sock, 1)[0]
        length += (d & 0x7F) * mult
        if not d & 0x80:
            break
        mult *= 128
    else:
        raise ConnectionError("malformed remaining length")
    return h, _read_exact(sock, length) if length else b""


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _frame(header: int, payload: bytes) -> bytes:
    return bytes([header]) + _varint(len(payload)) + payload


def topic_matches(filt: str, topic: str) -> bool:
    """MQTT topic-filter matching (spec 4.7: '+' one level, '#' rest)."""
    fp, tp = filt.split("/"), topic.split("/")
    for i, f in enumerate(fp):
        if f == "#":
            return True
        if i >= len(tp) or (f != "+" and f != tp[i]):
            return False
    return len(fp) == len(tp)


@dataclass
class MqttMessage:
    """What the on_message callback receives (paho surface subset)."""
    topic: str
    payload: bytes


class MiniMqttBroker:
    """Threaded MQTT 3.1.1 broker (QoS 0, clean sessions)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._lock = threading.Lock()
        self._subs: dict[socket.socket, list[str]] = {}
        # per-connection write locks: _route (publisher threads) and the
        # connection's own _serve thread (SUBACK/PINGRESP) both write to
        # a subscriber socket — unserialized sendalls would interleave
        # frames and desync the stream
        self._wlocks: dict[socket.socket, threading.Lock] = {}
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    # a stalled subscriber (full TCP buffer, process paused) must not
    # wedge the publisher's serve thread forever: sends time out and the
    # dead connection is dropped (its serve loop then cleans up)
    SEND_TIMEOUT_S = 30.0

    def _send(self, conn: socket.socket, data: bytes) -> None:
        wlock = self._wlocks.get(conn)
        if wlock is None:
            return                   # connection already torn down
        try:
            with wlock:
                conn.sendall(data)
        except (socket.timeout, OSError):
            log.warning("broker: dropping stalled/dead subscriber")
            try:
                # shutdown (not just close) so the connection's _serve
                # thread blocked in recv wakes up and runs its cleanup —
                # close() alone does not interrupt an in-flight recv
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()

    def _serve(self, conn: socket.socket) -> None:
        try:
            h, _ = _read_frame(conn)
            if h & 0xF0 != CONNECT:
                return
            # send-direction timeout ONLY (SO_SNDTIMEO): reads stay
            # blocking — a settimeout() would fire mid-frame on recv.
            # The payload is a struct timeval on POSIX but a DWORD of
            # milliseconds on Windows.
            conn.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("<L", int(self.SEND_TIMEOUT_S * 1000))
                if sys.platform == "win32"
                else struct.pack("ll", int(self.SEND_TIMEOUT_S), 0))
            with self._lock:
                self._subs[conn] = []
                self._wlocks[conn] = threading.Lock()
            self._send(conn, _frame(CONNACK, b"\x00\x00"))
            while True:
                h, body = _read_frame(conn)
                t = h & 0xF0
                if t == PUBLISH:
                    tl = struct.unpack(">H", body[:2])[0]
                    topic = body[2:2 + tl].decode()
                    payload = body[2 + tl:]     # QoS 0: no packet id
                    self._route(topic, payload)
                elif t == SUBSCRIBE & 0xF0:
                    pid, off, codes = body[:2], 2, b""
                    with self._lock:
                        while off < len(body):
                            fl = struct.unpack(">H", body[off:off + 2])[0]
                            filt = body[off + 2:off + 2 + fl].decode()
                            off += 3 + fl       # + requested-qos byte
                            self._subs[conn].append(filt)
                            codes += b"\x00"    # granted QoS 0
                    self._send(conn, _frame(SUBACK, pid + codes))
                elif t == PINGREQ:
                    self._send(conn, _frame(PINGRESP, b""))
                elif t == DISCONNECT:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                self._subs.pop(conn, None)
                self._wlocks.pop(conn, None)
            conn.close()

    def _route(self, topic: str, payload: bytes) -> None:
        pub = _frame(PUBLISH, _mqtt_str(topic) + payload)
        with self._lock:
            targets = [c for c, filts in self._subs.items()
                       if any(topic_matches(f, topic) for f in filts)]
        for c in targets:
            self._send(c, pub)       # _send drops dead receivers itself

    def close(self) -> None:
        self._running = False
        self._srv.close()
        with self._lock:
            conns = list(self._subs)
        for c in conns:
            c.close()


class MiniMqttClient:
    """paho-surface-compatible MQTT 3.1.1 client (the subset MqttBackend
    uses: connect / subscribe / publish / loop_start / loop_stop /
    disconnect, with an `on_message(client, userdata, msg)` callback)."""

    def __init__(self, client_id: str = ""):
        self._client_id = client_id or "mini-mqtt"
        self.on_message: Optional[Callable] = None
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._reader: Optional[threading.Thread] = None
        self._pinger: Optional[threading.Thread] = None
        self._running = False
        self._keepalive = 60
        # PUBLISHes read while synchronously waiting for a SUBACK — the
        # reader loop delivers them first, in arrival order
        self._pending: list[MqttMessage] = []

    def connect(self, host: str, port: int = 1883,
                keepalive: int = 60) -> None:
        self._sock = socket.create_connection((host, port), timeout=30)
        var = (_mqtt_str("MQTT") + b"\x04\x02"      # level 4, clean session
               + struct.pack(">H", keepalive) + _mqtt_str(self._client_id))
        self._sock.sendall(_frame(CONNECT, var))
        h, body = _read_frame(self._sock)
        if h & 0xF0 != CONNACK or body[1] != 0:
            raise ConnectionError(f"CONNACK refused: {body!r}")
        # blocking reads from here on: a read TIMEOUT can fire mid-frame
        # and desync the stream, so keepalive pings come from a separate
        # pinger thread instead of a socket timeout
        self._sock.settimeout(None)
        self._keepalive = keepalive

    @staticmethod
    def _parse_publish(body: bytes) -> MqttMessage:
        tl = struct.unpack(">H", body[:2])[0]
        return MqttMessage(topic=body[2:2 + tl].decode(),
                           payload=body[2 + tl:])

    def subscribe(self, topic: str, qos: int = 0) -> None:
        body = b"\x00\x01" + _mqtt_str(topic) + bytes([qos])
        with self._send_lock:
            self._sock.sendall(_frame(SUBSCRIBE, body))
        if self._running:
            return      # reader owns the socket; it consumes the SUBACK
        # pre-loop_start (the backend's construction path): wait for the
        # SUBACK so the subscription is REGISTERED before the caller's
        # next step — a QoS-0 publish races an unacked subscribe and
        # would be silently dropped.  PUBLISHes for earlier
        # subscriptions that arrive meanwhile are buffered, not lost.
        while True:
            h, rbody = _read_frame(self._sock)
            t = h & 0xF0
            if t == SUBACK:
                return
            if t == PUBLISH:
                self._pending.append(self._parse_publish(rbody))

    def publish(self, topic: str, payload) -> None:
        if isinstance(payload, str):
            payload = payload.encode()
        with self._send_lock:
            self._sock.sendall(_frame(PUBLISH, _mqtt_str(topic) + payload))

    def loop_start(self) -> None:
        self._running = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._pinger = threading.Thread(target=self._ping_loop, daemon=True)
        self._pinger.start()

    def _deliver(self, msg: MqttMessage) -> None:
        if self.on_message is not None:
            try:
                self.on_message(self, None, msg)
            except Exception:            # paho swallows handler errors
                log.exception("on_message handler failed")

    def _read_loop(self) -> None:
        pending, self._pending = self._pending, []
        for msg in pending:              # buffered during subscribe()
            self._deliver(msg)
        while self._running:
            try:
                h, body = _read_frame(self._sock)
            except (ConnectionError, OSError):
                return
            if h & 0xF0 == PUBLISH:
                self._deliver(self._parse_publish(body))
            # SUBACK / PINGRESP: nothing to do

    def _ping_loop(self) -> None:
        import time
        interval = max(self._keepalive / 2.0, 0.5)
        while self._running:
            time.sleep(interval)
            if not self._running:
                return
            try:
                with self._send_lock:
                    self._sock.sendall(_frame(PINGREQ, b""))
            except OSError:
                return

    def loop_stop(self) -> None:
        self._running = False

    def disconnect(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                with self._send_lock:
                    self._sock.sendall(_frame(DISCONNECT, b""))
            except OSError:
                pass
            self._sock.close()
