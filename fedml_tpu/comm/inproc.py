"""In-process comm backend — N logical ranks in one process.

The reference fakes multi-node with localhost MPI processes
(run_fedavg_distributed_pytorch.sh:19-21, SURVEY.md §4.5); here the same
manager/FSM code runs over an in-memory router, so the full message-driven
algorithm stack (init → local train → upload → aggregate → sync) is unit
-testable with zero sockets.  Frames still go through MessageCodec
encode/decode so the wire path is exercised.
"""
from __future__ import annotations

import threading

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message, MessageCodec


class InProcRouter:
    """Shared mailbox fabric; one per simulated deployment."""

    def __init__(self, encode: bool = True):
        self._backends: dict[int, "InProcBackend"] = {}
        self._lock = threading.Lock()
        self.encode = encode

    def register(self, rank: int, backend: "InProcBackend") -> None:
        with self._lock:
            self._backends[rank] = backend

    def deliver_raw(self, rank: int, wire: bytes) -> None:
        """Raw-frame delivery (the reliability layer's resends/acks):
        the pre-assembled wire bytes go straight through the receiver's
        _deliver_frame chokepoint, same as an encoded route()."""
        with self._lock:
            dst = self._backends.get(rank)
        if dst is None:
            raise KeyError(f"no backend registered for rank {rank}")
        dst._obs_received(len(wire))
        dst._deliver_frame(wire)

    def route(self, msg: Message) -> int:
        """Deliver; returns the encoded frame size (0 when encode=False
        skips the codec) so both endpoints' byte counters agree."""
        rank = msg.get_receiver_id()
        with self._lock:
            dst = self._backends.get(rank)
        if dst is None:
            raise KeyError(f"no backend registered for rank {rank}")
        nbytes = 0
        if self.encode:   # exercise the wire codec even in-memory —
            # including the v2 transport/compression features a sender
            # opted into, so the simulation sees the same lossy values
            # a socket deployment would.  The raw frame goes through
            # the receiver's _deliver_frame chokepoint, so an installed
            # ingest sink (async decode pool) sees inproc traffic too.
            payload = MessageCodec.encode(msg)
            nbytes = len(payload)
            dst._obs_received(nbytes)
            dst._deliver_frame(payload)
            return nbytes
        dst._obs_received(nbytes)
        # no-encode: the Message object crosses directly — strip the
        # sender's trace stamp here (the codec-framed _deliver_frame
        # chokepoint never runs) so handlers don't see obs params
        dst._note_frame(msg)
        dst._on_message(msg)
        return nbytes


class InProcBackend(BaseCommManager):
    backend_name = "inproc"

    def __init__(self, rank: int, router: InProcRouter):
        super().__init__()
        self.rank = rank
        self.router = router
        router.register(rank, self)

    @property
    def supports_frame_sink(self) -> bool:
        # a no-encode router hands Message objects across directly —
        # frames never exist, so a sink would never fire
        return bool(self.router.encode)

    @property
    def supports_reliability(self) -> bool:
        # same constraint: the envelope wraps wire frames, which a
        # no-encode router never materializes
        return bool(self.router.encode)

    def _raw_send(self, receiver: int, wire: bytes) -> None:
        self.router.deliver_raw(receiver, wire)

    def send_message(self, msg: Message) -> None:
        if not self._stamp_frame(msg):
            return                  # chaos send gate dropped the frame
        if self._reliable_tx:
            payload = MessageCodec.encode(msg)
            wire = self._reliability_endpoint().send(
                msg.get_receiver_id(), payload)
            self._obs_sent(len(wire))
            return
        self._obs_sent(self.router.route(msg))
