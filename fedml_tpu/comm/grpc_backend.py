"""gRPC comm backend — WAN / cross-silo transport.

Parity: fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:22-119
+ grpc_server.py:9-40.  Differences by design (SURVEY.md flags these):

  * one port scheme: every rank serves on base_port+rank and peers dial the
    same (the reference binds 50000+rank but dials 8888+receiver —
    grpc_comm_manager.py:41-61);
  * no busy-wait dispatch thread (grpc_comm_manager.py:87-98) — the servicer
    pushes straight into the manager's blocking inbox;
  * messages ride the binary MessageCodec frame through a *generic* RPC
    method (bytes in, bytes out), so no protobuf stub codegen is needed;
    1 GB max message kept (reference :36-40).

ip_config: {rank: ip} dict or a CSV path with `receiver_id,ip` rows
(ip_config_utils.py parity).
"""
from __future__ import annotations

import csv
import logging
from concurrent import futures
from typing import Union

import grpc

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message, MessageCodec

log = logging.getLogger(__name__)

_SERVICE = "fedml_tpu.Comm"
_METHOD = f"/{_SERVICE}/SendMessage"
_MAX_MSG = 1000 * 1024 * 1024
_OPTS = [("grpc.max_send_message_length", _MAX_MSG),
         ("grpc.max_receive_message_length", _MAX_MSG),
         ("grpc.enable_http_proxy", 0)]


def load_ip_config(path_or_dict: Union[str, dict]) -> dict[int, str]:
    """CSV `receiver_id,ip` → {rank: ip} (gRPC/ip_config_utils.py parity)."""
    if isinstance(path_or_dict, dict):
        return {int(k): v for k, v in path_or_dict.items()}
    out = {}
    with open(path_or_dict) as f:
        for row in csv.reader(f):
            if not row or row[0].strip().lower() in ("receiver_id", ""):
                continue
            out[int(row[0])] = row[1].strip()
    return out


class GrpcBackend(BaseCommManager):
    backend_name = "grpc"

    def __init__(self, rank: int, ip_config: Union[str, dict],
                 base_port: int = 50000, max_workers: int = 8):
        super().__init__()
        self.rank = rank
        self.ip_config = load_ip_config(ip_config)
        self.base_port = base_port
        self._channels: dict[int, grpc.Channel] = {}
        self._stubs: dict[int, grpc.UnaryUnaryMultiCallable] = {}

        def handle(request: bytes, context) -> bytes:
            self._obs_received(len(request))
            # _deliver_frame: inline decode or the async ingest sink;
            # a blocked sink holds this servicer thread, so gRPC's
            # bounded executor is the backpressure
            self._deliver_frame(request)
            return b"ok"

        handler = grpc.method_handlers_generic_handler(_SERVICE, {
            "SendMessage": grpc.unary_unary_rpc_method_handler(handle),
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_OPTS)
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(
            f"0.0.0.0:{base_port + rank}")
        self._server.start()
        log.info("gRPC rank %d serving on :%d", rank, self.port)

    def _stub(self, receiver: int):
        if receiver not in self._stubs:
            ip = self.ip_config[receiver]
            ch = grpc.insecure_channel(
                f"{ip}:{self.base_port + receiver}", options=_OPTS)
            self._channels[receiver] = ch
            self._stubs[receiver] = ch.unary_unary(_METHOD)
        return self._stubs[receiver]

    def send_message(self, msg: Message) -> None:
        # encode applies the v2 wire features (transport dtypes, zlib
        # head); gRPC's unary call needs the one contiguous frame
        self._stamp_frame(msg)      # trace block (no-op when obs is off)
        payload = MessageCodec.encode(msg)
        # wait_for_ready rides out the multi-process startup race (peer's
        # server not bound yet) instead of failing UNAVAILABLE immediately
        self._stub(msg.get_receiver_id())(payload, timeout=1800,
                                          wait_for_ready=True)
        self._obs_sent(len(payload))

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._server.stop(grace=1)
