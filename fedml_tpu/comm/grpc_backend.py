"""gRPC comm backend — WAN / cross-silo transport.

Parity: fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:22-119
+ grpc_server.py:9-40.  Differences by design (SURVEY.md flags these):

  * one port scheme: every rank serves on base_port+rank and peers dial the
    same (the reference binds 50000+rank but dials 8888+receiver —
    grpc_comm_manager.py:41-61);
  * no busy-wait dispatch thread (grpc_comm_manager.py:87-98) — the servicer
    pushes straight into the manager's blocking inbox;
  * messages ride the binary MessageCodec frame through a *generic* RPC
    method (bytes in, bytes out), so no protobuf stub codegen is needed;
    1 GB max message kept (reference :36-40).

ip_config: {rank: ip} dict or a CSV path with `receiver_id,ip` rows
(ip_config_utils.py parity).
"""
from __future__ import annotations

import csv
import logging
import os
import time
from concurrent import futures
from typing import Optional, Union

import grpc

from fedml_tpu.comm import reliability
from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.comm.reliability import BackoffPolicy

log = logging.getLogger(__name__)

# per-send RPC deadline: the old hard-coded timeout=1800 with no retry
# (ISSUE-8 satellite) — now a constructor knob with an env override for
# deployments that can't touch the construction site
ENV_SEND_TIMEOUT = "FEDML_GRPC_TIMEOUT_S"
DEFAULT_SEND_TIMEOUT_S = 1800.0

_SERVICE = "fedml_tpu.Comm"
_METHOD = f"/{_SERVICE}/SendMessage"
_MAX_MSG = 1000 * 1024 * 1024
_OPTS = [("grpc.max_send_message_length", _MAX_MSG),
         ("grpc.max_receive_message_length", _MAX_MSG),
         ("grpc.enable_http_proxy", 0)]


def load_ip_config(path_or_dict: Union[str, dict]) -> dict[int, str]:
    """CSV `receiver_id,ip` → {rank: ip} (gRPC/ip_config_utils.py parity)."""
    if isinstance(path_or_dict, dict):
        return {int(k): v for k, v in path_or_dict.items()}
    out = {}
    with open(path_or_dict) as f:
        for row in csv.reader(f):
            if not row or row[0].strip().lower() in ("receiver_id", ""):
                continue
            out[int(row[0])] = row[1].strip()
    return out


class GrpcBackend(BaseCommManager):
    backend_name = "grpc"

    def __init__(self, rank: int, ip_config: Union[str, dict],
                 base_port: int = 50000, max_workers: int = 8,
                 send_timeout_s: Optional[float] = None,
                 send_backoff: Optional[BackoffPolicy] = None):
        super().__init__()
        self.rank = rank
        self.ip_config = load_ip_config(ip_config)
        self.base_port = base_port
        env_t = os.environ.get(ENV_SEND_TIMEOUT)
        self.send_timeout_s = float(
            send_timeout_s if send_timeout_s is not None
            else (env_t if env_t else DEFAULT_SEND_TIMEOUT_S))
        # transient-failure retry for plain (non-enveloped) sends —
        # drawn from the same BackoffPolicy the reliability layer and
        # the TCP/native connect loops use, not another ad-hoc sleep
        self.send_backoff = send_backoff if send_backoff is not None \
            else BackoffPolicy(base_s=0.5, mult=2.0, max_s=8.0,
                               jitter=0.25, max_attempts=4)
        self._channels: dict[int, grpc.Channel] = {}
        self._stubs: dict[int, grpc.UnaryUnaryMultiCallable] = {}

        def handle(request: bytes, context) -> bytes:
            self._obs_received(len(request))
            # _deliver_frame: inline decode or the async ingest sink;
            # a blocked sink holds this servicer thread, so gRPC's
            # bounded executor is the backpressure.  The unary RESPONSE
            # is the reliability reply channel: when the frame carried
            # the FMLR envelope, the ack/nack rides back as the RPC
            # result instead of b"ok".
            out: list[bytes] = []
            try:
                self._deliver_frame(request, reply=out.append)
            except Exception:
                self._m_recv_deaths.inc()
                log.exception("grpc servicer died on an unexpected error")
            return out[0] if out else b"ok"

        handler = grpc.method_handlers_generic_handler(_SERVICE, {
            "SendMessage": grpc.unary_unary_rpc_method_handler(handle),
        })
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_OPTS)
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(
            f"0.0.0.0:{base_port + rank}")
        self._server.start()
        log.info("gRPC rank %d serving on :%d", rank, self.port)

    def _stub(self, receiver: int):
        if receiver not in self._stubs:
            ip = self.ip_config[receiver]
            ch = grpc.insecure_channel(
                f"{ip}:{self.base_port + receiver}", options=_OPTS)
            self._channels[receiver] = ch
            self._stubs[receiver] = ch.unary_unary(_METHOD)
        return self._stubs[receiver]

    def _raw_send(self, receiver: int, wire: bytes) -> None:
        """Raw transmit for the reliability layer; the unary response
        carries the peer's ack/nack, fed straight back into the
        endpoint (so a successful RPC usually clears the outstanding
        entry synchronously)."""
        resp = self._stub(receiver)(bytes(wire),
                                    timeout=self.send_timeout_s,
                                    wait_for_ready=True)
        if resp and bytes(resp[:4]) == reliability.MAGIC:
            self._reliability_endpoint().on_wire(resp)

    def send_message(self, msg: Message) -> None:
        # encode applies the v2 wire features (transport dtypes, zlib
        # head); gRPC's unary call needs the one contiguous frame
        if not self._stamp_frame(msg):
            return                  # chaos send gate dropped the frame
        payload = MessageCodec.encode(msg)
        rx = msg.get_receiver_id()
        if self._reliable_tx:
            wire = self._reliability_endpoint().send(rx, payload)
            self._obs_sent(len(wire))
            return
        # wait_for_ready rides out the multi-process startup race (peer's
        # server not bound yet) instead of failing UNAVAILABLE immediately;
        # transient RpcErrors retry on the shared backoff schedule
        # (ISSUE-8 satellite: was a hard-coded timeout=1800, no retry)
        attempt = 0
        while True:
            try:
                self._stub(rx)(payload, timeout=self.send_timeout_s,
                               wait_for_ready=True)
                break
            except grpc.RpcError:
                attempt += 1
                if attempt >= self.send_backoff.max_attempts:
                    raise
                self._obs_retry()
                time.sleep(self.send_backoff.delay(attempt))
        self._obs_sent(len(payload))

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._server.stop(grace=1)
