"""Remote SplitNN — the per-batch activation/gradient protocol over the
message layer.

Parity: fedml_api/distributed/split_nn/ — message_define.py:5-25 (types),
client_manager.py:17-107 (semaphore round-robin, acts up / grads down,
per-epoch validation), server_manager.py:14-45, client.py:24-41,
server.py:40-72.  SURVEY.md §3.4 calls this the comm-layer stress test: the
process boundary is crossed TWICE PER MINIBATCH.

TPU-native split of labor: the numerics are jitted XLA programs
(`SplitClientCompute.forward/backward`, `SplitServerCompute.train_step`)
with persistent optimizer state; the protocol layer just moves numpy
activations/gradients through Message frames, so it runs over any backend
(INPROC, GRPC, TCP/native).  Unlike the reference we also ship the batch
mask (our shards are padded) and reset per-epoch batch counters cleanly
(the reference reuses a single counter across train and eval, client_
manager.py:40-56).
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.trainer import (make_optimizer, masked_accuracy_sums,
                                    masked_cross_entropy)

log = logging.getLogger(__name__)
Pytree = Any


class SplitNNMessage:
    """Message-type constants (message_define.py:5-25)."""
    MSG_TYPE_S2C_GRADS = 1
    MSG_TYPE_C2S_SEND_ACTS = 2
    MSG_TYPE_C2S_VALIDATION_MODE = 3
    MSG_TYPE_C2S_VALIDATION_OVER = 4
    MSG_TYPE_C2S_PROTOCOL_FINISHED = 5
    MSG_TYPE_C2C_SEMAPHORE = 6

    MSG_ARG_KEY_ACTS = "activations"
    MSG_ARG_KEY_LABELS = "labels"
    MSG_ARG_KEY_MASK = "mask"
    MSG_ARG_KEY_GRADS = "activation_grads"
    MSG_ARG_KEY_PHASE = "phase"


class SplitClientCompute:
    """Client lower-net numerics: forward to the cut, backward from the
    server's activation gradients (client.py:24-35).  Optimizer state
    persists across batches (the reference builds optim.SGD once)."""

    def __init__(self, model, lr: float = 0.1, momentum: float = 0.9,
                 weight_decay: float = 5e-4, optimizer: str = "sgd"):
        self.model = model
        self.tx = make_optimizer(optimizer, lr, momentum, weight_decay)
        self._fwd = jax.jit(self._forward)
        self._bwd = jax.jit(self._backward)

    def init(self, rng, sample_x):
        params = self.model.init(rng, sample_x)["params"]
        return params, self.tx.init(params)

    def _forward(self, params, x):
        return self.model.apply({"params": params}, x)

    def _backward(self, params, opt_state, x, g):
        _acts, vjp = jax.vjp(
            lambda p: self.model.apply({"params": p}, x), params)
        grads = vjp(g)[0]
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def forward(self, params, x) -> jax.Array:
        return self._fwd(params, jnp.asarray(x))

    def backward(self, params, opt_state, x, grads):
        return self._bwd(params, opt_state, jnp.asarray(x),
                         jnp.asarray(grads))


class SplitServerCompute:
    """Server upper-net numerics: logits + loss + activation gradients in
    one jitted step (server.py:40-60 forward_pass+backward_pass fused)."""

    def __init__(self, model, lr: float = 0.1, momentum: float = 0.9,
                 weight_decay: float = 5e-4, optimizer: str = "sgd"):
        self.model = model
        self.tx = make_optimizer(optimizer, lr, momentum, weight_decay)
        self._step = jax.jit(self._train_step)
        self._ev = jax.jit(self._eval_step)

    def init(self, rng, sample_acts):
        params = self.model.init(rng, sample_acts)["params"]
        return params, self.tx.init(params)

    def _train_step(self, params, opt_state, acts, y, mask):
        def loss_fn(p, a):
            logits = self.model.apply({"params": p}, a)
            return masked_cross_entropy(logits, y, mask), logits
        (loss, logits), (gp, ga) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, acts)
        updates, opt_state = self.tx.update(gp, opt_state, params)
        params = optax.apply_updates(params, updates)
        correct, count = masked_accuracy_sums(logits, y, mask)
        return params, opt_state, ga, loss, correct, count

    def _eval_step(self, params, acts, y, mask):
        logits = self.model.apply({"params": params}, acts)
        loss = masked_cross_entropy(logits, y, mask)
        correct, count = masked_accuracy_sums(logits, y, mask)
        return loss, correct, count

    def train_step(self, params, opt_state, acts, y, mask):
        return self._step(params, opt_state, jnp.asarray(acts),
                          jnp.asarray(y), jnp.asarray(mask))

    def eval_step(self, params, acts, y, mask):
        return self._ev(params, jnp.asarray(acts), jnp.asarray(y),
                        jnp.asarray(mask))


class SplitNNClientManager(ClientManager):
    """client_manager.py:17-107 over the new comm layer.  Clients are ranks
    1..max_rank; rank 1 starts the protocol; after each epoch+validation the
    semaphore passes to node_right."""

    def __init__(self, compute: SplitClientCompute, params, opt_state,
                 train_shard: dict, test_shard: dict, rank: int,
                 max_rank: int, epochs: int, server_rank: int = 0,
                 backend: str = "INPROC",
                 act_transport: Optional[str] = None, **kw):
        """act_transport: opt-in lossy wire dtype ("bf16"/"int8", wire
        codec v2) for the per-batch ACTIVATION payload — the protocol
        crosses the process boundary twice per minibatch, so this is
        where split training's wire bytes live.  Labels/masks stay
        exact (they feed the loss/metric sums); the gradient downlink
        is the server's symmetric knob.  None (default) = exact."""
        super().__init__(rank, max_rank + 1, backend, **kw)
        self.act_transport = act_transport
        self.compute = compute
        self.params, self.opt_state = params, opt_state
        self.train_shard, self.test_shard = train_shard, test_shard
        self.max_rank = max_rank
        self.node_right = 1 if rank == max_rank else rank + 1
        self.server_rank = server_rank
        self.max_epochs = epochs          # MAX_EPOCH_PER_NODE
        self.epoch_count = 0              # this node's completed epochs
        self.batch_idx = 0
        self.phase = "train"
        self.done = threading.Event()

    # -- protocol ------------------------------------------------------------
    def start_protocol(self):
        """Rank 1 kicks off training (client_manager.py:17-21 run())."""
        if self.rank == 1:
            self.run_forward_pass()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            SplitNNMessage.MSG_TYPE_C2C_SEMAPHORE, self.handle_semaphore)
        self.register_message_receive_handler(
            SplitNNMessage.MSG_TYPE_S2C_GRADS, self.handle_gradients)

    def _shard(self):
        return self.train_shard if self.phase == "train" else self.test_shard

    def _n_batches(self):
        return self._shard()["x"].shape[0]

    def _batch(self):
        i = self.batch_idx
        s = self._shard()
        return s["x"][i], s["y"][i], s["mask"][i]

    def run_forward_pass(self):
        x, y, mask = self._batch()
        acts = np.asarray(self.compute.forward(self.params, x))
        self._last_x = x
        m = Message(SplitNNMessage.MSG_TYPE_C2S_SEND_ACTS, self.rank,
                    self.server_rank)
        m.add_params(SplitNNMessage.MSG_ARG_KEY_ACTS, acts)
        m.add_params(SplitNNMessage.MSG_ARG_KEY_LABELS, np.asarray(y))
        m.add_params(SplitNNMessage.MSG_ARG_KEY_MASK, np.asarray(mask))
        # the phase rides WITH the activations: over real sockets, messages
        # from different clients arrive on different connections and can
        # reorder vs the VALIDATION_MODE/OVER signals — the server must not
        # infer this batch's phase from its own (possibly stale) state, or
        # a train batch handled in 'validation' never gets its gradients
        # back and that client deadlocks
        m.add_params(SplitNNMessage.MSG_ARG_KEY_PHASE, self.phase)
        if self.act_transport:
            m.set_wire_transport(SplitNNMessage.MSG_ARG_KEY_ACTS,
                                 self.act_transport)
        self.send_message(m)
        self.batch_idx += 1

    def handle_semaphore(self, _msg: Message):
        self.phase, self.batch_idx = "train", 0
        self.run_forward_pass()

    def handle_gradients(self, msg: Message):
        grads = msg.get(SplitNNMessage.MSG_ARG_KEY_GRADS)
        self.params, self.opt_state = self.compute.backward(
            self.params, self.opt_state, self._last_x, grads)
        if self.batch_idx == self._n_batches():
            self.run_eval()
        else:
            self.run_forward_pass()

    def run_eval(self):
        """Per-epoch validation sweep, then hand the semaphore on
        (client_manager.py:44-60)."""
        self.send_signal(SplitNNMessage.MSG_TYPE_C2S_VALIDATION_MODE)
        self.phase, self.batch_idx = "eval", 0
        for _ in range(self._n_batches()):
            self.run_forward_pass()
        self.send_signal(SplitNNMessage.MSG_TYPE_C2S_VALIDATION_OVER)
        self.epoch_count += 1
        if (self.epoch_count == self.max_epochs
                and self.rank == self.max_rank):
            self.send_signal(SplitNNMessage.MSG_TYPE_C2S_PROTOCOL_FINISHED)
        else:
            m = Message(SplitNNMessage.MSG_TYPE_C2C_SEMAPHORE, self.rank,
                        self.node_right)
            self.send_message(m)
        if self.epoch_count == self.max_epochs:
            self.done.set()
            self.finish()

    def send_signal(self, msg_type):
        self.send_message(Message(msg_type, self.rank, self.server_rank))


class SplitNNServerManager(ServerManager):
    """server_manager.py:14-45 + server.py:40-72: owns the upper net,
    answers every train activation with gradients, accumulates validation
    stats, rotates the active node on validation-over."""

    def __init__(self, compute: SplitServerCompute, params, opt_state,
                 max_rank: int, rank: int = 0, backend: str = "INPROC",
                 grad_transport: Optional[str] = None, **kw):
        """grad_transport: the downlink twin of the client's
        act_transport — opt-in lossy wire dtype for the per-batch
        activation-gradient reply (wire codec v2); None = exact."""
        super().__init__(rank, max_rank + 1, backend, **kw)
        self.grad_transport = grad_transport
        self.compute = compute
        self.params, self.opt_state = params, opt_state
        self.max_rank = max_rank
        self.active_node = 1
        self.phase = "train"
        self.epoch = 0
        self._reset_stats()
        self.val_history: list[dict] = []
        self.done = threading.Event()

    def _reset_stats(self):
        self.total = 0.0
        self.correct = 0.0
        self.val_loss_sum = 0.0
        self.step = 0

    def register_message_receive_handlers(self):
        M = SplitNNMessage
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_SEND_ACTS, self.handle_acts)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_VALIDATION_MODE, self.handle_validation_mode)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_VALIDATION_OVER, self.handle_validation_over)
        self.register_message_receive_handler(
            M.MSG_TYPE_C2S_PROTOCOL_FINISHED, self.handle_finish)

    def handle_acts(self, msg: Message):
        acts = msg.get(SplitNNMessage.MSG_ARG_KEY_ACTS)
        y = msg.get(SplitNNMessage.MSG_ARG_KEY_LABELS)
        mask = msg.get(SplitNNMessage.MSG_ARG_KEY_MASK)
        # per-message phase (see client): ordering-independent branch
        phase = msg.get(SplitNNMessage.MSG_ARG_KEY_PHASE, self.phase)
        if phase == "train":
            (self.params, self.opt_state, ga, loss, correct,
             count) = self.compute.train_step(self.params, self.opt_state,
                                              acts, y, mask)
            reply = Message(SplitNNMessage.MSG_TYPE_S2C_GRADS, self.rank,
                            msg.get_sender_id())
            reply.add_params(SplitNNMessage.MSG_ARG_KEY_GRADS,
                             np.asarray(ga))
            if self.grad_transport:
                reply.set_wire_transport(SplitNNMessage.MSG_ARG_KEY_GRADS,
                                         self.grad_transport)
            self.send_message(reply)
            # a train batch reordered past a VALIDATION_MODE reset must not
            # pollute the validation accumulators
            if self.phase == "train":
                self.correct += float(correct)
                self.total += float(count)
                self.step += 1
        else:
            loss, correct, count = self.compute.eval_step(
                self.params, acts, y, mask)
            self.val_loss_sum += float(loss)
            self.correct += float(correct)
            self.total += float(count)
            self.step += 1

    def handle_validation_mode(self, _msg: Message):
        self.phase = "validation"
        self._reset_stats()

    def handle_validation_over(self, _msg: Message):
        """server.py:62-72 validation_over: record stats, rotate the active
        node, back to train mode."""
        acc = self.correct / max(self.total, 1.0)
        self.val_history.append({
            "epoch": self.epoch, "val_acc": acc,
            "val_loss": self.val_loss_sum / max(self.step, 1),
            "active_node": self.active_node})
        log.info("splitnn epoch %d: val_acc=%.4f (node %d)", self.epoch,
                 acc, self.active_node)
        self.epoch += 1
        self.active_node = (self.active_node % self.max_rank) + 1
        self.phase = "train"
        self._reset_stats()

    def handle_finish(self, _msg: Message):
        self.done.set()
        self.finish()
