"""Message envelope + wire codecs.

Parity: fedml_core/distributed/communication/message.py:5-74 — a typed
key→value bag with sender/receiver ids and JSON serialization.  The
reference JSON-encodes model weights as nested Python lists on the mobile
path (fedml_api/distributed/fedavg/utils.py:7-16) and pickles state dicts
through MPI otherwise; here the default codec is a compact self-describing
binary frame (JSON header + raw little-endian array buffers) that carries
jax/numpy pytrees zero-copy, and `to_json` keeps the mobile-parity list
form.
"""
from __future__ import annotations

import io
import json
from typing import Any

import numpy as np


class Message:
    """Typed message with params; mirrors the reference's constant names."""

    MSG_ARG_KEY_OPERATION = "operation"
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_OPERATION_SEND = "send"
    MSG_OPERATION_RECEIVE = "receive"
    MSG_OPERATION_BROADCAST = "broadcast"
    MSG_OPERATION_REDUCE = "reduce"

    def __init__(self, type: Any = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.type = type
        self.sender_id = sender_id
        self.receiver_id = receiver_id
        self.msg_params: dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # -- reference API (message.py:23-61) -----------------------------------
    def init(self, msg_params):
        self.msg_params = dict(msg_params)
        self.type = self.msg_params.get(Message.MSG_ARG_KEY_TYPE)
        self.sender_id = self.msg_params.get(Message.MSG_ARG_KEY_SENDER, 0)
        self.receiver_id = self.msg_params.get(Message.MSG_ARG_KEY_RECEIVER, 0)
        return self

    def get_sender_id(self) -> int:
        return int(self.msg_params[Message.MSG_ARG_KEY_SENDER])

    def get_receiver_id(self) -> int:
        return int(self.msg_params[Message.MSG_ARG_KEY_RECEIVER])

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def add(self, key: str, value: Any) -> None:
        self.add_params(key, value)

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def get_params(self) -> dict:
        return self.msg_params

    def get_type(self):
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def to_string(self) -> str:
        return (f"Message(type={self.type}, sender={self.sender_id}, "
                f"receiver={self.receiver_id}, "
                f"keys={sorted(self.msg_params)})")

    __repr__ = to_string

    # -- mobile-parity JSON (lists) -----------------------------------------
    def to_json(self) -> str:
        """JSON with ndarray/pytree leaves as nested lists (the reference's
        --is_mobile transform, fedavg/utils.py:7-16, applied at the
        envelope instead of per call site)."""
        def conv(v):
            if isinstance(v, np.ndarray):
                return v.tolist()
            if hasattr(v, "__array__") and not isinstance(v, (int, float,
                                                              bool, str)):
                return np.asarray(v).tolist()
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [conv(x) for x in v]
            return v
        return json.dumps({k: conv(v) for k, v in self.msg_params.items()})

    @classmethod
    def from_json(cls, payload: str) -> "Message":
        return cls().init(json.loads(payload))


class MessageCodec:
    """Binary wire format: 4-byte header length ‖ JSON header ‖ buffers.

    Pytree leaves that are numpy/jax arrays are flattened into contiguous
    little-endian buffers referenced from the header by (path, dtype,
    shape, offset).  Everything else must be JSON-serializable.
    """

    MAGIC = b"FML1"

    @staticmethod
    def _flatten(obj, path, arrays, meta):
        if isinstance(obj, dict):
            return {k: MessageCodec._flatten(v, f"{path}/{k}", arrays, meta)
                    for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [MessageCodec._flatten(v, f"{path}/{i}", arrays, meta)
                   for i, v in enumerate(obj)]
            return out if isinstance(obj, list) else {"__tuple__": out}
        if isinstance(obj, np.ndarray) or (
                hasattr(obj, "__array__")
                and not isinstance(obj, (int, float, bool, str, bytes))):
            a = np.ascontiguousarray(np.asarray(obj))
            ref = len(arrays)
            arrays.append(a)
            meta.append({"dtype": str(a.dtype), "shape": list(a.shape)})
            return {"__array__": ref}
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        return obj

    @staticmethod
    def _unflatten(obj, buffers):
        if isinstance(obj, dict):
            if "__array__" in obj and len(obj) == 1:
                return buffers[obj["__array__"]]
            if "__tuple__" in obj and len(obj) == 1:
                return tuple(MessageCodec._unflatten(v, buffers)
                             for v in obj["__tuple__"])
            return {k: MessageCodec._unflatten(v, buffers)
                    for k, v in obj.items()}
        if isinstance(obj, list):
            return [MessageCodec._unflatten(v, buffers) for v in obj]
        return obj

    @classmethod
    def encode(cls, msg: Message) -> bytes:
        arrays: list[np.ndarray] = []
        meta: list[dict] = []
        tree = cls._flatten(msg.msg_params, "", arrays, meta)
        header = json.dumps({"tree": tree, "arrays": meta}).encode()
        out = io.BytesIO()
        out.write(cls.MAGIC)
        out.write(len(header).to_bytes(8, "little"))
        out.write(header)
        for a in arrays:
            out.write(a.tobytes())
        return out.getvalue()

    @classmethod
    def decode(cls, payload: bytes) -> Message:
        assert payload[:4] == cls.MAGIC, "bad frame magic"
        hlen = int.from_bytes(payload[4:12], "little")
        header = json.loads(payload[12:12 + hlen].decode())
        off = 12 + hlen
        buffers = []
        for m in header["arrays"]:
            dt = np.dtype(m["dtype"])
            count = int(np.prod(m["shape"], dtype=np.int64)) if m["shape"] else 1
            nbytes = count * dt.itemsize
            a = np.frombuffer(payload, dtype=dt, count=count,
                              offset=off).reshape(m["shape"])
            buffers.append(a)
            off += nbytes
        params = cls._unflatten(header["tree"], buffers)
        return Message().init(params)
