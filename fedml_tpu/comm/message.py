"""Message envelope + wire codecs.

Parity: fedml_core/distributed/communication/message.py:5-74 — a typed
key→value bag with sender/receiver ids and JSON serialization.  The
reference JSON-encodes model weights as nested Python lists on the mobile
path (fedml_api/distributed/fedavg/utils.py:7-16) and pickles state dicts
through MPI otherwise; here the default codec is a compact self-describing
binary frame (JSON header + raw little-endian array buffers) that carries
jax/numpy pytrees zero-copy, and `to_json` keeps the mobile-parity list
form.

Wire codec v2 (transfer-compression layer): the FedAvg round's dominant
wire cost is raw f32 model buffers (the reference pays the same cost
through MPI pickles/JSON — FedML arXiv:2007.13518; the Smart-NIC FL
study arXiv:2307.06561 shows server-side comm handling dominating round
latency at scale).  v2 adds, all OPT-IN per message key:

* per-array transport dtypes — f32→bf16 (2x) or int8 + per-tensor
  affine scale (4x) on the wire, restored to the original dtype on
  decode.  Aggregation-critical payloads simply stay un-opted (exact,
  bitwise round trip);
* sparse_topk (ISSUE 19): only the k = max(1, n // SPARSE_TOPK_RATIO)
  largest-|value| entries of a float array ship, as u32 idx[k] ‖ f32
  val[k] in one u8 wire blob (~8x fewer bytes at the default ratio 16,
  LOSSY — pair it with client-side error feedback when the sum over
  rounds matters).  decode() densifies; decode_into() scatters the
  pairs straight into the preallocated flat row; decode_sparse()
  returns the (global-index, value) pairs without ever densifying, for
  the streaming sparse fold (async_/staleness.make_sparse_fold_fn);
* zlib compression of the header + small-array section;
* a chunked streaming encoder (`encode_parts`) that hands the frame to
  the socket as a prefix + per-buffer parts instead of materializing
  the whole frame through `BytesIO.getvalue()`.

Frames with no v2 feature active still encode as v1 ("FML1") — decode
accepts both magics, so v2-aware peers interoperate with v1 frames in
either direction.  FEDML_WIRE_V1=1 is the escape hatch: it forces v1
frames (features ignored) process-wide, mirroring `--no_prefetch`.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Any, Optional

import numpy as np

from fedml_tpu import obs

# the v2 per-array lossy wire transports this build can encode AND
# decode — named in the version-skew rejection so an old server tells
# the operator WHICH codec it is missing instead of dying in a thread.
# "secagg" is special: not lossy but OPAQUE — masked fixed-point field
# words (ISSUE 20) that only the secure commit barrier can turn back
# into floats, so plain decode hands the raw words through and
# decode_into refuses them by name.
WIRE_TRANSPORTS = ("bf16", "int8", "sparse_topk", "secagg")

# ship 1-in-16 entries on the sparse_topk wire (8 B per kept entry):
# matches the carry tier's DEFAULT_TOPK_RATIO (parallel/carry_codec.py
# imports from this module, so the constant lives here un-shared)
SPARSE_TOPK_RATIO = 16


class Message:
    """Typed message with params; mirrors the reference's constant names."""

    MSG_ARG_KEY_OPERATION = "operation"
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_OPERATION_SEND = "send"
    MSG_OPERATION_RECEIVE = "receive"
    MSG_OPERATION_BROADCAST = "broadcast"
    MSG_OPERATION_REDUCE = "reduce"

    def __init__(self, type: Any = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.type = type
        self.sender_id = sender_id
        self.receiver_id = receiver_id
        # send-side wire hints (NOT serialized; decode never restores
        # them): per-key transport dtypes + frame compression, consumed
        # by MessageCodec.encode_parts.  Default empty/off = v1 frame,
        # bitwise-exact arrays.
        self.wire_transport: dict[str, str] = {}
        self.wire_transport_meta: dict[str, dict] = {}
        self.wire_compress: bool = False
        self.msg_params: dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    def set_wire_transport(self, key: str, kind: Optional[str],
                           **meta) -> None:
        """Opt this message key's float arrays into a lossy wire dtype:
        "bf16" (2x), "int8" (4x, per-tensor affine scale), or
        "sparse_topk" (~8x, top-k index/value pairs — ISSUE 19).
        None/"none" clears the opt-in.  Keys never opted in ride exact
        — keep aggregation-critical payloads (e.g. model averages) that
        way unless the caller accepts the precision tradeoff.

        "secagg" (ISSUE 20) marks the key's array as MASKED fixed-point
        field words; it requires `scale=` and `p=` meta kwargs because
        the codec cannot recover the quantization parameters from
        masked words — they ride in the frame's enc header (the affine
        header shape) so the unmask barrier is self-describing."""
        if kind in (None, "none"):
            self.wire_transport.pop(key, None)
            self.wire_transport_meta.pop(key, None)
            return
        if kind not in WIRE_TRANSPORTS:
            raise ValueError(f"unknown wire transport {kind!r} "
                             f"(choose one of {WIRE_TRANSPORTS})")
        if kind == "secagg" and not {"scale", "p"} <= set(meta):
            raise ValueError(
                "secagg transport needs scale= and p= meta (the codec "
                "cannot infer quantization parameters from masked words)")
        self.wire_transport[key] = kind
        if meta:
            self.wire_transport_meta[key] = dict(meta)

    # -- reference API (message.py:23-61) -----------------------------------
    def init(self, msg_params):
        self.msg_params = dict(msg_params)
        self.type = self.msg_params.get(Message.MSG_ARG_KEY_TYPE)
        self.sender_id = self.msg_params.get(Message.MSG_ARG_KEY_SENDER, 0)
        self.receiver_id = self.msg_params.get(Message.MSG_ARG_KEY_RECEIVER, 0)
        return self

    def get_sender_id(self) -> int:
        return int(self.msg_params[Message.MSG_ARG_KEY_SENDER])

    def get_receiver_id(self) -> int:
        return int(self.msg_params[Message.MSG_ARG_KEY_RECEIVER])

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def add(self, key: str, value: Any) -> None:
        self.add_params(key, value)

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def get_params(self) -> dict:
        return self.msg_params

    def get_type(self):
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def to_string(self) -> str:
        return (f"Message(type={self.type}, sender={self.sender_id}, "
                f"receiver={self.receiver_id}, "
                f"keys={sorted(self.msg_params)})")

    __repr__ = to_string

    # -- mobile-parity JSON (lists) -----------------------------------------
    def to_json(self) -> str:
        """JSON with ndarray/pytree leaves as nested lists (the reference's
        --is_mobile transform, fedavg/utils.py:7-16, applied at the
        envelope instead of per call site)."""
        def conv(v):
            if isinstance(v, np.ndarray):
                return v.tolist()
            if hasattr(v, "__array__") and not isinstance(v, (int, float,
                                                              bool, str)):
                return np.asarray(v).tolist()
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [conv(x) for x in v]
            return v
        return json.dumps({k: conv(v) for k, v in self.msg_params.items()})

    @classmethod
    def from_json(cls, payload: str) -> "Message":
        return cls().init(json.loads(payload))


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, reaching through ml_dtypes for the extension
    dtypes plain numpy rejects (bfloat16 leaves arrive whenever a jax
    bf16 array rides a Message)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise TypeError(f"undecodable array dtype {name!r}") from None


def _bf16_dtype() -> np.dtype:
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)


# -- the v2 int8/affine fixed-point discipline -------------------------------
# Shared by the per-tensor wire transport below AND the carry codec
# (parallel/carry_codec.py): ONE definition of the quantization math so
# the dequant prologue on every consumer agrees bit-for-bit with the
# encoder.  scale/min may be scalars (per-tensor) or arrays broadcast
# per element (per-chunk).

def affine_int8_scale(mn, mx):
    """Affine scale for [mn, mx] → 255 int8 steps; 1.0 for a degenerate
    (constant) range so encode/decode stay finite."""
    return (mx - mn) / 255.0 or 1.0


def affine_int8_encode(a: np.ndarray, mn, scale) -> np.ndarray:
    """q = round((x - min)/scale) - 128, clipped to int8 — f64 math so
    every host quantizes identically regardless of simd path."""
    return np.clip(np.rint((a.astype(np.float64) - mn) / scale) - 128,
                   -128, 127).astype(np.int8)


def affine_int8_decode(q: np.ndarray, mn, scale, dtype=np.float32):
    """Exact inverse placement: x̂ = (q + 128)·scale + min, f64 math."""
    return ((q.astype(np.float64) + 128.0) * scale + mn).astype(dtype)


class MessageCodec:
    """Binary wire format: magic ‖ header length ‖ JSON header ‖ buffers.

    Pytree leaves that are numpy/jax arrays are flattened into contiguous
    little-endian buffers referenced from the header by (path, dtype,
    shape, offset).  Everything else must be JSON-serializable.

    v1 ("FML1"): 4B magic ‖ u64 LE header length ‖ JSON header ‖ raw
    buffers, in array order.

    v2 ("FML2"): 4B magic ‖ 1B flags ‖ u64 LE head length ‖ head ‖ big
    buffers.  `head` is (zlib-compressed iff flags&1): u64 LE JSON
    length ‖ JSON header ‖ small-array buffers (arrays ≤ SMALL_LIMIT
    bytes ride inside the head so header+small arrays compress
    together).  Array meta may carry an "enc" record describing a lossy
    transport dtype ({"kind": "bf16"|"int8", "orig": dtype[, "scale",
    "min"]}); decode restores the original dtype.  encode emits v1
    whenever no v2 feature is active, so default traffic stays
    byte-identical with older peers; decode accepts both magics.
    """

    MAGIC = b"FML1"
    MAGIC_V2 = b"FML2"
    FLAG_ZLIB = 0x01
    SMALL_LIMIT = 1024          # arrays ≤ this ride in the head section
    ENV_FORCE_V1 = "FEDML_WIRE_V1"   # escape hatch: ignore v2 features

    @staticmethod
    def _flatten(obj, path, arrays, meta, paths):
        if isinstance(obj, dict):
            return {k: MessageCodec._flatten(v, f"{path}/{k}", arrays,
                                             meta, paths)
                    for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            out = [MessageCodec._flatten(v, f"{path}/{i}", arrays, meta,
                                         paths)
                   for i, v in enumerate(obj)]
            return out if isinstance(obj, list) else {"__tuple__": out}
        if isinstance(obj, np.ndarray) or (
                hasattr(obj, "__array__")
                and not isinstance(obj, (int, float, bool, str, bytes))):
            a = np.ascontiguousarray(np.asarray(obj))
            ref = len(arrays)
            arrays.append(a)
            meta.append({"dtype": str(a.dtype), "shape": list(a.shape)})
            paths.append(path)
            return {"__array__": ref}
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        return obj

    @staticmethod
    def _unflatten(obj, buffers):
        if isinstance(obj, dict):
            if "__array__" in obj and len(obj) == 1:
                return buffers[obj["__array__"]]
            if "__tuple__" in obj and len(obj) == 1:
                return tuple(MessageCodec._unflatten(v, buffers)
                             for v in obj["__tuple__"])
            return {k: MessageCodec._unflatten(v, buffers)
                    for k, v in obj.items()}
        if isinstance(obj, list):
            return [MessageCodec._unflatten(v, buffers) for v in obj]
        return obj

    # -- transport dtypes ----------------------------------------------------
    @staticmethod
    def _transport_kind(path: str, transport: dict) -> Optional[str]:
        for key, kind in transport.items():
            pre = "/" + key
            if path == pre or path.startswith(pre + "/"):
                return kind
        return None

    @staticmethod
    def _encode_transport(a: np.ndarray, kind: str, m: dict,
                          extra: Optional[dict] = None) -> np.ndarray:
        """Lossy wire encoding of one float array; updates its meta
        record in place.  Non-float (and non-finite int8 candidates)
        stay exact — a silent fallback beats a corrupt quantization."""
        if kind == "secagg":
            # masked field words (uint32 residues mod p, ISSUE 20): the
            # payload is already its own wire form — pass through and
            # stamp the self-describing enc header.  This branch MUST
            # precede the float guard: the array is integer by design.
            if not extra or not {"scale", "p"} <= set(extra):
                raise ValueError(
                    "secagg transport needs scale=/p= meta from "
                    "set_wire_transport (unrecoverable from masked words)")
            w = np.ascontiguousarray(a, np.uint32)
            m["dtype"] = "uint32"
            m["shape"] = list(w.shape)
            m["enc"] = {"kind": "secagg", "orig": str(a.dtype),
                        "oshape": list(a.shape),
                        "scale": int(extra["scale"]), "p": int(extra["p"])}
            return w
        if not np.issubdtype(a.dtype, np.floating):
            return a
        if kind == "bf16":
            if a.dtype == _bf16_dtype():
                return a                       # already bf16 on the wire
            w = a.astype(_bf16_dtype())
            m["dtype"] = str(w.dtype)
            m["enc"] = {"kind": "bf16", "orig": str(a.dtype)}
            return w
        if kind == "sparse_topk":
            # top-k magnitude pairs: u32 idx[k] ‖ f32 val[k] in one u8
            # blob.  Index-sorted so the wire form is deterministic.
            if a.size == 0 or not np.all(np.isfinite(a)):
                return a
            flat = np.ascontiguousarray(a, dtype=np.float32).ravel()
            k = max(1, flat.size // SPARSE_TOPK_RATIO)
            if k >= flat.size:
                return a               # nothing to drop; ride exact
            sel = np.argpartition(np.abs(flat), flat.size - k)[-k:]
            sel = np.sort(sel).astype("<u4")
            w = np.frombuffer(
                sel.tobytes() + flat[sel].astype("<f4").tobytes(),
                dtype=np.uint8)
            m["dtype"] = "uint8"
            m["shape"] = [int(w.size)]
            m["enc"] = {"kind": "sparse_topk", "orig": str(a.dtype),
                        "oshape": list(a.shape), "k": int(k)}
            return w
        # int8 + per-tensor affine: q = round((x - min)/scale) - 128
        if a.size == 0 or not np.all(np.isfinite(a)):
            return a
        mn = float(np.min(a))
        mx = float(np.max(a))
        scale = affine_int8_scale(mn, mx)
        q = affine_int8_encode(a, mn, scale)
        m["dtype"] = "int8"
        m["enc"] = {"kind": "int8", "orig": str(a.dtype),
                    "scale": scale, "min": mn}
        return q

    @staticmethod
    def _sparse_pairs(a: np.ndarray, enc: dict):
        """(idx u32[k], vals f32[k]) views of one sparse_topk wire blob."""
        k = int(enc["k"])
        blob = np.ascontiguousarray(a, dtype=np.uint8)
        if blob.size != 8 * k:
            raise ValueError(
                f"sparse_topk blob is {blob.size} B, k={k} needs {8 * k}")
        idx = blob[:4 * k].view("<u4")
        vals = blob[4 * k:].view("<f4")
        return idx, vals

    @staticmethod
    def _decode_transport(a: np.ndarray, enc: Optional[dict]) -> np.ndarray:
        if not enc:
            return a
        orig = _np_dtype(enc.get("orig", "float32"))
        if enc["kind"] == "bf16":
            return a.astype(orig)
        if enc["kind"] == "int8":
            return affine_int8_decode(a, enc["min"], enc["scale"], orig)
        if enc["kind"] == "secagg":
            # masked fixed-point words CANNOT be dequantized per-array —
            # the pairwise masks only cancel in the cohort SUM.  Hand
            # the raw u32 residues through (fresh, mutable copy to keep
            # decode's leaf contract); the secure server unmasks at the
            # commit barrier (fedml_tpu/secure), every other consumer
            # quarantines the uplink by its secagg marker.
            return np.array(a, dtype=np.uint32)
        if enc["kind"] == "sparse_topk":
            idx, vals = MessageCodec._sparse_pairs(a, enc)
            oshape = tuple(enc.get("oshape", ()))
            n = int(np.prod(oshape, dtype=np.int64)) if oshape else 1
            if idx.size and int(idx.max()) >= n:
                raise ValueError(
                    f"sparse_topk index {int(idx.max())} outside "
                    f"original shape {oshape} (corrupt frame)")
            dense = np.zeros(n, dtype=np.float32)
            dense[idx] = vals
            return dense.reshape(oshape).astype(orig)
        raise ValueError(
            f"unknown wire transport encoding {enc.get('kind')!r} — "
            f"this peer decodes {list(WIRE_TRANSPORTS)}; a newer sender "
            f"(version skew)? upgrade this server or clear the sender's "
            f"set_wire_transport opt-in")

    # -- encode --------------------------------------------------------------
    @staticmethod
    def _buf(a: np.ndarray):
        """Byte view of a contiguous array for the socket — zero-copy
        when the buffer protocol allows, tobytes() otherwise (ml_dtypes
        extension formats refuse the memoryview cast)."""
        try:
            return a.data.cast("B")
        except (TypeError, ValueError, BufferError):
            return a.tobytes()

    @classmethod
    def encode_parts(cls, msg: Message) -> tuple[int, list]:
        """Chunked streaming encoder: returns (total_len, parts) where
        `parts` is a list of bytes-like objects whose concatenation is
        the frame.  Stream-capable backends (tcp) sendall() each part —
        the multi-GB frame never exists as one contiguous buffer; the
        others join.  Emits a v1 frame when no v2 feature is active (or
        FEDML_WIRE_V1=1 forces it)."""
        arrays: list[np.ndarray] = []
        meta: list[dict] = []
        paths: list[str] = []
        tree = cls._flatten(msg.msg_params, "", arrays, meta, paths)
        raw_bytes = sum(a.nbytes for a in arrays)

        force_v1 = os.environ.get(cls.ENV_FORCE_V1, "") not in ("", "0")
        transport = {} if force_v1 else getattr(msg, "wire_transport", {})
        compress = (not force_v1) and getattr(msg, "wire_compress", False)

        if transport:
            tmeta = getattr(msg, "wire_transport_meta", {})
            for i, (a, m, p) in enumerate(zip(arrays, meta, paths)):
                kind = cls._transport_kind(p, transport)
                if kind is not None:
                    arrays[i] = cls._encode_transport(
                        a, kind, m, cls._transport_kind(p, tmeta))

        if not transport and not compress:       # plain v1 frame
            header = json.dumps({"tree": tree, "arrays": meta}).encode()
            parts = [cls.MAGIC + len(header).to_bytes(8, "little")
                     + header]
            parts += [cls._buf(a) for a in arrays]
            total = sum(len(p) if isinstance(p, (bytes, bytearray))
                        else p.nbytes for p in parts)
            cls._account(raw_bytes + len(header) + 12, total)
            return total, parts

        small = [a.nbytes <= cls.SMALL_LIMIT for a in arrays]
        for m, s in zip(meta, small):
            if s:
                m["small"] = True
        header = json.dumps({"tree": tree, "arrays": meta}).encode()
        head = b"".join(
            [len(header).to_bytes(8, "little"), header]
            + [a.tobytes() for a, s in zip(arrays, small) if s])
        flags = 0
        if compress:
            head = zlib.compress(head)
            flags |= cls.FLAG_ZLIB
        parts = [cls.MAGIC_V2 + bytes([flags])
                 + len(head).to_bytes(8, "little") + head]
        parts += [cls._buf(a) for a, s in zip(arrays, small) if not s]
        total = sum(len(p) if isinstance(p, (bytes, bytearray))
                    else p.nbytes for p in parts)
        cls._account(raw_bytes + len(header) + 13, total)
        return total, parts

    @staticmethod
    def _account(raw: int, wire: int) -> None:
        """Compression accounting (always-on metrics, fedml_tpu/obs):
        raw = what the arrays+header would weigh uncompressed, wire =
        actual frame bytes; comm_compression_ratio is the cumulative
        raw/wire quotient."""
        c_raw = obs.counter("comm_raw_bytes_total")
        c_wire = obs.counter("comm_compressed_bytes_total")
        c_raw.inc(raw)
        c_wire.inc(wire)
        wired = c_wire.value
        if wired > 0:
            obs.gauge("comm_compression_ratio").set(c_raw.value / wired)

    @classmethod
    def encode(cls, msg: Message) -> bytes:
        """One contiguous frame (bytes.join accepts the memoryview
        parts directly).  Backends that need a single buffer (gRPC
        unary, native fh_send, inproc) call THIS — frame assembly has
        exactly one definition."""
        return b"".join(cls.encode_parts(msg)[1])

    # -- decode --------------------------------------------------------------
    @classmethod
    def _frame_header(cls, payload):
        """Shared v1/v2 frame parse: validates magic + lengths,
        decompresses the v2 head, and returns

            (header, small_src, small_off, big_off)

        where `header` is the JSON header dict, `small_src`/`small_off`
        locate the v2 head's small-array section (None/0 for v1), and
        `big_off` is the big-buffer section's offset into `payload`.
        Arrays then lie consecutively per section in meta order."""
        magic = bytes(payload[:4])
        if magic == cls.MAGIC:
            hoff, flags = 4, 0
        elif magic == cls.MAGIC_V2:
            hoff, flags = 5, payload[4]
        elif magic == b"FMLR":
            # a reliability envelope (comm/reliability.py) reached the
            # codec un-unwrapped — the receive chokepoint normally
            # strips it; name the layer so the misroute is debuggable
            raise ValueError(
                "bad frame magic b'FMLR': reliability envelope not "
                "unwrapped (route the frame through "
                "BaseCommManager._deliver_frame or "
                "ReliableEndpoint.on_wire before decode)")
        else:
            raise ValueError(f"bad frame magic {magic!r} (expected "
                             f"{cls.MAGIC!r} or {cls.MAGIC_V2!r})")
        if len(payload) < hoff + 8:
            raise ValueError("truncated frame: missing header length")
        hlen = int.from_bytes(payload[hoff:hoff + 8], "little")
        off = hoff + 8
        if off + hlen > len(payload):
            raise ValueError(
                f"truncated frame: header declares {hlen} bytes, payload "
                f"has {len(payload) - off} after the length field")
        if magic == cls.MAGIC:
            header = json.loads(payload[off:off + hlen].decode())
            return header, None, 0, off + hlen
        head = payload[off:off + hlen]
        if flags & cls.FLAG_ZLIB:
            try:
                head = zlib.decompress(head)
            except zlib.error as e:
                raise ValueError(f"corrupt compressed head: {e}") from None
        if len(head) < 8:
            raise ValueError("truncated frame: head too short")
        jlen = int.from_bytes(head[:8], "little")
        if 8 + jlen > len(head):
            raise ValueError("truncated frame: head JSON overruns")
        header = json.loads(head[8:8 + jlen].decode())
        return header, head, 8 + jlen, off + hlen

    @classmethod
    def _each_array(cls, header, payload, small_src, small_off, big_off):
        """Yield (index, meta, src, offset, dtype, count) for every
        array in the frame, walking the small (head) and big (payload)
        sections in meta order with bounds checks."""
        for i, m in enumerate(header["arrays"]):
            dt = _np_dtype(m["dtype"])
            count = (int(np.prod(m["shape"], dtype=np.int64))
                     if m["shape"] else 1)
            nbytes = count * dt.itemsize
            if m.get("small"):
                if small_src is None:
                    raise ValueError(
                        "corrupt frame: v1 frames have no small-array "
                        "head section but the header flags a small array")
                src, off = small_src, small_off
                small_off += nbytes
            else:
                src, off = payload, big_off
                big_off += nbytes
            if off + nbytes > len(src):
                raise ValueError(
                    f"truncated frame: array needs {nbytes} bytes at "
                    f"offset {off}, payload has {len(src)}")
            yield i, m, src, off, dt, count

    @staticmethod
    def _array_paths(tree, path="", out=None) -> dict:
        """Array ref → codec path ("/key/sub/leaf") from the header
        tree — the inverse of _flatten's path bookkeeping, so
        decode_into can place each buffer without paths on the wire."""
        if out is None:
            out = {}
        if isinstance(tree, dict):
            if "__array__" in tree and len(tree) == 1:
                out[tree["__array__"]] = path
            elif "__tuple__" in tree and len(tree) == 1:
                for i, v in enumerate(tree["__tuple__"]):
                    MessageCodec._array_paths(v, f"{path}/{i}", out)
            else:
                for k, v in tree.items():
                    MessageCodec._array_paths(v, f"{path}/{k}", out)
        elif isinstance(tree, list):
            for i, v in enumerate(tree):
                MessageCodec._array_paths(v, f"{path}/{i}", out)
        return out

    @classmethod
    def decode(cls, payload: bytes, writable: bool = True,
               copy: Optional[str] = None) -> Message:
        """Decode a v1 or v2 frame.  `writable=True` (default) copies
        each array out of the frame so leaves are mutable; False keeps
        the v1/big-buffer arrays as read-only zero-copy views into
        `payload` (cheapest, but in-place mutation raises).  The copy
        is a deliberate correctness default — np.frombuffer views blew
        up downstream mutators — at the cost of one transient extra
        copy per leaf while `payload` is still referenced.

        `copy` is the documented name for that choice: "never" is the
        zero-copy fast path (read-only views into `payload` for every
        uncompressed leaf — the async server's ingest fallback uses it
        because it re-flattens the tree immediately and never mutates),
        "always" the mutable default.  v2 small-in-head arrays are
        always fresh (the head is a transient buffer)."""
        if copy is not None:
            if copy not in ("always", "never"):
                raise ValueError(f"unknown copy mode {copy!r} "
                                 "(choose always or never)")
            writable = copy == "always"
        header, small_src, small_off, big_off = cls._frame_header(payload)
        buffers: list = [None] * len(header["arrays"])
        for i, m, src, off, dt, count in cls._each_array(
                header, payload, small_src, small_off, big_off):
            a = np.frombuffer(src, dtype=dt, count=count,
                              offset=off).reshape(m["shape"])
            if (writable or m.get("small")) and not m.get("enc"):
                # np.frombuffer views are read-only; decoded pytree
                # leaves must survive in-place mutation downstream.
                # (transport-decoded arrays are fresh already)
                a = a.copy()
            buffers[i] = cls._decode_transport(a, m.get("enc"))
        params = cls._unflatten(header["tree"], buffers)
        return Message().init(params)

    @classmethod
    def decode_into(cls, payload: bytes, out_row: np.ndarray,
                    layout) -> Message:
        """Decode-into fast path (ISSUE 6): validate the frame and write
        the `layout.key` subtree's leaves — dequantized and cast to f32
        — DIRECTLY into the preallocated flat row `out_row` at the
        layout's precomputed offsets (fedml_tpu/async_/staleness.py
        RowLayout: the flatten_vars_row element order), skipping the
        intermediate pytree and the per-leaf frombuffer copy entirely.
        One pass per leaf: a same-dtype f32 leaf is a straight memcpy
        into the row (GIL released), other dtypes cast-into, int8
        transport dequants through the same f64 affine as
        _decode_transport so the row is bitwise what
        flatten_vars_row(decode(payload)) would build.

        Every param OUTSIDE the layout key decodes normally into the
        returned Message; the layout key itself comes back as None (its
        values live in `out_row`).  Raises ValueError on malformed
        frames (decode's hardening) and on template mismatch — a frame
        whose `layout.key` arrays don't exactly tile the row.  On a
        raise, `out_row`'s contents are UNDEFINED (leaves validated
        before the failing one were already written): callers must
        treat the row as scratch until decode_into returns — which the
        ingest pool does, fully rewriting its scratch rows on every
        successful decode."""
        if (out_row.dtype != np.float32 or out_row.ndim != 1
                or out_row.shape[0] != layout.p):
            raise ValueError(
                f"decode_into row must be a [{layout.p}] f32 vector, got "
                f"{out_row.dtype}{out_row.shape}")
        header, small_src, small_off, big_off = cls._frame_header(payload)
        paths = cls._array_paths(header["tree"])
        prefix = "/" + layout.key
        buffers: list = [None] * len(header["arrays"])
        filled = 0
        for i, m, src, off, dt, count in cls._each_array(
                header, payload, small_src, small_off, big_off):
            path = paths.get(i, "")
            if path == prefix or path.startswith(prefix + "/"):
                enc = m.get("enc")
                kind = enc.get("kind") if enc else None
                if kind == "secagg":
                    # masked field words can never fill a float row —
                    # fail by NAME so a non-secure server reads this as
                    # config/version skew, not a template mismatch
                    raise ValueError(
                        f"masked secagg frame under {path!r}: "
                        f"decode_into cannot dequantize masked field "
                        f"words — secure uplinks route through "
                        f"MessageCodec.decode_secagg on a --secure_agg "
                        f"server (sender/server config or version skew)")
                if kind not in (None, "bf16", "int8", "sparse_topk"):
                    # an alien kind must fail as VERSION SKEW, not as
                    # the shape mismatch its opaque wire blob would
                    # otherwise trip below
                    raise ValueError(
                        f"unknown wire transport encoding {kind!r} — "
                        f"this peer decodes {list(WIRE_TRANSPORTS)}; a "
                        f"newer sender (version skew)? upgrade this "
                        f"server or clear the sender's "
                        f"set_wire_transport opt-in")
                ent = layout.offsets.get(path)
                if ent is None:
                    raise ValueError(
                        f"decode_into: frame array {path!r} is not in the "
                        f"row layout (model template mismatch)")
                dst_off, size, shape = ent
                sparse = kind == "sparse_topk"
                # a sparse wire array is a u8 blob — validate the
                # ORIGINAL (pre-sparsification) shape against the layout
                wire_shape = (tuple(enc.get("oshape", ()))
                              if sparse else tuple(m["shape"]))
                wire_count = (int(np.prod(wire_shape, dtype=np.int64))
                              if wire_shape else 1)
                if wire_count != size or wire_shape != shape:
                    raise ValueError(
                        f"decode_into: frame array {path!r} has shape "
                        f"{wire_shape}, layout expects {shape}")
                view = np.frombuffer(src, dtype=dt, count=count, offset=off)
                dst = out_row[dst_off:dst_off + size]
                if sparse:
                    # scatter the k (index, value) pairs straight into
                    # the flat row slot (ISSUE 19) — zero the slot
                    # first, the dropped entries mean zero
                    k = int(enc["k"])
                    if count != 8 * k:
                        raise ValueError(
                            f"decode_into: sparse_topk blob for {path!r} "
                            f"is {count} B, k={k} needs {8 * k}")
                    idx = np.frombuffer(src, dtype="<u4", count=k,
                                        offset=off)
                    vals = np.frombuffer(src, dtype="<f4", count=k,
                                         offset=off + 4 * k)
                    if k and int(idx.max()) >= size:
                        raise ValueError(
                            f"decode_into: sparse_topk index "
                            f"{int(idx.max())} outside [{size}] leaf "
                            f"{path!r} (corrupt frame)")
                    dst[:] = 0.0
                    dst[idx] = vals
                elif enc is None or enc["kind"] == "bf16":
                    # straight memcpy for f32, single-pass cast-into
                    # for f64/bf16/int leaves
                    np.copyto(dst, view, casting="unsafe")
                elif enc["kind"] == "int8":
                    # the same f64 affine as _decode_transport, so the
                    # row matches the legacy decode+flatten bitwise
                    np.copyto(dst,
                              (view.astype(np.float64) + 128.0)
                              * enc["scale"] + enc["min"],
                              casting="unsafe")
                else:
                    raise ValueError(
                        f"unknown wire transport encoding "
                        f"{enc.get('kind')!r} — this peer decodes "
                        f"{list(WIRE_TRANSPORTS)}; a newer sender "
                        f"(version skew)? upgrade this server or clear "
                        f"the sender's set_wire_transport opt-in")
                filled += size
            else:
                a = np.frombuffer(src, dtype=dt, count=count,
                                  offset=off).reshape(m["shape"])
                if not m.get("enc"):
                    a = a.copy()          # metadata arrays stay mutable
                buffers[i] = cls._decode_transport(a, m.get("enc"))
        if filled != layout.p:
            raise ValueError(
                f"decode_into: frame covered {filled} of {layout.p} row "
                f"elements under {prefix!r} (model template mismatch)")
        params = cls._unflatten(header["tree"], buffers)
        params[layout.key] = None
        return Message().init(params)

    @classmethod
    def decode_sparse(cls, payload: bytes, layout):
        """Sparse twin of decode_into (ISSUE 19): for a frame whose
        `layout.key` subtree rides ENTIRELY on the sparse_topk
        transport, return

            (msg, idx, vals)

        where `idx` (i64) / `vals` (f32) are the concatenated (global
        row index, value) pairs of every leaf — each leaf's wire
        indices shifted by its RowLayout offset — and `msg` is the
        decoded envelope with the layout key set to None.  The caller
        feeds the pairs straight to the jitted sparse fold
        (async_/staleness.make_sparse_fold_fn) so streaming
        aggregation-on-arrival never materializes the dense row on the
        host.  Raises ValueError if any layout-key leaf is NOT sparse
        (mixed/dense frame — fall back to decode_into), on template
        mismatch, and on decode's malformed-frame hardening."""
        header, small_src, small_off, big_off = cls._frame_header(payload)
        paths = cls._array_paths(header["tree"])
        prefix = "/" + layout.key
        buffers: list = [None] * len(header["arrays"])
        idx_parts: list = []
        val_parts: list = []
        covered = 0
        for i, m, src, off, dt, count in cls._each_array(
                header, payload, small_src, small_off, big_off):
            path = paths.get(i, "")
            if path == prefix or path.startswith(prefix + "/"):
                ent = layout.offsets.get(path)
                if ent is None:
                    raise ValueError(
                        f"decode_sparse: frame array {path!r} is not in "
                        f"the row layout (model template mismatch)")
                enc = m.get("enc")
                if not enc or enc.get("kind") != "sparse_topk":
                    raise ValueError(
                        f"decode_sparse: frame array {path!r} is not "
                        f"sparse_topk (mixed frame — use decode_into)")
                dst_off, size, shape = ent
                oshape = tuple(enc.get("oshape", ()))
                ocount = (int(np.prod(oshape, dtype=np.int64))
                          if oshape else 1)
                if ocount != size or oshape != shape:
                    raise ValueError(
                        f"decode_sparse: frame array {path!r} has shape "
                        f"{oshape}, layout expects {shape}")
                k = int(enc["k"])
                if count != 8 * k:
                    raise ValueError(
                        f"decode_sparse: sparse_topk blob for {path!r} "
                        f"is {count} B, k={k} needs {8 * k}")
                idx = np.frombuffer(src, dtype="<u4", count=k, offset=off)
                vals = np.frombuffer(src, dtype="<f4", count=k,
                                     offset=off + 4 * k)
                if k and int(idx.max()) >= size:
                    raise ValueError(
                        f"decode_sparse: sparse_topk index "
                        f"{int(idx.max())} outside [{size}] leaf "
                        f"{path!r} (corrupt frame)")
                idx_parts.append(idx.astype(np.int64) + dst_off)
                val_parts.append(np.asarray(vals, dtype=np.float32))
                covered += size
            else:
                a = np.frombuffer(src, dtype=dt, count=count,
                                  offset=off).reshape(m["shape"])
                if not m.get("enc"):
                    a = a.copy()          # metadata arrays stay mutable
                buffers[i] = cls._decode_transport(a, m.get("enc"))
        if covered != layout.p:
            raise ValueError(
                f"decode_sparse: frame covered {covered} of {layout.p} "
                f"row elements under {prefix!r} (model template "
                f"mismatch)")
        params = cls._unflatten(header["tree"], buffers)
        params[layout.key] = None
        gi = (np.concatenate(idx_parts) if idx_parts
              else np.zeros(0, dtype=np.int64))
        gv = (np.concatenate(val_parts) if val_parts
              else np.zeros(0, dtype=np.float32))
        return Message().init(params), gi, gv

    @classmethod
    def decode_secagg(cls, payload: bytes, key: str, n_words: int):
        """Masked twin of decode_into (ISSUE 20): for a frame whose
        `key` param is ONE transport=secagg array, return

            (msg, words, enc)

        where `words` is the masked row as a fresh u32 [n_words] copy
        (ready for the jitted field fold), `enc` its self-describing
        header ({"kind","orig","oshape","scale","p"}), and `msg` the
        decoded envelope with `key` set to None.  Raises ValueError if
        the key's array is NOT a secagg frame (plain uplink — the
        caller falls back to decode_into/decode), if the word count
        disagrees with the server's row (model template mismatch), and
        on decode's malformed-frame hardening."""
        header, small_src, small_off, big_off = cls._frame_header(payload)
        paths = cls._array_paths(header["tree"])
        prefix = "/" + key
        buffers: list = [None] * len(header["arrays"])
        words = None
        enc_out = None
        for i, m, src, off, dt, count in cls._each_array(
                header, payload, small_src, small_off, big_off):
            path = paths.get(i, "")
            if path == prefix or path.startswith(prefix + "/"):
                enc = m.get("enc")
                if not enc or enc.get("kind") != "secagg":
                    raise ValueError(
                        f"decode_secagg: frame array {path!r} is not a "
                        f"secagg frame (plain uplink — fall back to "
                        f"decode_into/decode)")
                if words is not None:
                    raise ValueError(
                        f"decode_secagg: multiple arrays under "
                        f"{prefix!r} — a secagg uplink is ONE flat row")
                if count != int(n_words):
                    raise ValueError(
                        f"decode_secagg: masked row has {count} field "
                        f"words, server layout expects {n_words} "
                        f"(model template mismatch)")
                words = np.frombuffer(
                    src, dtype=dt, count=count,
                    offset=off).astype(np.uint32, copy=True)
                enc_out = dict(enc)
            else:
                a = np.frombuffer(src, dtype=dt, count=count,
                                  offset=off).reshape(m["shape"])
                if not m.get("enc"):
                    a = a.copy()          # metadata arrays stay mutable
                buffers[i] = cls._decode_transport(a, m.get("enc"))
        if words is None:
            raise ValueError(
                f"decode_secagg: no secagg array under {prefix!r} "
                f"(plain uplink — fall back to decode_into/decode)")
        params = cls._unflatten(header["tree"], buffers)
        params[key] = None
        return Message().init(params), words, enc_out
