"""NativeTcpBackend — the C++ transport behind the comm interface.

Same wire format and constructor as the pure-Python TcpBackend (its
behavioral spec): 8-byte LE length ‖ MessageCodec frame.  Socket accept,
framing, and the inbound queue live in native threads
(fedml_tpu/native/fedml_host.cpp); Python only decodes Messages — so the
GIL never gates frame reassembly, the reference's known chokepoint (its
comm daemons are Python threads, mpi_receive_thread.py:19-28).

Falls back is the caller's job: `native_available()` says whether the
library loaded; managers select backend "NATIVE_TCP" explicitly or "TCP"
picks native automatically when present.

Reactor receive path (ISSUE 11): `reactor=True` rewires this backend's
INBOUND side onto the shared selector reactor (comm/reactor.py) — same
wire format, but with the overload-safety machinery (bounded buffers,
stall/rate eviction, load shedding, graceful drain, read-suspension
backpressure) the native drain loop cannot provide.  Outbound sends
keep the native fh_connect/fh_send fast path either way.  Default is
the native drain loop (its no-GIL frame reassembly is the point of
this backend); deployments that need overload safety over raw C++
throughput opt in per instance or via FEDML_TCP_REACTOR.
"""
from __future__ import annotations

import ctypes
import logging
import threading
import time
from typing import Optional, Union

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.comm.reactor import ReactorConfig, ReactorGroup
from fedml_tpu.comm.reliability import BackoffPolicy
from fedml_tpu.native import load_library

log = logging.getLogger(__name__)

# launch-race connect retry — the shared backoff schedule (ISSUE 8),
# bounded by the caller's retry_for deadline
_CONNECT_BACKOFF = BackoffPolicy(base_s=0.2, mult=1.5, max_s=2.0,
                                 jitter=0.2, max_attempts=1_000_000)


def native_available() -> bool:
    return load_library() is not None


class NativeTcpBackend(BaseCommManager):
    backend_name = "native_tcp"
    # fh_* peers never read their dial-out sockets (the API has no
    # in-band reply channel) — a reactor inbound path must route
    # acks/nacks through _raw_send (dial the peer's own listener), NOT
    # back over the accepted socket where they'd rot unread and every
    # enveloped frame would resend to abandonment
    reactor_inband_reply = False

    def __init__(self, rank: int, ip_config: Union[str, dict],
                 base_port: int = 52000, reactor: bool = False,
                 reactor_config: Optional[ReactorConfig] = None):
        super().__init__()
        from fedml_tpu.comm.grpc_backend import load_ip_config
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native transport unavailable (no g++?)")
        self.rank = rank
        self.ip_config = load_ip_config(ip_config)
        self.base_port = base_port
        self._conns: dict[int, int] = {}
        self._conn_lock = threading.Lock()
        self._alive = True
        from fedml_tpu.comm.reactor import reactor_default
        # FEDML_TCP_REACTOR=0 is PROCESS-WIDE (same hatch TcpBackend
        # honors): it pins the native drain loop even when a caller
        # asked for the reactor inbound path
        self.reactor_mode = bool(reactor) and reactor_default()
        self._rg: Optional[ReactorGroup] = None
        self._server = None
        self._drain = None
        if self.reactor_mode:
            # inbound over the Python reactor (overload safety:
            # eviction deadlines, rate ceilings, shed gate, drain);
            # outbound stays native fh_send.  Same 8-byte-LE-length
            # wire, so native and reactor peers interoperate.
            self._rg = ReactorGroup(
                self, ("0.0.0.0", base_port + rank), reactor_config,
                name=f"native-{rank}")
            self._rg.start()
            return
        self._server = self._lib.fh_server_create(base_port + rank)
        if not self._server:
            raise OSError(f"cannot listen on port {base_port + rank}")
        self._drain = threading.Thread(target=self._drain_loop, daemon=True)
        self._drain.start()

    def _drain_loop(self) -> None:
        buf = ctypes.POINTER(ctypes.c_ubyte)()
        length = ctypes.c_long()
        while self._alive:
            rc = self._lib.fh_recv(self._server, ctypes.byref(buf),
                                   ctypes.byref(length), 200)
            if rc == -2:          # server closed
                return
            if rc != 0:           # timeout — re-check aliveness
                continue
            try:
                payload = ctypes.string_at(buf, length.value)
            finally:
                self._lib.fh_buf_free(buf)
            self._obs_received(len(payload))
            try:
                # inline decode or the async ingest sink (comm/base.py)
                self._deliver_frame(payload)
            except Exception:     # malformed frame: drop, keep serving
                # _deliver_frame quarantines codec errors itself now;
                # anything that still lands here is an unexpected
                # delivery-path failure — counted like a thread death
                # would be (the loop survives, the signal must not hide)
                self._m_recv_deaths.inc()
                log.exception("undecodable frame (%d bytes)", length.value)

    def _connect_locked(self, receiver: int, retry_for: float = 30.0):
        c = self._conns.get(receiver)
        if c is None:
            host = self.ip_config[receiver].encode()
            # ride out the multi-process startup race (peer's listener not
            # bound yet).  This holds _conn_lock while retrying — acceptable
            # because this transport serializes sends by design (see
            # send_message) and the race only exists at launch.
            deadline = time.monotonic() + retry_for
            attempt = 0
            while True:
                c = self._lib.fh_connect(host, self.base_port + receiver)
                if c:
                    break
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"cannot reach rank {receiver} at "
                        f"{self.ip_config[receiver]}:"
                        f"{self.base_port + receiver}")
                self._obs_retry()
                attempt += 1
                time.sleep(_CONNECT_BACKOFF.delay(attempt))
            self._conns[receiver] = c
        return c

    def _send_wire_locked_retry(self, rx: int, payload: bytes) -> None:
        """connect + fh_send with the one-shot stale-handle retry, all
        under _conn_lock (see send_message)."""
        with self._conn_lock:
            conn = self._connect_locked(rx)
            if self._lib.fh_send(conn, payload, len(payload)) != 0:
                self._obs_retry()
                stale = self._conns.pop(rx, None)
                if stale is not None:
                    self._lib.fh_conn_close(stale)
                conn = self._connect_locked(rx)
                if self._lib.fh_send(conn, payload, len(payload)) != 0:
                    raise ConnectionError(f"send to rank {rx} failed")

    def _raw_send(self, receiver: int, wire: bytes) -> None:
        """Reliability transmit primitive: every native peer listens, so
        acks/resends dial the peer's own server (there is no in-band
        reply channel in the fh_* API)."""
        self._send_wire_locked_retry(receiver, bytes(wire))

    def send_message(self, msg: Message) -> None:
        # encode applies the v2 wire features (transport dtypes, zlib
        # head); fh_send frames one contiguous buffer, so the chunked
        # send stays a pure-Python-TCP feature
        if not self._stamp_frame(msg):
            return                  # chaos send gate dropped the frame
        payload = MessageCodec.encode(msg)
        rx = msg.get_receiver_id()
        if self._reliable_tx:
            wire = self._reliability_endpoint().send(rx, payload)
            self._obs_sent(len(wire))
            return
        # the whole connect+send (and the dead-connection retry) runs under
        # _conn_lock, like the pure-Python spec's sendall — so a failing
        # sender can never fh_conn_close a handle another thread is using
        self._send_wire_locked_retry(rx, payload)
        self._obs_sent(len(payload))

    def close(self) -> None:
        if not self._alive:
            return
        self._alive = False
        if self.reactor_mode:
            self._rg.close()        # drain + close every inbound socket
            with self._conn_lock:
                for c in self._conns.values():
                    self._lib.fh_conn_close(c)
                self._conns.clear()
            return
        with self._conn_lock:
            for c in self._conns.values():
                self._lib.fh_conn_close(c)
            self._conns.clear()
        # the drain thread may be inside fh_recv on the Server's condvar —
        # it must exit (≤200 ms timeout tick) BEFORE fh_server_close deletes
        # the Server, or the wait is a use-after-free.  If it hasn't exited
        # (e.g. an _on_message observer callback is wedged) the Server is
        # deliberately leaked: a leak is recoverable, a freed condvar under
        # a waiting thread is not.
        self._drain.join(timeout=5)
        if self._drain.is_alive():
            log.warning("drain thread still running after 5s; leaking "
                        "native server to avoid use-after-free")
            return
        self._lib.fh_server_close(self._server)
        self._server = None
