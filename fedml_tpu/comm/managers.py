"""ClientManager / ServerManager — the message-FSM runtime.

Parity: fedml_core/distributed/client/client_manager.py:14-79 and
server/server_manager.py:14-74 — select a backend by string, register as
observer, dispatch inbound messages through a handler dict keyed by message
type (register_message_receive_handler, client_manager.py:67-68).

Backend strings: "INPROC" (router passed via kwargs), "GRPC", "TCP"
(native C++ transport), "MQTT".  The reference's "MPI" process model has no
TPU equivalent by design — in-mesh participants use fedml_tpu/parallel/.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from fedml_tpu import obs
from fedml_tpu.comm.base import BaseCommManager, Observer
from fedml_tpu.comm.message import Message

log = logging.getLogger(__name__)


class ManagerClosedError(RuntimeError):
    """send_message on a finished manager.  Raised so wrong shutdown
    ordering fails loudly; the ONE benign case — a handler that was
    already in flight when another thread called finish() — is caught
    at the FSM dispatch chokepoint (receive_message) and degraded to a
    logged drop, matching the pre-guard behavior for that race."""


def _build_backend(backend: str, rank: int, size: int, **kw) -> BaseCommManager:
    b = backend.upper()
    if b == "INPROC":
        from fedml_tpu.comm.inproc import InProcBackend
        return InProcBackend(rank, kw["router"])
    if b == "GRPC":
        from fedml_tpu.comm.grpc_backend import GrpcBackend
        return GrpcBackend(rank, kw["ip_config"],
                           base_port=kw.get("base_port", 50000),
                           send_timeout_s=kw.get("send_timeout_s"),
                           send_backoff=kw.get("send_backoff"))
    if b == "NATIVE_TCP":
        # explicit selection may compile the library on first use
        from fedml_tpu.comm.native_tcp import NativeTcpBackend
        return NativeTcpBackend(rank, kw["ip_config"],
                                kw.get("base_port", 52000),
                                reactor=bool(kw.get("reactor", False)),
                                reactor_config=kw.get("reactor_config"))
    if b == "TCP":
        # auto-upgrade to the native transport only when the .so is already
        # built (never run a compile inside backend construction)
        from fedml_tpu.native import library_built
        if library_built() and not kw.pop("force_python_tcp", False):
            from fedml_tpu.comm.native_tcp import NativeTcpBackend
            return NativeTcpBackend(rank, kw["ip_config"],
                                    kw.get("base_port", 52000),
                                    reactor=bool(kw.get("reactor", False)),
                                    reactor_config=kw.get("reactor_config"))
        from fedml_tpu.comm.tcp_backend import TcpBackend
        # reactor=None -> the transport default (reactor unless
        # FEDML_TCP_REACTOR=0); callers pin either path explicitly —
        # the ingest torture's legacy arms force threads, the
        # connection bench forces the reactor with a tuned config
        return TcpBackend(rank, kw["ip_config"],
                          base_port=kw.get("base_port", 52000),
                          reactor=kw.get("reactor"),
                          reactor_config=kw.get("reactor_config"))
    if b == "MQTT":
        from fedml_tpu.comm.mqtt_backend import MqttBackend
        return MqttBackend(rank, size, host=kw.get("host", "127.0.0.1"),
                           port=kw.get("port", 1883),
                           client_factory=kw.get("client_factory"))
    raise ValueError(f"unknown comm backend {backend!r}")


class _Manager(Observer):
    node_type = "generic"

    def __init__(self, rank: int, size: int, backend: str = "INPROC", **kw):
        self.rank = rank
        self.size = size
        self.backend_name = backend
        self.com_manager = _build_backend(backend, rank, size, **kw)
        self.com_manager.add_observer(self)
        self.message_handler_dict: dict[object, Callable[[Message], None]] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- reference API -------------------------------------------------------
    def register_message_receive_handler(self, msg_type,
                                         handler: Callable[[Message], None]):
        self.message_handler_dict[msg_type] = handler

    def receive_message(self, msg_type, msg: Message) -> None:
        handler = self.message_handler_dict.get(msg_type)
        if handler is None:
            log.warning("%s rank %d: no handler for %r", self.node_type,
                        self.rank, msg_type)
            return
        # spans live at this chokepoint (not per backend) so every
        # transport's FSM dispatch/send shows on one timeline; the
        # byte/message counters live in the backends where frame sizes
        # are known (comm/base.py hooks)
        with obs.span("comm.handle", backend=self.backend_name,
                      node=self.node_type, rank=self.rank,
                      msg_type=str(msg_type)):
            try:
                handler(msg)
            except ManagerClosedError:
                if not self._closed:
                    raise      # a PEER's closed manager: real FSM bug
                # this manager finished while the handler was in
                # flight — its reply has nowhere to go; drop like the
                # pre-guard code did instead of killing the recv loop
                log.warning("%s rank %d: dropped handler send for %r "
                            "(manager finished mid-handler)",
                            self.node_type, self.rank, msg_type)

    def send_message(self, msg: Message) -> None:
        if self._closed:
            # loud, not silent: a send after finish() means the caller's
            # shutdown ordering is wrong (e.g. an async commit racing a
            # teardown) — dropping the frame here would surface later as
            # a peer hanging on a message that never left this process.
            # (receive_message downgrades the one benign case — a
            # handler already in flight when finish() landed.)
            raise ManagerClosedError(
                f"{self.node_type} rank {self.rank}: send_message after "
                f"finish() — the manager is closed")
        with obs.span("comm.send", backend=self.backend_name,
                      node=self.node_type, rank=self.rank,
                      msg_type=str(msg.get_type()),
                      receiver=msg.get_receiver_id()):
            self.com_manager.send_message(msg)

    def run(self) -> None:
        """Register handlers then block on the receive loop (the reference's
        run(), client_manager.py:42-45)."""
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()

    def run_async(self) -> threading.Thread:
        """Run the receive loop on a daemon thread (for in-process
        multi-rank simulations and tests)."""
        self.register_message_receive_handlers()
        self._thread = threading.Thread(
            target=self.com_manager.handle_receive_message, daemon=True)
        self._thread.start()
        return self._thread

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their FSM here."""

    def finish(self) -> None:
        """Graceful stop — the reference calls MPI.COMM_WORLD.Abort()
        (client_manager.py:70-79); we stop the loop, close the backend,
        and JOIN the run_async() receive thread (with a bounded timeout:
        a backend whose recv loop is wedged must not hang teardown
        forever — the leak is logged instead).  Idempotent, and marks
        the manager closed so late send_message calls fail loudly
        instead of racing the closed transport."""
        if self._closed:
            return
        self._closed = True
        self.com_manager.stop_receive_message()
        close = getattr(self.com_manager, "close", None)
        if close is not None:
            close()
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                log.warning(
                    "%s rank %d: receive thread still alive 10s after "
                    "finish() — backend recv loop did not stop",
                    self.node_type, self.rank)


class ClientManager(_Manager):
    node_type = "client"


class ServerManager(_Manager):
    node_type = "server"
