"""Selector/event-loop reactor transport — overload-safe live connections
(ISSUE 11).

The FedML regime (arXiv:2007.13518) is live concurrent uplinks, and the
Smart-NIC server study (arXiv:2307.06561) shows the connection layer —
not the aggregation math — is what collapses first.  The thread-per-
connection transport (one Python recv thread per peer) dies far below
the PR-10 registry's 1M-client capacity: 10k peers means 10k blocked OS
threads before the first frame decodes.  This module replaces that with
a classic reactor:

* **one `selectors`-based event loop per core** (`Reactor`), owning
  NON-BLOCKING accepted sockets with per-connection bounded read/write
  buffers and incremental frame reassembly (8-byte LE length prefix ‖
  frame — the same wire format as the thread transport, byte for byte);
* complete frames feed the backend's existing `_deliver_frame`
  chokepoint, so chaos injection (PR 8), the reliability envelope,
  trace stamping (PR 7), and the admission screen (PR 9) all ride
  UNCHANGED — the reactor is a transport swap, not a protocol change
  (a reactor-transport async run commits the same accumulator as the
  thread-per-connection run, pinned in tests/test_reactor.py);
* **backpressure as read-interest suspension**: when the decode pool or
  the bounded inbox cannot admit a frame
  (`BaseCommManager._reactor_pressure`), the reactor STOPS READING that
  peer — bytes queue in the kernel socket buffer and TCP flow control
  reaches the sender — instead of blocking a shared loop thread the
  way a blocking sink blocks a dedicated recv thread;
* **overload safety**, every degradation counted, never a silent hang:
  slow-peer (slowloris) stall eviction (a connection mid-frame with no
  progress past `stall_timeout_s` is closed), optional idle eviction,
  per-connection byte- and frame-rate ceilings (violating windows
  throttle, repeat offenders evict), a load-shedding gate that rejects
  new connections and sheds the stalest-uplink peers when the decode
  pool saturates past `shed_after_s` / RSS crosses `rss_limit_bytes` /
  an external gate trips, and graceful drain on shutdown (pending
  writes flush inside `drain_s`, then every socket closes — the FD
  audit in tests/test_reactor.py holds a 10k-churn run to zero leaks).

Known tradeoff, stated honestly: a SINK-LESS backend (the sync FSM
deployment path — no decode pool installed) decodes frames inline on
the owning loop thread, so concurrent multi-MB decodes serialize per
loop where the thread transport overlapped them across per-connection
recv threads (zlib/numpy release the GIL).  `reactors=N` spreads
connections across loops; the production ingestion path (the async
server's decode pool) never decodes on the loop at all — it is the
sink-less, many-large-concurrent-uplink corner that prefers
`reactor=False`, and the round-barrier FSM deployments that live in
that corner are latency-tolerant by construction.

Observability (the ISSUE-11 satellite): `comm_open_connections` gauge,
`comm_connections_evicted_total{reason=stall|rate|shed|idle|protocol|
error}`,
`comm_uplinks_shed_total`, `comm_connections_drained_total`,
`comm_accept_fd_exhausted_total`, a `reactor_loop_lag_seconds`
histogram on the sub-ms decode ladder, and `reactor.*` spans/instants
feeding the PR-7 timeline's "reactor" stage.
"""
from __future__ import annotations

import dataclasses
import errno
import itertools
import logging
import os
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Optional

from fedml_tpu import obs

log = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
_RECV_CHUNK = 1 << 18            # 256 KiB per readable event per conn

ENV_REACTOR = "FEDML_TCP_REACTOR"    # "0" = thread-per-connection escape


def reactor_default() -> bool:
    """Process-wide default transport choice: the reactor, unless
    FEDML_TCP_REACTOR=0 pins the legacy thread-per-connection path
    (the same escape-hatch stance as FEDML_WIRE_V1/FEDML_RELIABLE)."""
    return os.environ.get(ENV_REACTOR, "") != "0"


def fd_limit() -> tuple[int, int]:
    """(soft, hard) RLIMIT_NOFILE — the `ulimit -n` every FD-exhaustion
    message must name."""
    import resource
    return resource.getrlimit(resource.RLIMIT_NOFILE)


def open_fd_count() -> int:
    """Open descriptors of this process (-1 where /proc is absent) —
    the churn test's leak probe."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


class FdExhaustionError(OSError):
    """accept(2) failed with EMFILE/ENFILE: the process (or system) is
    out of file descriptors.  Named — with the current `ulimit -n` in
    the message — so the operator sees "raise the fd limit or shed
    load", not a bare OSError that killed the listener."""


def accept_exhaustion(exc: OSError) -> Optional[FdExhaustionError]:
    """Translate an accept(2) OSError into the named FD-exhaustion
    error (None when it is some other failure).  The reactor logs the
    translated error and keeps the listener ALIVE with a short accept
    backoff; the thread transport's accept loop does the same — under
    no circumstance does fd pressure silently end accepting."""
    if exc.errno in (errno.EMFILE, errno.ENFILE):
        soft, hard = fd_limit()
        return FdExhaustionError(
            exc.errno,
            f"accept failed: file descriptors exhausted "
            f"(ulimit -n: soft={soft} hard={hard}) — raise the limit or "
            f"let the shed gate cap connections")
    return None


@dataclasses.dataclass
class ReactorConfig:
    """Overload-safety knobs of one reactor group (one listening
    backend).  Defaults are permissive — existing deployments behave
    like the thread transport did; the connection bench and the CLI
    tighten them."""
    reactors: int = 1                 # event loops (≈ one per core)
    max_connections: int = 16384      # inbound admission ceiling
    max_frame_bytes: int = 1 << 30    # oversized length prefix = protocol evict
    read_buffer: int = 4 << 20        # unparsed inbound bytes beyond which
    #                                   reads pause (a frame may exceed it;
    #                                   the bound then is frame + one chunk)
    write_buffer: int = 8 << 20       # pending outbound cap — a peer that
    #                                   won't read its acks past this is a
    #                                   slow reader and evicts as a stall
    stall_timeout_s: Optional[float] = 30.0   # mid-frame no-progress evict
    idle_timeout_s: Optional[float] = None    # fully-idle evict (opt-in)
    max_bytes_per_sec: Optional[float] = None   # per-conn ceilings; a
    max_frames_per_sec: Optional[float] = None  # violating window throttles
    rate_violation_limit: int = 3     # consecutive violating windows -> evict
    shed_on_pressure: bool = False    # decode-pool pressure sustained past
    shed_after_s: float = 1.0         # shed_after_s trips the shed gate
    shed_batch: int = 8               # conns shed per housekeeping pass
    rss_limit_bytes: Optional[int] = None     # memory watermark gate
    drain_s: float = 2.0              # graceful-drain budget at close()
    tick_s: float = 0.05              # loop wakeup when idle
    housekeep_s: float = 0.25         # eviction/resume scan cadence

    def __post_init__(self):
        if self.reactors < 1:
            raise ValueError(f"reactors must be >= 1, got {self.reactors}")
        if self.max_connections < 1:
            raise ValueError("max_connections must be >= 1")


class _Conn:
    """Per-connection reactor state: reassembly buffer, bounded write
    queue, rate window, and the activity clocks the eviction deadlines
    read."""

    __slots__ = ("sock", "fd", "outbound", "buf", "need", "out",
                 "out_bytes", "created", "last_progress", "last_frame",
                 "last_write_progress", "win_start", "win_bytes",
                 "win_frames", "win_flagged", "violations",
                 "paused_pressure", "rate_pause_until",
                 "registered_mask", "closed")

    def __init__(self, sock: socket.socket, outbound: bool):
        now = time.monotonic()
        self.sock = sock
        self.fd = sock.fileno()
        self.outbound = outbound
        self.buf = bytearray()
        self.need: Optional[int] = None
        self.out: deque = deque()
        self.out_bytes = 0
        self.created = now
        self.last_progress = now
        self.last_frame = now
        self.last_write_progress = now
        self.win_start = now
        self.win_bytes = 0
        self.win_frames = 0
        self.win_flagged = False
        self.violations = 0
        self.paused_pressure = False
        self.rate_pause_until = 0.0
        self.registered_mask = 0
        self.closed = False


class Reactor:
    """One event loop: a selector + its thread.  All mutation of the
    selector and the conn table happens ON the loop thread — cross-
    thread callers go through `call_soon` + the wake socketpair."""

    def __init__(self, group: "ReactorGroup", idx: int):
        self.group = group
        self.idx = idx
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           ("wake", None))
        self._pending: deque = deque()
        self._plock = threading.Lock()
        self._conns: dict[int, _Conn] = {}
        # insertion-ordered (dict-as-set): the resume sweep pops FIFO
        # and a re-paused conn re-inserts at the END, so paused peers
        # genuinely rotate — a plain set iterates in fd-hash order and
        # would let the lowest-fd peer starve the rest under sustained
        # pressure
        self._pressure_paused: dict[int, None] = {}
        self._ready_hook_installed = False
        self._alive = True
        self._draining = False
        self._drain_deadline = 0.0
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"reactor-{group.name}-{idx}")

    # -- cross-thread entry points -------------------------------------------
    def call_soon(self, fn: Callable[[], None]) -> None:
        with self._plock:
            self._pending.append(fn)
        try:
            self._wake_w.send(b"\0")
        except OSError:
            pass                    # loop gone / wake buffer full: either
            #                         way the loop wakes within tick_s

    def adopt(self, sock: socket.socket, outbound: bool) -> None:
        self.call_soon(lambda: self._register(sock, outbound))

    def forget(self, sock: socket.socket) -> None:
        """Drop a socket another thread already invalidated/closed
        (the _raw_send failure path) without double-closing it."""
        fd = -1
        try:
            fd = sock.fileno()
        except OSError:
            pass
        self.call_soon(lambda: self._forget(sock, fd))

    def send(self, conn: _Conn, data: bytes) -> None:
        if threading.current_thread() is self._thread:
            self._enqueue(conn, data)
        else:
            self.call_soon(lambda: self._enqueue(conn, data))

    # -- loop ----------------------------------------------------------------
    def _run(self) -> None:
        cfg = self.group.cfg
        next_house = time.monotonic() + cfg.housekeep_s
        while self._alive:
            try:
                events = self._sel.select(timeout=cfg.tick_s)
            except OSError:
                events = []
            t0 = time.perf_counter()
            worked = bool(events)
            while True:
                with self._plock:
                    if not self._pending:
                        break
                    fn = self._pending.popleft()
                worked = True
                try:
                    fn()
                except Exception:
                    log.exception("reactor-%s-%d: pending callback failed",
                                  self.group.name, self.idx)
            for key, mask in events:
                kind, payload = key.data
                try:
                    if kind == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except OSError:
                            pass
                    elif kind == "listener":
                        self.group._on_accept(self)
                    elif kind == "conn":
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(payload)
                        if mask & selectors.EVENT_READ:
                            self._on_readable(payload)
                except Exception:
                    # the zero-recv-deaths contract: nothing that
                    # escapes a handler may kill the LOOP — count it
                    # like a thread death would have been and close
                    # only the offending connection
                    self.group.backend._m_recv_deaths.inc()
                    log.exception("reactor-%s-%d: handler died",
                                  self.group.name, self.idx)
                    if kind == "conn":
                        self._evict(payload, "error")
            now = time.monotonic()
            if now >= next_house or self._draining:
                self._housekeep(now)
                next_house = now + cfg.housekeep_s
            if worked:
                # loop lag: how long this iteration's event batch held
                # the loop (every other connection's added latency);
                # idle ticks don't observe — the ladder measures lag
                # under load, not sleep accuracy
                self.group._m_loop_lag.observe(time.perf_counter() - t0)
        self._teardown()

    # -- registration / interest ---------------------------------------------
    def _register(self, sock: socket.socket, outbound: bool) -> None:
        if not self._alive or self._draining:
            self._safe_close(sock)
            if not outbound:
                self.group._note_inbound_closed()
            return
        conn = _Conn(sock, outbound)
        try:
            self._sel.register(sock, selectors.EVENT_READ, ("conn", conn))
        except KeyError:
            # the kernel reused the fd of a socket whose forget() has
            # not drained yet: evict the stale registration by object
            # and retry once — never leak the fresh socket
            self._forget_stale_fd(conn.fd)
            try:
                self._sel.register(sock, selectors.EVENT_READ,
                                   ("conn", conn))
            except (KeyError, ValueError, OSError):
                self._safe_close(sock)
                if not outbound:
                    self.group._note_inbound_closed()
                return
        except (ValueError, OSError):
            self._safe_close(sock)
            if not outbound:
                self.group._note_inbound_closed()
            return
        conn.registered_mask = selectors.EVENT_READ
        self._conns[conn.fd] = conn

    def _forget(self, sock: socket.socket, fd: int) -> None:
        # resolve by OBJECT identity, not fd: the caller may have
        # closed the socket already (fileno() == -1) and the kernel may
        # have reused the fd for a newer conn — popping blindly by fd
        # would corrupt the table
        conn = self._conns.get(fd) if fd >= 0 else None
        if conn is None or conn.sock is not sock:
            conn = next((c for c in self._conns.values()
                         if c.sock is sock), None)
        if conn is not None:
            self._conns.pop(conn.fd, None)
            conn.closed = True
            self.group._note_close(conn)
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            pass

    def _forget_stale_fd(self, fd: int) -> None:
        """Drop a stale conn (and its selector entry) still keyed on a
        now-reused fd."""
        conn = self._conns.pop(fd, None)
        if conn is not None:
            conn.closed = True
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            self.group._note_close(conn)

    def _set_interest(self, conn: _Conn) -> None:
        if conn.closed:
            return
        mask = 0
        now = time.monotonic()
        if (not conn.paused_pressure and now >= conn.rate_pause_until
                and len(conn.buf) <= max(self.group.cfg.read_buffer,
                                         (conn.need or 0) + 8)):
            mask |= selectors.EVENT_READ
        if conn.out:
            mask |= selectors.EVENT_WRITE
        if mask == conn.registered_mask:
            return
        try:
            if mask == 0:
                self._sel.unregister(conn.sock)
            elif conn.registered_mask == 0:
                self._sel.register(conn.sock, mask, ("conn", conn))
            else:
                self._sel.modify(conn.sock, mask, ("conn", conn))
            conn.registered_mask = mask
        except (KeyError, ValueError, OSError):
            self._close(conn)

    # -- read path: reassembly + delivery ------------------------------------
    def _on_readable(self, conn: _Conn) -> None:
        if conn.closed:
            return
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            # peer closed (or half-closed its write side): deliver any
            # complete frames already buffered, then close — a shutdown
            # mid-frame drops the partial silently like a torn wire
            self._parse(conn, at_eof=True)
            self._close(conn)
            return
        conn.buf += data
        conn.last_progress = time.monotonic()
        self._parse(conn)

    def _parse(self, conn: _Conn, at_eof: bool = False) -> None:
        group = self.group
        backend = group.backend
        cfg = group.cfg
        while not conn.closed:
            if conn.need is None:
                if len(conn.buf) < 8:
                    break
                need = _LEN.unpack_from(conn.buf)[0]
                if need > cfg.max_frame_bytes:
                    log.warning(
                        "%s: peer %s declared a %d-byte frame (cap %d) — "
                        "evicting (protocol)", group.name,
                        self._peer(conn), need, cfg.max_frame_bytes)
                    self._evict(conn, "protocol")
                    return
                conn.need = need
            if len(conn.buf) < 8 + conn.need:
                break
            if not conn.outbound and backend._reactor_pressure():
                # outbound (dial-out) conns carry only reliability
                # acks, consumed before the sink — pausing them under
                # pool pressure buys no backpressure and only triggers
                # resend storms (like _rate_account, they are exempt)
                if at_eof:
                    # the peer is GONE and the pool is full: delivering
                    # would block the loop in the sink's semaphore —
                    # shed the parked frames instead, each one counted
                    # (the dropped-frames counter, the shutdown-drain
                    # precedent); an enveloped sender that reconnects
                    # resends them unacked
                    self._shed_parked(conn)
                    return
                # ISSUE-11 satellite: backpressure propagates as
                # read-interest suspension — the frame stays parked in
                # the buffer, the kernel buffer fills, TCP flow control
                # reaches the sender; the LOOP keeps serving everyone
                # else.  Housekeeping resumes the read when the decode
                # pool frees up.
                if not conn.paused_pressure:
                    conn.paused_pressure = True
                    self._pressure_paused[conn.fd] = None
                    group._note_pressure(True)
                    self._set_interest(conn)
                    if not self._ready_hook_installed:
                        # event-driven resume: the consumer pings us the
                        # moment capacity frees, so paused reads resume
                        # within one loop wakeup — the housekeeping scan
                        # is only the fallback
                        self._ready_hook_installed = True
                        backend.add_ingest_ready_hook(
                            self._ingest_ready_ping)
                return
            need = conn.need
            payload = bytes(memoryview(conn.buf)[8:8 + need])
            del conn.buf[:8 + need]
            conn.need = None
            now = time.monotonic()
            conn.last_frame = now
            conn.last_progress = now
            # no in-band reply on OUTBOUND conns: they are blocking
            # sockets whose write side belongs to the sender threads —
            # an ack enqueued from the loop could block in send() on a
            # peer that never reads (and protocol-conformant peers only
            # ever send acks down our dial-outs, which need no reply);
            # backends whose peers cannot read in-band replies at all
            # (native fh_*) opt out wholesale via reactor_inband_reply
            reply = (self._make_reply(conn)
                     if not conn.outbound
                     and getattr(backend, "reactor_inband_reply", True)
                     else None)
            backend._obs_received(len(payload))
            if not conn.outbound:
                # rate ceiling: the already-reassembled frame still
                # delivers (we have it), but a violating conn throttles
                # (reads suspend until the window rolls) or — on repeat
                # violation — evicts before its next frame
                self._rate_account(conn, now, len(payload))
            try:
                backend._deliver_frame(payload, reply=reply)
            except Exception:
                backend._m_recv_deaths.inc()
                log.exception("%s: frame delivery died (%d bytes)",
                              group.name, len(payload))
                self._evict(conn, "error")
                return

    def _shed_parked(self, conn: _Conn) -> None:
        """Count-and-discard the complete frames parked in a dead
        conn's buffer (EOF under pool pressure)."""
        backend = self.group.backend
        while len(conn.buf) >= 8:
            need = conn.need if conn.need is not None \
                else _LEN.unpack_from(conn.buf)[0]
            if len(conn.buf) < 8 + need:
                break
            del conn.buf[:8 + need]
            conn.need = None
            backend._m_dropped.inc()
        conn.buf.clear()

    def _rate_account(self, conn: _Conn, now: float, nbytes: int) -> bool:
        """Per-connection byte/frame rate ceilings over 1 s windows.
        Returns True when the conn was throttled or evicted."""
        cfg = self.group.cfg
        if cfg.max_bytes_per_sec is None and cfg.max_frames_per_sec is None:
            return False
        if now - conn.win_start >= 1.0:
            if not conn.win_flagged and conn.violations > 0:
                conn.violations -= 1      # a clean window earns one back
            conn.win_start = now
            conn.win_bytes = 0
            conn.win_frames = 0
            conn.win_flagged = False
        conn.win_bytes += nbytes
        conn.win_frames += 1
        over = ((cfg.max_bytes_per_sec is not None
                 and conn.win_bytes > cfg.max_bytes_per_sec)
                or (cfg.max_frames_per_sec is not None
                    and conn.win_frames > cfg.max_frames_per_sec))
        if not over:
            return False
        if not conn.win_flagged:
            # one violation per WINDOW, not per frame — a coalesced
            # recv batch must not burn the whole violation budget in
            # one parse pass (the documented ladder is throttle first,
            # evict after rate_violation_limit consecutive bad windows)
            conn.win_flagged = True
            conn.violations += 1
        if conn.violations >= cfg.rate_violation_limit:
            self._evict(conn, "rate")
            return True
        # throttle: no reads until the current window rolls over
        conn.rate_pause_until = conn.win_start + 1.0
        self._set_interest(conn)
        return True

    # -- write path ----------------------------------------------------------
    def _make_reply(self, conn: _Conn) -> Callable[[bytes], None]:
        """The transport's reverse channel for this connection: acks and
        nacks ride back length-prefixed over the same socket the data
        arrived on (reliability.py's reply contract)."""
        def reply(wire: bytes) -> None:
            self.send(conn, _LEN.pack(len(wire)) + bytes(wire))
        return reply

    def _enqueue(self, conn: _Conn, data: bytes) -> None:
        if conn.closed:
            return
        if conn.outbound:
            # a blocking dial-out socket cannot be written from the
            # loop (send() could block it forever); no reply callable
            # is handed out for these, so this is a programming error
            log.warning("reactor write to an outbound conn dropped "
                        "(fd=%d) — dial-out writes belong to the "
                        "sender threads", conn.fd)
            return
        if conn.out_bytes + len(data) > self.group.cfg.write_buffer:
            # a peer that will not read what we send is the write-side
            # slowloris; its pending bytes are bounded by eviction, not
            # by the heap
            log.warning("%s: write buffer overflow (%d pending) for %s — "
                        "evicting slow reader", self.group.name,
                        conn.out_bytes, self._peer(conn))
            self._evict(conn, "stall")
            return
        conn.out.append(memoryview(bytes(data)))
        conn.out_bytes += len(data)
        self._on_writable(conn)

    def _on_writable(self, conn: _Conn) -> None:
        if conn.closed:
            return
        try:
            while conn.out:
                mv = conn.out[0]
                n = conn.sock.send(mv)
                conn.out_bytes -= n
                conn.last_write_progress = time.monotonic()
                if n < len(mv):
                    conn.out[0] = mv[n:]
                    break
                conn.out.popleft()
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close(conn)
            return
        self._set_interest(conn)

    # -- housekeeping: resume / evict / shed ---------------------------------
    def _housekeep(self, now: float) -> None:
        with obs.span("reactor.housekeep", idx=self.idx,
                      conns=len(self._conns)):
            self._housekeep_inner(now)

    def _housekeep_inner(self, now: float) -> None:
        group = self.group
        cfg = group.cfg
        if self._draining:
            done = all(not c.out for c in self._conns.values())
            if done or now >= self._drain_deadline:
                for conn in list(self._conns.values()):
                    group._m_drained.inc()
                    self._close(conn)
                self._alive = False
            return
        if self._pressure_paused:
            self._resume_paused()          # fallback sweep
        for conn in list(self._conns.values()):
            if conn.rate_pause_until and now >= conn.rate_pause_until:
                conn.rate_pause_until = 0.0
                self._set_interest(conn)
            if conn.closed or conn.outbound:
                continue
            stalled_read = (conn.need is not None or len(conn.buf) > 0)
            if (cfg.stall_timeout_s is not None and stalled_read
                    and not conn.paused_pressure
                    and now - conn.last_progress > cfg.stall_timeout_s):
                # slowloris: a header or partial frame is pending and
                # the peer has fed us nothing for the whole deadline
                self._evict(conn, "stall")
                continue
            if (cfg.stall_timeout_s is not None and conn.out
                    and now - conn.last_write_progress
                    > cfg.stall_timeout_s):
                self._evict(conn, "stall")
                continue
            if (cfg.idle_timeout_s is not None
                    and now - max(conn.last_frame, conn.created)
                    > cfg.idle_timeout_s):
                # distinct reason: opt-in idle reaping must not pollute
                # the slowloris (mid-frame stall) signal in an incident
                self._evict(conn, "idle")
        if group._overloaded(now):
            self._shed(now)
        if self.idx == 0:
            group._maybe_resume_listener(now)

    def _ingest_ready_ping(self) -> None:
        """The consumer's capacity-freed wakeup.  Fires on EVERY decode-
        task completion once installed, so the empty-paused fast path
        must cost one attribute read — no lock, no wake syscall —
        or the hook would tax the whole steady-state hot path forever
        after one transient pressure episode."""
        if self._pressure_paused and self._alive:
            self.call_soon(self._resume_paused)

    def _resume_paused(self) -> None:
        """Resume every pressure-paused conn while capacity holds —
        parse order round-robins so one chatty peer cannot starve the
        rest of the paused set."""
        if not self._pressure_paused or self._draining:
            return
        if self.group.backend._reactor_pressure():
            return                    # still full; the next ready ping
            #                           (or housekeeping) retries
        self.group._note_pressure(False)
        for fd in list(self._pressure_paused):
            conn = self._conns.get(fd)
            self._pressure_paused.pop(fd, None)
            if conn is None or conn.closed:
                continue
            conn.paused_pressure = False
            self._set_interest(conn)
            self._parse(conn)         # frames parked in the buffer
            # re-evaluate interest AFTER the parse drained the buffer:
            # a parked frame larger than read_buffer failed the read-
            # mask bound before the drain, and leaving READ off would
            # starve a healthy peer into a bogus stall eviction
            self._set_interest(conn)
            if conn.paused_pressure:
                break                 # refilled mid-sweep; rest stay paused

    def _shed(self, now: float) -> None:
        """Shed the lowest-priority uplinks: staleness-ranked — the
        inbound conns whose last completed frame is OLDEST (their
        uplinks are the stalest) go first."""
        ranked = sorted(
            (c for c in self._conns.values()
             if not c.outbound and not c.closed),
            key=lambda c: c.last_frame)
        for conn in ranked[:self.group.cfg.shed_batch]:
            self.group._m_shed.inc()
            self._evict(conn, "shed")

    # -- teardown ------------------------------------------------------------
    def begin_drain(self, deadline: float) -> None:
        def _start():
            self._draining = True
            self._drain_deadline = deadline
            for conn in list(self._conns.values()):
                # stop reading; keep write interest so pending acks
                # flush inside the drain budget
                conn.paused_pressure = True
                self._set_interest(conn)
        self.call_soon(_start)

    def stop(self) -> None:
        self._alive = False
        try:
            self._wake_w.send(b"\0")
        except OSError:
            pass

    def join(self, timeout: float) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def _teardown(self) -> None:
        for conn in list(self._conns.values()):
            self._close(conn)
        for s in (self._wake_r, self._wake_w):
            self._safe_close(s)
        try:
            self._sel.close()
        except OSError:
            pass

    def force_close(self) -> None:
        """Last-resort close from the shutting-down thread when the
        loop failed to exit: a leaked fd is worse than a racy close."""
        for conn in list(self._conns.values()):
            self._safe_close(conn.sock)
        self._conns.clear()

    # -- close helpers -------------------------------------------------------
    def _evict(self, conn: _Conn, reason: str) -> None:
        if conn.closed:
            return
        self.group._m_evicted(reason).inc()
        obs.instant("reactor.evict", reason=reason, fd=conn.fd,
                    outbound=conn.outbound)
        self._close(conn)

    def _close(self, conn: _Conn) -> None:
        if conn.closed:
            return
        conn.closed = True
        self._conns.pop(conn.fd, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        self._safe_close(conn.sock)
        self.group._note_close(conn)

    @staticmethod
    def _safe_close(sock: socket.socket) -> None:
        try:
            sock.close()
        except OSError:
            pass

    @staticmethod
    def _peer(conn: _Conn) -> str:
        try:
            return str(conn.sock.getpeername())
        except OSError:
            return f"fd={conn.fd}"


class ReactorGroup:
    """N reactors + one listening socket (registered on reactor 0) for
    one backend.  Owns the admission gate, the shed decision, and the
    connection counters; the backend owns the protocol."""

    def __init__(self, backend, bind_addr: Optional[tuple[str, int]],
                 cfg: Optional[ReactorConfig] = None, name: str = "tcp"):
        self.backend = backend
        self.cfg = cfg if cfg is not None else ReactorConfig()
        self.name = name
        self._lock = threading.Lock()
        self._open_inbound = 0
        self.peak_connections = 0
        self._pressure_since: Optional[float] = None
        self._rss_checked = 0.0
        self._rss_over = False
        self._overload_gate: Optional[Callable[[], bool]] = None
        # per-reason door-shed ledger: ceiling / external gate /
        # sustained ingest pressure / RSS watermark.  The fused-cluster
        # report reads this to attribute sheds to LANE pressure (the
        # registry-fed gate) vs the transport's own watermarks.
        self.shed_reasons = {"ceiling": 0, "gate": 0, "pressure": 0,
                             "rss": 0}
        self._listener_paused_until = 0.0
        self._listener_registered = False
        b = backend.backend_name
        # rank label: a set() gauge shared by several in-process groups
        # (server + dial-back clients in one test/torture process)
        # would flap last-writer-wins without it
        self._m_open = obs.gauge("comm_open_connections", backend=b,
                                 rank=str(getattr(backend, "rank", 0)))
        self._m_shed = obs.counter("comm_uplinks_shed_total", backend=b)
        self._m_drained = obs.counter("comm_connections_drained_total",
                                      backend=b)
        self._m_fd_exhausted = obs.counter(
            "comm_accept_fd_exhausted_total", backend=b)
        self._m_loop_lag = obs.histogram(
            "reactor_loop_lag_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS, backend=b)
        self._evict_counters: dict[str, obs.Counter] = {}
        self.listener: Optional[socket.socket] = None
        if bind_addr is not None:
            # bind synchronously so a busy port raises from the
            # constructor exactly like the thread transport did
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                ls.bind(bind_addr)
                ls.listen(1024)
            except OSError:
                ls.close()
                raise
            ls.setblocking(False)
            self.listener = ls
        self.reactors = [Reactor(self, i)
                         for i in range(self.cfg.reactors)]
        self._rr = itertools.cycle(self.reactors)

    def _m_evicted(self, reason: str):
        c = self._evict_counters.get(reason)
        if c is None:
            c = obs.counter("comm_connections_evicted_total",
                            backend=self.backend.backend_name,
                            reason=reason)
            self._evict_counters[reason] = c
        return c

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        for r in self.reactors:
            r._thread.start()
        if self.listener is not None:
            r0 = self.reactors[0]
            r0.call_soon(self._register_listener)

    def _register_listener(self) -> None:
        if self.listener is None:
            return
        try:
            self.reactors[0]._sel.register(
                self.listener, selectors.EVENT_READ, ("listener", None))
            self._listener_registered = True
        except (ValueError, KeyError, OSError):
            pass

    def _unregister_listener(self) -> None:
        if self.listener is None or not self._listener_registered:
            return
        try:
            self.reactors[0]._sel.unregister(self.listener)
        except (KeyError, ValueError, OSError):
            pass
        self._listener_registered = False

    def adopt_outbound(self, sock: socket.socket) -> None:
        """Register a dial-out connection for reads (acks/nacks from
        the peer ride back over it) — replaces the thread transport's
        per-connection reader thread.  The socket stays BLOCKING: the
        sender threads' sendall path owns writes; the reactor only ever
        recv()s after the selector said readable."""
        next(self._rr).adopt(sock, outbound=True)

    def forget(self, sock: socket.socket) -> None:
        for r in self.reactors:
            r.forget(sock)

    def close(self) -> None:
        """Graceful drain, then teardown: stop accepting, give pending
        writes `drain_s` to flush, close every socket, stop the loops.
        After this returns no reactor-owned fd is open (the churn
        test's audit)."""
        with obs.span("reactor.drain", backend=self.backend.backend_name,
                      open=self._open_inbound):
            if self.listener is not None:
                self.reactors[0].call_soon(self._unregister_listener)
            deadline = time.monotonic() + self.cfg.drain_s
            for r in self.reactors:
                r.begin_drain(deadline)
            for r in self.reactors:
                r.join(timeout=self.cfg.drain_s + 2.0)
            for r in self.reactors:
                if r._thread.is_alive():
                    r.stop()
            for r in self.reactors:
                r.join(timeout=2.0)
            for r in self.reactors:
                if r._thread.is_alive():
                    log.warning("reactor-%s-%d did not exit; force-closing "
                                "its sockets", self.name, r.idx)
                    r.force_close()
            if self.listener is not None:
                try:
                    self.listener.close()
                except OSError:
                    pass
                self.listener = None

    # -- accept + admission --------------------------------------------------
    def _on_accept(self, reactor: Reactor) -> None:
        now = time.monotonic()
        while True:
            try:
                s, _addr = self.listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                if self.listener is None:
                    return
                exh = accept_exhaustion(e)
                if exh is not None:
                    # the ISSUE-11 satellite: NAMED error with the
                    # current ulimit, listener survives with a backoff
                    # instead of the accept loop dying on a bare OSError
                    self._m_fd_exhausted.inc()
                    log.error("%s: %s", self.name, exh)
                    obs.instant("reactor.fd_exhausted",
                                backend=self.backend.backend_name)
                    self._listener_paused_until = now + 0.5
                    self._unregister_listener()
                    return
                log.warning("%s: accept failed: %s", self.name, e)
                return
            why = ("ceiling"
                   if self._open_inbound >= self.cfg.max_connections
                   else self._overload_reason(now))
            if why is not None:
                # load shedding at the door: reject before the conn
                # costs a registration — counted + attributed, never
                # silent
                self._m_shed.inc()
                self.shed_reasons[why] += 1
                obs.instant("reactor.shed_accept",
                            open=self._open_inbound, reason=why)
                Reactor._safe_close(s)
                continue
            try:
                s.setblocking(False)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                Reactor._safe_close(s)
                continue
            # admission accounting happens HERE, not at the (deferred)
            # registration on the target loop — a storm draining the
            # whole listen backlog in one pass must see an up-to-date
            # count, or the ceiling overshoots by the backlog depth
            self._note_inbound_open()
            next(self._rr).adopt(s, outbound=False)

    def _maybe_resume_listener(self, now: float) -> None:
        if (self.listener is not None and not self._listener_registered
                and now >= self._listener_paused_until
                and not self.reactors[0]._draining):
            self._register_listener()

    # -- overload decision ---------------------------------------------------
    def set_overload_gate(self, fn: Optional[Callable[[], bool]]) -> None:
        """External shed signal (the serving layer's watermark —
        decode-pool depth, commit backlog, anything): while it returns
        True, new connections are rejected and the stalest uplinks are
        shed batch by batch."""
        self._overload_gate = fn

    def _note_pressure(self, pressing: bool) -> None:
        if not self.cfg.shed_on_pressure:
            return
        with self._lock:
            if pressing and self._pressure_since is None:
                self._pressure_since = time.monotonic()
            elif not pressing:
                self._pressure_since = None

    def _overloaded(self, now: float) -> bool:
        return self._overload_reason(now) is not None

    def _overload_reason(self, now: float) -> Optional[str]:
        """Which watermark (if any) says shed: "gate" (the external
        serving-layer signal — lane/registry pressure), "pressure"
        (sustained ingest-pool backpressure), or "rss"."""
        gate = self._overload_gate
        if gate is not None:
            try:
                if gate():
                    return "gate"
            except Exception:
                log.exception("%s: overload gate failed", self.name)
        if self.cfg.shed_on_pressure:
            with self._lock:
                since = self._pressure_since
            if since is not None and now - since >= self.cfg.shed_after_s:
                return "pressure"
        if self.cfg.rss_limit_bytes is not None:
            if now - self._rss_checked > 0.5:
                from fedml_tpu.scale.serve import rss_bytes
                self._rss_checked = now
                self._rss_over = rss_bytes() > self.cfg.rss_limit_bytes
            if self._rss_over:
                return "rss"
        return None

    # -- connection accounting -----------------------------------------------
    def _note_inbound_open(self) -> None:
        with self._lock:
            self._open_inbound += 1
            if self._open_inbound > self.peak_connections:
                self.peak_connections = self._open_inbound
            self._m_open.set(self._open_inbound)

    def _note_inbound_closed(self) -> None:
        with self._lock:
            self._open_inbound = max(0, self._open_inbound - 1)
            self._m_open.set(self._open_inbound)

    def _note_close(self, conn: _Conn) -> None:
        if conn.outbound:
            cb = getattr(self.backend, "_on_outbound_closed", None)
            if cb is not None:
                try:
                    cb(conn.sock)
                except Exception:
                    pass
            return
        self._note_inbound_closed()

    @property
    def open_connections(self) -> int:
        with self._lock:
            return self._open_inbound
