"""Comm abstraction: BaseCommManager + Observer.

Parity: fedml_core/distributed/communication/base_com_manager.py:7-27 and
observer.py:4-7.  Backends push received Messages into an internal queue;
`handle_receive_message()` drains it and fans out to observers — a blocking
get instead of the reference's 0.3 s polling loop
(mpi/com_manager.py:71-78).
"""
from __future__ import annotations

import abc
import queue

from fedml_tpu import obs
from fedml_tpu.comm.message import Message


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type, msg_params: Message) -> None: ...


class BaseCommManager(abc.ABC):
    """Backend interface. Concrete backends implement `send_message` and
    arrange for inbound messages to reach `_on_message` (thread-safe).

    Observability hooks: every backend carries byte/message counters in
    the process metrics registry, labeled by `backend_name` (a class
    attr each concrete backend sets).  Concrete send/recv paths call
    `_obs_sent(nbytes)` / `_obs_received(nbytes)` where the wire size
    is known, and `_obs_retry()` on reconnect/resend attempts — so
    "where did the round's bytes go" is answerable per backend from
    one Prometheus snapshot (fedml_tpu/obs)."""

    backend_name = "base"

    def __init__(self):
        self._observers: list[Observer] = []
        self._inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self._running = False
        b = self.backend_name
        self._m_sent_msgs = obs.counter("comm_sent_messages_total",
                                        backend=b)
        self._m_sent_bytes = obs.counter("comm_sent_bytes_total", backend=b)
        self._m_recv_msgs = obs.counter("comm_received_messages_total",
                                        backend=b)
        self._m_recv_bytes = obs.counter("comm_received_bytes_total",
                                         backend=b)
        self._m_retries = obs.counter("comm_retries_total", backend=b)

    # -- observability hooks -------------------------------------------------
    def _obs_sent(self, nbytes: int) -> None:
        self._m_sent_msgs.inc()
        self._m_sent_bytes.inc(nbytes)

    def _obs_received(self, nbytes: int) -> None:
        self._m_recv_msgs.inc()
        self._m_recv_bytes.inc(nbytes)

    def _obs_retry(self) -> None:
        self._m_retries.inc()

    # -- reference API -------------------------------------------------------
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None: ...

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Blocking dispatch loop; returns after stop_receive_message()."""
        self._running = True
        while self._running:
            msg = self._inbox.get()
            if msg is None:       # sentinel from stop_receive_message
                break
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._inbox.put(None)

    # -- backend-side delivery ----------------------------------------------
    def _on_message(self, msg: Message) -> None:
        self._inbox.put(msg)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)
