"""Comm abstraction: BaseCommManager + Observer.

Parity: fedml_core/distributed/communication/base_com_manager.py:7-27 and
observer.py:4-7.  Backends push received Messages into an internal queue;
`handle_receive_message()` drains it and fans out to observers — a blocking
get instead of the reference's 0.3 s polling loop
(mpi/com_manager.py:71-78).
"""
from __future__ import annotations

import abc
import queue
import time

from fedml_tpu import obs
from fedml_tpu.obs import propagate
from fedml_tpu.comm.message import Message, MessageCodec


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type, msg_params: Message) -> None: ...


class BaseCommManager(abc.ABC):
    """Backend interface. Concrete backends implement `send_message` and
    arrange for inbound messages to reach `_on_message` (thread-safe).

    Observability hooks: every backend carries byte/message counters in
    the process metrics registry, labeled by `backend_name` (a class
    attr each concrete backend sets).  Concrete send/recv paths call
    `_obs_sent(nbytes)` / `_obs_received(nbytes)` where the wire size
    is known, and `_obs_retry()` on reconnect/resend attempts — so
    "where did the round's bytes go" is answerable per backend from
    one Prometheus snapshot (fedml_tpu/obs)."""

    backend_name = "base"
    # True when inbound traffic reaches the _deliver_frame chokepoint as
    # raw wire frames, so an installed frame sink actually sees it; a
    # backend whose receive path hands over already-decoded Messages
    # (broker JSON, no-encode inproc) must override with False so ingest
    # pools fall back to inline decode instead of idling silently
    supports_frame_sink = True

    def __init__(self):
        self._observers: list[Observer] = []
        self._inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self._running = False
        self._draining = False
        self._frame_sink = None
        b = self.backend_name
        self._m_sent_msgs = obs.counter("comm_sent_messages_total",
                                        backend=b)
        self._m_sent_bytes = obs.counter("comm_sent_bytes_total", backend=b)
        self._m_recv_msgs = obs.counter("comm_received_messages_total",
                                        backend=b)
        self._m_recv_bytes = obs.counter("comm_received_bytes_total",
                                         backend=b)
        self._m_retries = obs.counter("comm_retries_total", backend=b)
        self._m_decode_seconds = obs.histogram(
            "comm_decode_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS, backend=b)
        # federation-wide tracing (fedml_tpu/obs/propagate.py): per-peer
        # clock-offset estimator fed by the trace blocks send paths
        # stamp and receive paths strip at the chokepoints below
        self._clock = propagate.make_clock(b)

    # -- observability hooks -------------------------------------------------
    def _obs_sent(self, nbytes: int) -> None:
        self._m_sent_msgs.inc()
        self._m_sent_bytes.inc(nbytes)

    def _obs_received(self, nbytes: int) -> None:
        self._m_recv_msgs.inc()
        self._m_recv_bytes.inc(nbytes)

    def _obs_retry(self) -> None:
        self._m_retries.inc()

    # -- federation-wide tracing (ISSUE 7) -----------------------------------
    def _stamp_frame(self, msg: Message) -> None:
        """Outbound chokepoint twin of `_deliver_frame`: attach the
        compact trace block (sender rank, send timestamps, span digest,
        clock echo) BEFORE encode.  Every concrete backend calls this
        first in `send_message`.  With tracing disabled nothing is
        added — frames stay byte-identical to the untraced build
        (pinned in tests/test_wire_codec.py)."""
        propagate.stamp(msg, getattr(self, "rank", 0), clock=self._clock)

    def _note_frame(self, msg: Message) -> None:
        """Strip + account the trace block / piggybacked metrics delta
        of an inbound Message before the FSM sees it (clock-offset
        estimate, trace.recv instant, cohort metrics fold)."""
        propagate.note(msg, backend=self.backend_name, clock=self._clock)

    # -- reference API -------------------------------------------------------
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None: ...

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Blocking dispatch loop; returns after stop_receive_message()."""
        self._running = True
        while self._running:
            msg = self._inbox.get()
            if msg is None:       # sentinel from stop_receive_message
                break
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._draining = True   # release recv threads blocked in put()
        try:
            self._inbox.put_nowait(None)   # wake a get() blocked on empty
        except queue.Full:
            pass   # bounded + full: get() returns an item, sees _running

    def bound_inbox(self, maxsize: int) -> None:
        """Swap the unbounded inbox for a bounded one BEFORE traffic
        starts (ingestion-style consumers): a full inbox blocks
        `_on_message`, stalling the recv thread so transport flow
        control reaches the sender instead of decoded frames piling up
        on the heap — the legacy (sink-less) torture arm's memory
        bound."""
        self._inbox = queue.Queue(maxsize=maxsize)

    # -- backend-side delivery ----------------------------------------------
    def set_frame_sink(self, sink) -> None:
        """Install a raw-frame interceptor (the async ingest path,
        fedml_tpu/async_/lifecycle.py): inbound wire frames reach
        `sink(payload)` BEFORE decode, so an ingest pool can
        decode-into preallocated buffer rows off the recv thread.  The
        sink returns None when it consumed the frame, or a decoded
        Message to dispatch through the normal observer path.  A
        blocking sink is the backpressure mechanism: the transport's
        recv loop stalls, and flow control propagates to the sender."""
        self._frame_sink = sink

    def _deliver_frame(self, payload) -> None:
        """Inbound raw-frame chokepoint shared by every codec-framed
        backend: route to the frame sink when one is installed,
        otherwise decode inline (timed into comm_decode_seconds) and
        enqueue for the dispatch loop."""
        sink = self._frame_sink
        if sink is not None:
            msg = sink(payload)
            if msg is None:
                return
            self._note_frame(msg)   # idempotent (note pops the params)
        else:
            t0 = time.perf_counter()
            with obs.span("comm.decode", backend=self.backend_name,
                          nbytes=len(payload)):
                msg = MessageCodec.decode(payload)
            self._m_decode_seconds.observe(time.perf_counter() - t0)
            self._note_frame(msg)
        self._on_message(msg)

    def _on_message(self, msg: Message) -> None:
        if self._inbox.maxsize > 0:
            # bounded inbox: block (= recv-thread backpressure) but wake
            # periodically so shutdown can release us — a put() stuck
            # forever on a full queue after the dispatch loop exited
            # would leak every recv thread and its decoded payload
            while not self._draining:
                try:
                    self._inbox.put(msg, timeout=0.2)
                    return
                except queue.Full:
                    continue
            return                          # shutting down: drop the frame
        self._inbox.put(msg)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)
