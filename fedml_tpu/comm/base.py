"""Comm abstraction: BaseCommManager + Observer.

Parity: fedml_core/distributed/communication/base_com_manager.py:7-27 and
observer.py:4-7.  Backends push received Messages into an internal queue;
`handle_receive_message()` drains it and fans out to observers — a blocking
get instead of the reference's 0.3 s polling loop
(mpi/com_manager.py:71-78).
"""
from __future__ import annotations

import abc
import logging
import queue
import time

from fedml_tpu import obs
from fedml_tpu.obs import propagate
from fedml_tpu.comm import reliability
from fedml_tpu.comm.message import Message, MessageCodec

log = logging.getLogger(__name__)


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type, msg_params: Message) -> None: ...


class BaseCommManager(abc.ABC):
    """Backend interface. Concrete backends implement `send_message` and
    arrange for inbound messages to reach `_on_message` (thread-safe).

    Observability hooks: every backend carries byte/message counters in
    the process metrics registry, labeled by `backend_name` (a class
    attr each concrete backend sets).  Concrete send/recv paths call
    `_obs_sent(nbytes)` / `_obs_received(nbytes)` where the wire size
    is known, and `_obs_retry()` on reconnect/resend attempts — so
    "where did the round's bytes go" is answerable per backend from
    one Prometheus snapshot (fedml_tpu/obs)."""

    backend_name = "base"
    # True when inbound traffic reaches the _deliver_frame chokepoint as
    # raw wire frames, so an installed frame sink actually sees it; a
    # backend whose receive path hands over already-decoded Messages
    # (broker JSON, no-encode inproc) must override with False so ingest
    # pools fall back to inline decode instead of idling silently
    supports_frame_sink = True
    # True when the backend can carry the reliability envelope (raw
    # binary frames + a way to push acks back): MQTT speaks broker JSON
    # (the broker's QoS is its reliability story) and a no-encode inproc
    # router never materializes frames — both override with False
    supports_reliability = True

    def __init__(self):
        self._observers: list[Observer] = []
        self._inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self._running = False
        self._draining = False
        self._frame_sink = None
        self._ingest_pressure = None    # reactor backpressure probe
        self._ingest_ready_hooks = []   # reactor resume wakeups
        self._chaos = None              # ChaosPolicy (install_chaos)
        self._rel_ep = None             # lazy ReliableEndpoint
        self._reliable_tx = False       # sends are enveloped when True
        b = self.backend_name
        self._m_sent_msgs = obs.counter("comm_sent_messages_total",
                                        backend=b)
        self._m_sent_bytes = obs.counter("comm_sent_bytes_total", backend=b)
        self._m_recv_msgs = obs.counter("comm_received_messages_total",
                                        backend=b)
        self._m_recv_bytes = obs.counter("comm_received_bytes_total",
                                         backend=b)
        self._m_retries = obs.counter("comm_retries_total", backend=b)
        # robustness accounting (ISSUE 8): frames dropped at the bounded
        # inbox during shutdown drain, frames quarantined instead of
        # killing a recv thread, and recv threads that DID die (the
        # chaos acceptance gate demands this stays 0)
        self._m_dropped = obs.counter("comm_frames_dropped_total",
                                      backend=b)
        self._m_quarantined = obs.counter("comm_frames_quarantined_total")
        self._m_recv_deaths = obs.counter("comm_recv_thread_deaths_total")
        self._m_decode_seconds = obs.histogram(
            "comm_decode_seconds",
            buckets=obs.metrics.DECODE_SECONDS_BUCKETS, backend=b)
        # federation-wide tracing (fedml_tpu/obs/propagate.py): per-peer
        # clock-offset estimator fed by the trace blocks send paths
        # stamp and receive paths strip at the chokepoints below
        self._clock = propagate.make_clock(b)

    # -- observability hooks -------------------------------------------------
    def _obs_sent(self, nbytes: int) -> None:
        self._m_sent_msgs.inc()
        self._m_sent_bytes.inc(nbytes)

    def _obs_received(self, nbytes: int) -> None:
        self._m_recv_msgs.inc()
        self._m_recv_bytes.inc(nbytes)

    def _obs_retry(self) -> None:
        self._m_retries.inc()

    # -- chaos + reliability (ISSUE 8) ---------------------------------------
    def install_chaos(self, policy) -> None:
        """Install a seeded fault injector (comm/chaos.py) at this
        backend's two frame chokepoints: the send gate in _stamp_frame
        and the raw-frame receive path in _deliver_frame.  One policy
        may be shared across backends."""
        if not self.supports_frame_sink and self.backend_name != "mqtt":
            # a no-encode inproc router hands Message objects across —
            # frames never exist, so wire-level faults cannot apply
            log.warning(
                "chaos installed on %s, but this backend never "
                "materializes wire frames — only the send gate "
                "(partition/drop/delay) applies", self.backend_name)
        cfg = getattr(policy, "cfg", None)
        if (getattr(self, "reactor_mode", False) and cfg is not None
                and getattr(cfg, "delay", 0.0) > 0.0):
            # on the reactor transport the receive path runs on a
            # SHARED event loop: an injected delay sleeps the loop, so
            # it models a NIC-level stall hitting every conn on that
            # loop, not one slow peer (the thread transport's shape) —
            # loud, because the head-of-line coupling changes what the
            # fault measures
            log.warning(
                "chaos delay faults on the %s reactor transport stall "
                "the shared event loop (head-of-line for every conn on "
                "it), not just the injected peer — use the thread "
                "transport (reactor=False) for per-peer delay "
                "semantics", self.backend_name)
        self._chaos = policy

    def enable_reliability(self, policy=None) -> bool:
        """Opt this backend's SENDS into the reliability envelope
        (comm/reliability.py): per-peer seq + CRC32, ack/nack, backoff
        resend.  Receives always unwrap envelopes regardless (mixed
        deployments interoperate).  Returns False — and stays on the
        byte-identical pre-PR wire — under the FEDML_RELIABLE=0 escape
        hatch or on backends that can't carry the envelope."""
        if reliability.escape_hatch_off():
            log.info(
                "FEDML_RELIABLE=0: reliability envelope disabled on %s",
                self.backend_name)
            return False
        if not self.supports_reliability:
            log.warning(
                "reliability requested on %s, which cannot carry the "
                "envelope (broker JSON / no-encode router) — sends stay "
                "fire-and-forget", self.backend_name)
            return False
        self._reliability_endpoint(policy)
        self._reliable_tx = True
        return True

    def _reliability_endpoint(self, policy=None):
        """Lazy per-backend ReliableEndpoint — created on enable, or on
        the first inbound FMLR frame from an enveloping peer (so acks
        and the dedup ledger work even when this side's own sends are
        plain)."""
        if self._rel_ep is None:
            self._rel_ep = reliability.ReliableEndpoint(
                getattr(self, "rank", 0), self._raw_send, policy=policy,
                name=self.backend_name)
        return self._rel_ep

    def _raw_send(self, receiver: int, wire: bytes) -> None:
        """Raw wire write of pre-assembled bytes to a peer — the resend
        thread's and the ack path's transmit primitive.  Codec-framed
        backends override; the base refuses (MQTT / no-encode inproc
        never carry envelopes)."""
        raise NotImplementedError(
            f"{self.backend_name} has no raw-frame send path")

    def _chaos_disconnect(self, msg: Message) -> bool:
        """Backend hook for the disconnect-mid-frame fault: transmit a
        deliberately torn frame and kill the connection (TCP overrides).
        Returns False when unsupported — the gate degrades the fault to
        a drop."""
        return False

    # -- federation-wide tracing (ISSUE 7) -----------------------------------
    def _stamp_frame(self, msg: Message) -> bool:
        """Outbound chokepoint twin of `_deliver_frame`: the chaos send
        gate (partition / per-peer drop / delay / disconnect-mid-frame),
        then the compact trace block (sender rank, send timestamps,
        span digest, clock echo) BEFORE encode.  Every concrete backend
        calls this first in `send_message` and returns without sending
        when it yields False.  With tracing disabled nothing is added —
        frames stay byte-identical to the untraced build (pinned in
        tests/test_wire_codec.py)."""
        chaos = self._chaos
        if chaos is not None:
            act, delay = chaos.plan_send(msg.get_receiver_id())
            if act in ("drop", "partition"):
                return False
            if act == "delay":
                time.sleep(min(delay, 1.0))
            elif act == "disconnect":
                self._chaos_disconnect(msg)
                return False        # the frame died mid-wire either way
        propagate.stamp(msg, getattr(self, "rank", 0), clock=self._clock)
        return True

    def _note_frame(self, msg: Message) -> None:
        """Strip + account the trace block / piggybacked metrics delta
        of an inbound Message before the FSM sees it (clock-offset
        estimate, trace.recv instant, cohort metrics fold)."""
        propagate.note(msg, backend=self.backend_name, clock=self._clock)

    # -- reference API -------------------------------------------------------
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None: ...

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Blocking dispatch loop; returns after stop_receive_message()."""
        self._running = True
        while self._running:
            msg = self._inbox.get()
            if msg is None:       # sentinel from stop_receive_message
                break
            self._notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        self._draining = True   # release recv threads blocked in put()
        if self._rel_ep is not None:
            self._rel_ep.close()           # stop the resend thread
        try:
            self._inbox.put_nowait(None)   # wake a get() blocked on empty
        except queue.Full:
            pass   # bounded + full: get() returns an item, sees _running

    def bound_inbox(self, maxsize: int) -> None:
        """Swap the unbounded inbox for a bounded one BEFORE traffic
        starts (ingestion-style consumers): a full inbox blocks
        `_on_message`, stalling the recv thread so transport flow
        control reaches the sender instead of decoded frames piling up
        on the heap — the legacy (sink-less) torture arm's memory
        bound."""
        self._inbox = queue.Queue(maxsize=maxsize)

    # -- backend-side delivery ----------------------------------------------
    def set_frame_sink(self, sink) -> None:
        """Install a raw-frame interceptor (the async ingest path,
        fedml_tpu/async_/lifecycle.py): inbound wire frames reach
        `sink(payload)` BEFORE decode, so an ingest pool can
        decode-into preallocated buffer rows off the recv thread.  The
        sink returns None when it consumed the frame, or a decoded
        Message to dispatch through the normal observer path.  A
        blocking sink is the backpressure mechanism: the transport's
        recv loop stalls, and flow control propagates to the sender."""
        self._frame_sink = sink

    def set_ingest_pressure(self, fn) -> None:
        """Install a non-blocking admission probe (ISSUE 11): `fn()`
        returns True while the consumer CANNOT take another frame (the
        decode pool is at its in-flight bound).  Reactor transports
        consult it BEFORE delivering a reassembled frame and suspend
        the peer's read interest instead of blocking a shared loop
        thread — the event-loop twin of the blocking-sink backpressure
        thread transports get for free.  Thread transports ignore it
        (their recv thread blocking in the sink IS the backpressure)."""
        self._ingest_pressure = fn

    def add_ingest_ready_hook(self, fn) -> None:
        """Register a wakeup a reactor loop installs the first time it
        suspends a peer for pressure: the consumer calls
        `_notify_ingest_ready()` whenever capacity frees, so paused
        reads resume within one event-loop wakeup instead of waiting
        for the housekeeping scan."""
        if fn not in self._ingest_ready_hooks:
            self._ingest_ready_hooks.append(fn)

    def _notify_ingest_ready(self) -> None:
        for fn in list(self._ingest_ready_hooks):
            try:
                fn()
            except Exception:
                log.exception("ingest-ready hook failed")

    def _reactor_pressure(self) -> bool:
        """True while a reactor must NOT deliver another frame: the
        installed ingest probe says the pool is full, or the bounded
        inbox is — both resolve by suspending reads, never by blocking
        the loop."""
        fn = self._ingest_pressure
        if fn is not None:
            try:
                if fn():
                    return True
            except Exception:
                log.exception("ingest pressure probe failed — treating "
                              "as no pressure")
        if self._inbox.maxsize > 0 and self._inbox.full():
            return True
        return False

    def _deliver_frame(self, payload, reply=None) -> None:
        """Inbound raw-frame chokepoint shared by every codec-framed
        backend: chaos receive faults first (drop/dup/reorder/delay/
        corrupt on the raw bytes), then per surviving frame the
        reliability envelope (CRC quarantine, dedup ledger, ack via
        `reply` — the transport's reverse channel — or _raw_send), then
        the frame sink when one is installed, otherwise inline decode
        (timed into comm_decode_seconds) and the dispatch queue.  A
        frame the codec rejects is QUARANTINED (counted + logged), never
        an exception up the recv thread."""
        chaos = self._chaos
        if chaos is not None:
            for p in chaos.filter_recv(payload):
                self._deliver_one(p, reply)
            return
        self._deliver_one(payload, reply)

    def _deliver_one(self, payload, reply=None) -> None:
        if bytes(payload[:4]) == reliability.MAGIC:
            payload = self._reliability_endpoint().on_wire(payload,
                                                           reply=reply)
            if payload is None:
                return              # ack/nack, suppressed dup, quarantine
        sink = self._frame_sink
        if sink is not None:
            msg = sink(payload)
            if msg is None:
                return
            self._note_frame(msg)   # idempotent (note pops the params)
        else:
            t0 = time.perf_counter()
            try:
                with obs.span("comm.decode", backend=self.backend_name,
                              nbytes=len(payload)):
                    msg = MessageCodec.decode(payload)
            except Exception as e:
                # corrupt/alien frame with no envelope to nack through:
                # quarantine instead of killing the recv thread
                self._m_quarantined.inc()
                log.warning(
                    "%s: undecodable frame (%d bytes) quarantined: %s",
                    self.backend_name, len(payload), e)
                return
            self._m_decode_seconds.observe(time.perf_counter() - t0)
            self._note_frame(msg)
        self._on_message(msg)

    def _on_message(self, msg: Message) -> None:
        if self._inbox.maxsize > 0:
            # bounded inbox: block (= recv-thread backpressure) but wake
            # periodically so shutdown can release us — a put() stuck
            # forever on a full queue after the dispatch loop exited
            # would leak every recv thread and its decoded payload
            while not self._draining:
                try:
                    self._inbox.put(msg, timeout=0.2)
                    return
                except queue.Full:
                    continue
            # shutting down: drop the frame — COUNTED, so the rollup
            # shows how much shutdown loss the drain swallowed instead
            # of it vanishing silently (ISSUE-8 satellite)
            self._m_dropped.inc()
            return
        self._inbox.put(msg)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)
