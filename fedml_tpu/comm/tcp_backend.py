"""TCP comm backend — length-prefixed MessageCodec frames over raw sockets.

The lean transport for trusted intra-cluster control traffic (the reference
covers this niche with Torch-RPC/TensorPipe, trpc_comm_manager.py:26-144 —
tensor-native, no JSON).  Frame format: 8-byte little-endian length ‖
MessageCodec bytes.

When the native C++ transport (fedml_tpu/native/) is built, `TcpBackend`
transparently uses it for the socket loop; this pure-Python path is the
fallback and the behavioral spec.
"""
from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Union

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message, MessageCodec

log = logging.getLogger(__name__)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpBackend(BaseCommManager):
    backend_name = "tcp"

    def __init__(self, rank: int, ip_config: Union[str, dict],
                 base_port: int = 52000):
        super().__init__()
        from fedml_tpu.comm.grpc_backend import load_ip_config
        self.rank = rank
        self.ip_config = load_ip_config(ip_config)
        self.base_port = base_port
        self._conns: dict[int, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", base_port + rank))
        self._listener.listen(64)
        self._alive = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        try:
            while self._alive:
                (length,) = struct.unpack("<Q", _read_exact(conn, 8))
                payload = _read_exact(conn, length)
                self._obs_received(len(payload))
                # _deliver_frame: inline decode, or hand the raw frame
                # to an installed ingest sink (async decode pool) — a
                # blocked sink stalls this loop and TCP flow control
                # backpressures the sender
                self._deliver_frame(payload)
        except (ConnectionError, OSError):
            conn.close()

    def _connect(self, receiver: int, retry_for: float = 60.0) -> socket.socket:
        with self._conn_lock:
            s = self._conns.get(receiver)
        if s is not None:
            return s
        # multi-process launches race: the peer's listener may not be bound
        # yet (run_fedavg_grpc.sh starts all ranks at once), so refused
        # connections retry with backoff — OUTSIDE the lock, so one slow
        # peer cannot stall sends to the others (or close())
        deadline = time.monotonic() + retry_for
        while True:
            try:
                s = socket.create_connection(
                    (self.ip_config[receiver], self.base_port + receiver),
                    timeout=30)
                break
            except ConnectionRefusedError:
                if time.monotonic() >= deadline:
                    raise
                self._obs_retry()
                time.sleep(0.2)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._conn_lock:
            racer = self._conns.get(receiver)
            if racer is not None:           # lost a concurrent connect race
                s.close()
                return racer
            self._conns[receiver] = s
        return s

    def send_message(self, msg: Message) -> None:
        # chunked streaming send: the codec hands back a frame prefix +
        # one part per array buffer, and each part goes to the socket
        # directly — a multi-GB model frame is never materialized as one
        # contiguous buffer (the old encode() + concat path transiently
        # held ~3x the payload: arrays + BytesIO + the length-prefixed
        # copy)
        self._stamp_frame(msg)      # trace block (no-op when obs is off)
        total, parts = MessageCodec.encode_parts(msg)
        sock = self._connect(msg.get_receiver_id())
        with self._conn_lock:
            sock.sendall(struct.pack("<Q", total))
            for part in parts:
                sock.sendall(part)
        self._obs_sent(total)

    def close(self) -> None:
        self._alive = False
        self._listener.close()
        with self._conn_lock:
            for s in self._conns.values():
                s.close()
            self._conns.clear()
