"""TCP comm backend — length-prefixed MessageCodec frames over raw sockets.

The lean transport for trusted intra-cluster control traffic (the reference
covers this niche with Torch-RPC/TensorPipe, trpc_comm_manager.py:26-144 —
tensor-native, no JSON).  Frame format: 8-byte little-endian length ‖
MessageCodec bytes.

Two receive transports, one wire format (ISSUE 11):

* **reactor** (default): a `selectors` event loop per core
  (comm/reactor.py) owns non-blocking accepted sockets with bounded
  buffers, incremental frame reassembly, stall/rate eviction, load
  shedding, and graceful drain — the overload-safe path that holds 10k
  live connections.  Backpressure from the decode pool reaches the
  peer as read-interest suspension, never as a blocked loop thread.
* **threads** (`reactor=False`, or FEDML_TCP_REACTOR=0 process-wide):
  the original one-recv-thread-per-connection path — kept as the
  behavioral spec, the bitwise anchor (a reactor run commits the same
  accumulator, pinned in tests/test_reactor.py), and the ingest
  torture's faithful PR-5/6 A/B arm.

When the native C++ transport (fedml_tpu/native/) is built, `TcpBackend`
transparently uses it for the socket loop; this pure-Python path is the
fallback and the behavioral spec.

Reliability (ISSUE 8): with `enable_reliability()` the frame rides the
FMLR envelope and acks flow back over the SAME connection the data
arrived on (both transports hand `_deliver_frame` a reply callable) —
so a client that only dials out still gets its acks; outbound
connections are registered with the reactor for reads (thread mode
spawns a reader) so dial-out acks for OUR enveloped sends are seen too.
Resends reuse `_raw_send`, which invalidates the cached connection on
failure and redials — a server restart (the crash-resume scenario) is
survived by the backoff schedule, not by the caller.
"""
from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Optional, Union

from fedml_tpu.comm.base import BaseCommManager
from fedml_tpu.comm.message import Message, MessageCodec
from fedml_tpu.comm.reactor import (ReactorConfig, ReactorGroup,
                                    accept_exhaustion, reactor_default)
from fedml_tpu.comm.reliability import BackoffPolicy

log = logging.getLogger(__name__)

# THE connect-retry schedule (replaces the ad-hoc 0.2 s sleep loop):
# effectively unbounded attempts — the caller's retry_for deadline is
# the bound, the policy only shapes the delays
_CONNECT_BACKOFF = BackoffPolicy(base_s=0.2, mult=1.5, max_s=2.0,
                                 jitter=0.2, max_attempts=1_000_000)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpBackend(BaseCommManager):
    backend_name = "tcp"

    def __init__(self, rank: int, ip_config: Union[str, dict],
                 base_port: int = 52000,
                 reactor: Optional[bool] = None,
                 reactor_config: Optional[ReactorConfig] = None):
        super().__init__()
        from fedml_tpu.comm.grpc_backend import load_ip_config
        self.rank = rank
        self.ip_config = load_ip_config(ip_config)
        self.base_port = base_port
        self._conns: dict[int, socket.socket] = {}
        self._conn_lock = threading.Lock()
        # accepted (inbound) connections (thread mode), closed on
        # close(): leaving them established would hold the listen port
        # hostage against a same-port restart — the crash-resume rebind
        # — and leave peers talking into a half-dead socket
        self._accepted: set[socket.socket] = set()
        self._alive = True
        # FEDML_TCP_REACTOR=0 overrides everything (the escape hatch);
        # an explicit reactor= argument overrides the default
        if not reactor_default():
            reactor = False
        elif reactor is None:
            reactor = True
        self.reactor_mode = bool(reactor)
        self._rg: Optional[ReactorGroup] = None
        self._listener: Optional[socket.socket] = None
        if self.reactor_mode:
            # the group binds synchronously, so a busy port raises from
            # the constructor exactly like the thread transport
            self._rg = ReactorGroup(
                self, ("0.0.0.0", base_port + rank), reactor_config,
                name=f"tcp-{rank}")
            self._rg.start()
            return
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", base_port + rank))
        self._listener.listen(64)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _ = self._listener.accept()
            except OSError as e:
                exh = accept_exhaustion(e)
                if exh is not None and self._alive:
                    # ISSUE-11 satellite: fd exhaustion is a NAMED
                    # error with the current ulimit, and the listener
                    # SURVIVES with a backoff — a bare OSError used to
                    # end this loop and silently stop accepting forever
                    log.error("tcp rank %d: %s", self.rank, exh)
                    time.sleep(0.5)
                    continue
                return
            with self._conn_lock:
                self._accepted.add(conn)
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        # reply channel: acks/nacks ride back over the connection the
        # frame came in on — the only route to a peer that never
        # listens (the torture spam clients)
        wlock = threading.Lock()

        def reply(wire: bytes) -> None:
            with wlock:
                conn.sendall(struct.pack("<Q", len(wire)))
                conn.sendall(wire)

        try:
            while self._alive:
                (length,) = struct.unpack("<Q", _read_exact(conn, 8))
                payload = _read_exact(conn, length)
                self._obs_received(len(payload))
                # _deliver_frame: inline decode, or hand the raw frame
                # to an installed ingest sink (async decode pool) — a
                # blocked sink stalls this loop and TCP flow control
                # backpressures the sender
                self._deliver_frame(payload, reply=reply)
        except (ConnectionError, OSError):
            conn.close()
        except Exception:
            # the chaos acceptance gate: NOTHING that escapes the
            # delivery path may silently kill a recv thread — count it
            # so "zero recv-thread deaths" is assertable
            self._m_recv_deaths.inc()
            log.exception("tcp recv loop died on an unexpected error")
            conn.close()
        finally:
            with self._conn_lock:
                self._accepted.discard(conn)

    def _on_outbound_closed(self, sock: socket.socket) -> None:
        """Reactor callback: a dial-out connection it owned for reads
        died/was drained — drop the cached handle so the next send
        redials instead of writing into a closed socket."""
        with self._conn_lock:
            for rx, s in list(self._conns.items()):
                if s is sock:
                    self._conns.pop(rx, None)

    def _connect(self, receiver: int, retry_for: float = 60.0) -> socket.socket:
        with self._conn_lock:
            s = self._conns.get(receiver)
        if s is not None:
            return s
        # multi-process launches race: the peer's listener may not be bound
        # yet (run_fedavg_grpc.sh starts all ranks at once), so refused
        # connections retry on the shared backoff schedule — OUTSIDE the
        # lock, so one slow peer cannot stall sends to the others (or
        # close())
        deadline = time.monotonic() + retry_for
        attempt = 0
        while True:
            try:
                s = socket.create_connection(
                    (self.ip_config[receiver], self.base_port + receiver),
                    timeout=30)
                break
            except (ConnectionRefusedError, ConnectionResetError,
                    TimeoutError):
                # transient launch/restart races only — a gaierror
                # (typo'd host) must fail fast, not burn the deadline
                if time.monotonic() >= deadline:
                    raise
                self._obs_retry()
                attempt += 1
                time.sleep(_CONNECT_BACKOFF.delay(attempt))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._conn_lock:
            racer = self._conns.get(receiver)
            if racer is not None:           # lost a concurrent connect race
                s.close()
                return racer
            self._conns[receiver] = s
        if self.reactor_mode:
            # the reactor owns reads on dial-out conns (acks from an
            # enveloping peer); the socket stays blocking — sender
            # threads own the write side via sendall under _conn_lock
            self._rg.adopt_outbound(s)
        elif self._reliable_tx:
            # dial-out connections need a reader: the peer's acks for
            # our enveloped frames come back over this socket
            threading.Thread(target=self._recv_loop, args=(s,),
                             daemon=True).start()
        return s

    def _raw_send(self, receiver: int, wire: bytes) -> None:
        """Raw framed write (reliability resends + acks).  A transport
        failure invalidates the cached connection — the NEXT attempt
        redials, which is how a restarted peer (crash-resume) is
        rejoined — and re-raises for the resend scheduler."""
        sock = self._connect(receiver, retry_for=5.0)
        try:
            with self._conn_lock:
                sock.sendall(struct.pack("<Q", len(wire)))
                sock.sendall(wire)
        except OSError:
            with self._conn_lock:
                if self._conns.get(receiver) is sock:
                    self._conns.pop(receiver, None)
            if self._rg is not None:
                self._rg.forget(sock)   # BEFORE close: fileno still valid
            try:
                sock.close()
            except OSError:
                pass
            raise

    def _chaos_disconnect(self, msg: Message) -> bool:
        """Disconnect-mid-frame fault: send the length prefix plus HALF
        the frame, then hard-close the connection.  The receiver's
        reassembly path sees the torn frame end in EOF, drops the
        partial, and closes that conn only; the next real send redials
        — the torn-wire case the reliability resend exists for, so
        under the envelope the frame is registered first and
        recovers."""
        rx = msg.get_receiver_id()
        payload = MessageCodec.encode(msg)
        if self._reliable_tx:
            payload = self._reliability_endpoint().wrap(rx, payload)
        try:
            sock = self._connect(rx, retry_for=5.0)
            with self._conn_lock:
                sock.sendall(struct.pack("<Q", len(payload)))
                sock.sendall(payload[:max(1, len(payload) // 2)])
                self._conns.pop(rx, None)
            if self._rg is not None:
                self._rg.forget(sock)   # BEFORE close: fileno still valid
            sock.close()
        except OSError:
            pass                     # the fault IS a broken connection
        return True

    def send_message(self, msg: Message) -> None:
        # chunked streaming send: the codec hands back a frame prefix +
        # one part per array buffer, and each part goes to the socket
        # directly — a multi-GB model frame is never materialized as one
        # contiguous buffer (the old encode() + concat path transiently
        # held ~3x the payload: arrays + BytesIO + the length-prefixed
        # copy)
        if not self._stamp_frame(msg):
            return                   # chaos send gate dropped the frame
        rx = msg.get_receiver_id()
        if self._reliable_tx:
            # the envelope needs the whole frame (CRC + resend buffer),
            # so the reliable path joins the parts; first transmit +
            # retries live in the endpoint
            payload = MessageCodec.encode(msg)
            wire = self._reliability_endpoint().send(rx, payload)
            self._obs_sent(len(wire))
            return
        total, parts = MessageCodec.encode_parts(msg)
        sock = self._connect(rx)
        with self._conn_lock:
            sock.sendall(struct.pack("<Q", total))
            for part in parts:
                sock.sendall(part)
        self._obs_sent(total)

    def close(self) -> None:
        self._alive = False
        if self.reactor_mode:
            # graceful drain: the group stops accepting, flushes
            # pending writes inside its drain budget, and closes every
            # socket it owns (accepted AND adopted dial-outs) — the
            # listen port is free for a same-port restart when this
            # returns
            self._rg.close()
            with self._conn_lock:
                for s in self._conns.values():
                    try:
                        s.close()
                    except OSError:
                        pass
                self._conns.clear()
            return
        # shutdown BEFORE close: close() alone does not interrupt the
        # accept(2) the _accept_loop thread is blocked in, and the
        # in-flight syscall keeps the kernel socket alive and LISTENING
        # — which held the port hostage against a same-port restart
        # (the crash-resume rebind) even with the fd closed
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass                    # never listened / already dead
        self._listener.close()
        with self._conn_lock:
            for s in self._conns.values():
                s.close()
            self._conns.clear()
            for s in list(self._accepted):
                try:
                    s.close()       # releases the listen port for a
                except OSError:     # same-port restart (crash-resume)
                    pass
            self._accepted.clear()
