"""Seeded wire-level fault injection — the federation chaos layer (ISSUE 8).

FL_PyTorch (arXiv:2202.03099) frames robustness scenarios as first-class
experiment axes; FedML's own regime (arXiv:2007.13518) is intermittent
clients on unreliable links.  This module makes those scenarios
INJECTABLE at the two chokepoints every backend already funnels through
(fedml_tpu/comm/base.py), so one policy object tortures all five
transports uniformly:

* **send gate** (`BaseCommManager._stamp_frame`): partition (a peer set
  whose outbound frames all vanish), per-peer drop/delay overrides, and
  disconnect-mid-frame (the TCP backend tears the connection down
  halfway through a frame — the torn-wire case `_read_exact` turns into
  a ConnectionError);
* **receive path** (`_deliver_frame` / the MQTT JSON handler): drop,
  duplicate, reorder (hold one frame, release it after the next),
  delay, and byte-corruption — applied to the raw frame bytes BEFORE
  the reliability envelope or the codec sees them, exactly where a bad
  NIC or a flaky broker would hit.

Determinism: every draw flows through a per-stream
`np.random.Generator` seeded from (cfg.seed, direction, stream id) —
send streams are keyed by peer rank, receive streams by the receiving
thread (one per connection/client on every real transport).  A stream's
injected-event trace is therefore a pure function of the seed and its
own frame order, regardless of cross-stream thread interleaving: two
runs with the same seed produce identical per-stream traces, two seeds
differ (pinned in tests/test_chaos.py).  The bounded `events` list is
that trace; `counts` is the rollup the chaos bench reports.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Iterable, Optional

import numpy as np

from fedml_tpu import obs

log = logging.getLogger(__name__)

# receive-side fault kinds, in the cumulative-draw order (one uniform
# per frame walks this ladder — a frame suffers at most one fault)
RECV_KINDS = ("drop", "dup", "reorder", "delay", "corrupt")
# send-side kinds the gate can return
SEND_KINDS = ("partition", "drop", "delay", "disconnect")

_MAX_EVENTS = 50_000


@dataclasses.dataclass
class ChaosConfig:
    """Fault rates (probabilities per frame).  drop/dup/reorder/delay/
    corrupt apply at the receive chokepoint; disconnect at the send
    gate (mid-frame teardown needs the sender's socket).  `per_peer`
    maps a peer rank to overrides for the SEND gate's drop/delay/
    disconnect — per-peer receive attribution would need the envelope
    decoded first, so asymmetric links are modeled sender-side."""
    drop: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    delay: float = 0.0
    corrupt: float = 0.0
    disconnect: float = 0.0
    delay_s: float = 0.01            # mean injected delay (exponential)
    corrupt_nbytes: int = 8          # bytes flipped per corrupted frame
    seed: int = 0
    per_peer: Optional[dict] = None  # rank -> {"drop"/"delay"/"disconnect": p}

    def __post_init__(self):
        for k in ("drop", "dup", "reorder", "delay", "corrupt",
                  "disconnect"):
            v = getattr(self, k)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"chaos rate {k}={v} outside [0, 1]")


class _Stream:
    __slots__ = ("rng", "n")

    def __init__(self, seed: int, direction: int, ident: int):
        self.rng = np.random.default_rng([seed, direction, ident])
        self.n = 0


class ChaosPolicy:
    """Seeded fault injector; install on a backend with
    `BaseCommManager.install_chaos`.  Thread-safe; one policy may be
    shared by several backends (the event trace then interleaves their
    streams, each stream still deterministic)."""

    def __init__(self, cfg: Optional[ChaosConfig] = None, **rates):
        self.cfg = cfg if cfg is not None else ChaosConfig(**rates)
        self._lock = threading.Lock()
        self._send_streams: dict[int, _Stream] = {}
        self._recv_tls = threading.local()
        self._next_recv = 0
        self._held: Optional[bytes] = None     # the reorder slot
        self._partitioned: set[int] = set()
        self.events: list[dict] = []
        self.counts: dict[str, int] = {}
        self._m_injected = obs.counter("comm_chaos_injected_total")

    # -- partitions (dynamic — a chaos scenario toggles these mid-run) -------
    def partition(self, *ranks: int) -> None:
        """Make `ranks` unreachable: every outbound frame to them drops
        (counted as "partition") until heal()."""
        with self._lock:
            self._partitioned.update(int(r) for r in ranks)

    def heal(self, *ranks: int) -> None:
        """Lift the partition for `ranks` (all of it when empty)."""
        with self._lock:
            if ranks:
                self._partitioned.difference_update(int(r) for r in ranks)
            else:
                self._partitioned.clear()

    def partitioned(self) -> frozenset:
        with self._lock:
            return frozenset(self._partitioned)

    # -- bookkeeping ---------------------------------------------------------
    def _record(self, stream: str, n: int, kind: str) -> None:
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
            if len(self.events) < _MAX_EVENTS:
                self.events.append({"stream": stream, "n": n,
                                    "kind": kind})
        self._m_injected.inc()
        obs.instant(f"chaos.{kind}", stream=stream, n=n)

    def trace(self) -> list[dict]:
        with self._lock:
            return list(self.events)

    def summary(self) -> dict:
        with self._lock:
            return dict(self.counts)

    # -- send gate -----------------------------------------------------------
    def plan_send(self, peer: int) -> tuple[str, float]:
        """One draw from `peer`'s send stream: ("pass"|"drop"|"delay"|
        "disconnect"|"partition", delay_seconds).  Partition wins before
        any draw (and consumes none, so healing preserves the stream's
        remaining schedule)."""
        with self._lock:
            if peer in self._partitioned:
                pass_through = False
            else:
                pass_through = True
            st = self._send_streams.get(peer)
            if st is None:
                st = self._send_streams[peer] = _Stream(
                    self.cfg.seed, 0, peer)
        if not pass_through:
            self._record(f"send:{peer}", -1, "partition")
            return "partition", 0.0
        over = (self.cfg.per_peer or {}).get(peer, {})
        p_drop = float(over.get("drop", 0.0))
        p_delay = float(over.get("delay", 0.0))
        p_disc = float(over.get("disconnect", self.cfg.disconnect))
        if p_drop + p_delay + p_disc <= 0.0:
            return "pass", 0.0
        with self._lock:
            n = st.n
            st.n += 1
            u = float(st.rng.random())
            d = float(st.rng.exponential(self.cfg.delay_s))
        if u < p_drop:
            self._record(f"send:{peer}", n, "drop")
            return "drop", 0.0
        if u < p_drop + p_delay:
            self._record(f"send:{peer}", n, "delay")
            return "delay", d
        if u < p_drop + p_delay + p_disc:
            self._record(f"send:{peer}", n, "disconnect")
            return "disconnect", 0.0
        return "pass", 0.0

    # -- receive path --------------------------------------------------------
    def _recv_stream(self) -> tuple[str, _Stream]:
        st = getattr(self._recv_tls, "stream", None)
        if st is None:
            with self._lock:
                ident = self._next_recv
                self._next_recv += 1
            st = _Stream(self.cfg.seed, 1, ident)
            self._recv_tls.stream = st
            self._recv_tls.ident = ident
        return f"recv:{self._recv_tls.ident}", st

    def filter_recv(self, payload) -> Iterable:
        """Apply one receive-side fault draw to `payload`; returns the
        list of frames to actually deliver (possibly empty, possibly
        two, possibly byte-flipped).  May sleep (injected delay) — it
        runs on the transport's recv thread, so the delay backpressures
        exactly like real network latency would.

        A reorder-held frame is released behind the NEXT frame
        regardless of that frame's own draw, so "reorder" really means
        swapped delivery, never a disguised drop (only a frame held at
        the very end of a run is lost — the tail truncation any real
        reordering window has)."""
        c = self.cfg
        total = c.drop + c.dup + c.reorder + c.delay + c.corrupt
        if total <= 0.0:
            return (payload,)
        with self._lock:
            held, self._held = self._held, None
        out = self._fate(payload)
        if held is not None:
            out = tuple(out) + (held,)
        return out

    def _fate(self, payload) -> tuple:
        c = self.cfg
        name, st = self._recv_stream()
        with self._lock:
            n = st.n
            st.n += 1
            u = float(st.rng.random())
            d = float(st.rng.exponential(c.delay_s))
            k = c.corrupt_nbytes
            idx = st.rng.integers(0, max(1, len(payload)),
                                  size=max(1, k)) if c.corrupt else None
        edge = c.drop
        if u < edge:
            self._record(name, n, "drop")
            return ()
        edge += c.dup
        if u < edge:
            self._record(name, n, "dup")
            return (payload, payload)
        edge += c.reorder
        if u < edge:
            # stash; filter_recv releases it behind the NEXT frame
            self._record(name, n, "reorder")
            with self._lock:
                self._held = bytes(payload)
            return ()
        edge += c.delay
        if u < edge:
            self._record(name, n, "delay")
            time.sleep(min(d, 1.0))
            return (payload,)
        edge += c.corrupt
        if u >= edge:
            return (payload,)      # the frame passes clean
        # corrupt: flip bytes at the drawn offsets (on a copy — the
        # caller's buffer may be shared)
        self._record(name, n, "corrupt")
        bad = bytearray(payload)
        if bad:
            for i in np.asarray(idx).tolist():
                bad[int(i) % len(bad)] ^= 0xFF
        return (bytes(bad),)
