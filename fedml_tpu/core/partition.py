"""Non-IID dataset partitioners (host-side numpy).

Parity target: reference fedml_core/non_iid_partition/noniid_partition.py:6-103
(LDA-Dirichlet with a min-samples rebalance loop) and the `homo` /
`power-law` styles used by the dataset loaders
(e.g. cifar10/data_loader.py:125-156).  Partitioning is host-side metadata —
it produces index maps that the data layer turns into padded, HBM-resident
per-client shards.
"""
from __future__ import annotations

import numpy as np


def partition_homo(n_samples: int, n_clients: int, seed: int = 0) -> dict[int, np.ndarray]:
    """Uniform random split ("homo" in the reference loaders)."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(n_samples)
    return {i: np.sort(part) for i, part in enumerate(np.array_split(idxs, n_clients))}


def partition_dirichlet(
    labels: np.ndarray,
    n_clients: int,
    alpha: float,
    min_size_floor: int = 10,
    seed: int = 0,
    task: str = "classification",
) -> dict[int, np.ndarray]:
    """Latent-Dirichlet partition over class proportions.

    For each class k, draw p ~ Dir(alpha * 1_C) and split that class's sample
    indices among clients in proportion p, capping clients that already hold
    >= n/C samples (the same balancing rule as the reference's
    partition_class_samples_with_dirichlet_distribution,
    noniid_partition.py:76-91).  Re-draw until every client holds at least
    ``min_size_floor`` samples (reference's min-10 rebalance loop,
    noniid_partition.py:28-52).
    """
    labels = np.asarray(labels)
    n = labels.shape[0]
    classes = np.unique(labels)
    rng = np.random.RandomState(seed)

    # Termination fix over the reference: clamp the floor to the FEASIBLE
    # n // n_clients (the reference's ``n/C + 1`` bound cannot be met by
    # all clients simultaneously — k·(⌊n/k⌋+1) > n — so any call with
    # n < 10·n_clients would loop forever there), and relax it by 1 after
    # every 200 unlucky draws so tiny-n/small-α configs still return.
    target = max(min(min_size_floor, n // n_clients), 0)
    attempts = 0
    idx_batch: list[list[int]] = []
    while True:   # at least one draw, even when target == 0 (n < n_clients)
        attempts += 1
        if attempts % 200 == 0 and target > 0:
            target -= 1
        idx_batch = [[] for _ in range(n_clients)]
        for k in classes:
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            proportions = rng.dirichlet(np.repeat(alpha, n_clients))
            # Cap clients already at their fair share.
            proportions = np.array(
                [p * (len(b) < n / n_clients) for p, b in zip(proportions, idx_batch)]
            )
            proportions = proportions / proportions.sum()
            cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            idx_batch = [b + part.tolist() for b, part in zip(idx_batch, np.split(idx_k, cuts))]
        min_size = min(len(b) for b in idx_batch)
        if min_size >= target:
            break

    out = {}
    for i in range(n_clients):
        rng.shuffle(idx_batch[i])
        out[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return out


def partition_power_law(
    labels: np.ndarray,
    n_clients: int,
    seed: int = 0,
    a: float = 3.0,
    min_per_client: int = 10,
) -> dict[int, np.ndarray]:
    """Power-law sample-count partition (the MNIST/LEAF "power-law" style of
    benchmark/README.md:12): client sizes follow a power-law, samples drawn
    from a label-sorted pool so clients also skew by class."""
    labels = np.asarray(labels)
    n = labels.shape[0]
    rng = np.random.RandomState(seed)
    raw = rng.power(a, n_clients) + 1e-3
    sizes = np.maximum((raw / raw.sum() * (n - min_per_client * n_clients)).astype(int)
                       + min_per_client, min_per_client)
    # Trim/extend to exactly n so every sample is assigned.
    while sizes.sum() > n:
        sizes[np.argmax(sizes)] -= 1
    while sizes.sum() < n:
        sizes[np.argmin(sizes)] += 1
    order = np.argsort(labels, kind="stable")
    out, off = {}, 0
    for i in range(n_clients):
        out[i] = np.sort(order[off:off + sizes[i]])
        off += sizes[i]
    return out


def record_data_stats(labels: np.ndarray, net_dataidx_map: dict[int, np.ndarray]) -> dict:
    """Per-client class histogram (reference noniid_partition.py:94-103)."""
    stats = {}
    for cid, idxs in net_dataidx_map.items():
        unq, cnt = np.unique(np.asarray(labels)[idxs], return_counts=True)
        stats[cid] = {int(u): int(c) for u, c in zip(unq, cnt)}
    return stats
