"""ClientTrainer — the functional replacement for the reference's
ModelTrainer ABC (fedml_core/trainer/model_trainer.py:4-37).

The reference's operator is an object with ``get/set_model_params, train,
test``.  TPU-native, the operator is a set of *pure functions* closed over
the model definition:

  init(rng, sample)                 -> variables pytree
  train_step(state, batch)          -> state            (one SGD step)
  local_train(variables, shard)     -> (variables, metrics)   lax.scan'd
  eval_step(variables, batch)       -> metric sums

so that an entire federated round — local epochs for a whole cohort of
clients — is one jit-compiled XLA program (vmap over the client axis,
shard_map over the mesh).  Batches carry an explicit ``mask`` channel so
unequal client dataset sizes become padding, not data-dependent control flow
(SURVEY.md §7 hard-part #1).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import chex
import jax
import jax.numpy as jnp
import optax

from fedml_tpu import obs
from fedml_tpu.core.pytree import (tree_merge_counts, tree_select,
                                   tree_vary_noop)

Pytree = Any


@chex.dataclass
class TrainState:
    variables: Pytree          # {"params": ..., ["batch_stats": ...]}
    opt_state: Pytree
    rng: jax.Array


def _split_variables(variables):
    params = variables["params"]
    rest = {k: v for k, v in variables.items() if k != "params"}
    return params, rest


def make_lr_schedule(mode: str, base_lr: float, total_steps: int,
                     iters_per_epoch: int = 1, lr_step_epochs: int = 0,
                     warmup_steps: int = 0):
    """The reference's LR_Scheduler (fedseg/utils.py:114-157) as an optax
    schedule over the LOCAL step count T (the reference recreates its
    scheduler per train() call, so per-round restart is parity):

      poly: lr·(1−T/N)^0.9 · cos: 0.5·lr·(1+cos(πT/N)) ·
      step: lr·0.1^(epoch//lr_step) · linear warmup for T < warmup_steps.
    """
    if mode not in ("poly", "cos", "step"):
        raise ValueError(f"unknown lr schedule {mode!r}")
    if mode == "step" and not lr_step_epochs:
        raise ValueError("step schedule needs lr_step_epochs")
    N = max(total_steps, 1)

    def schedule(count):
        T = jnp.minimum(count, N).astype(jnp.float32)
        if mode == "poly":
            lr = base_lr * (1.0 - T / N) ** 0.9
        elif mode == "cos":
            lr = 0.5 * base_lr * (1.0 + jnp.cos(jnp.pi * T / N))
        else:
            epoch = count // iters_per_epoch
            lr = base_lr * 0.1 ** (epoch // lr_step_epochs)
        if warmup_steps > 0:
            lr = jnp.where(T < warmup_steps, lr * T / warmup_steps, lr)
        return lr

    return schedule


def make_optimizer(name: str, lr, momentum: float = 0.0,
                   weight_decay: float = 0.0) -> optax.GradientTransformation:
    """Client optimizer factory (reference exposes sgd/adam via --client_optimizer,
    my_model_trainer_classification.py:25-35).  `lr` may be a float or an
    optax schedule (make_lr_schedule)."""
    if name == "adamw":   # adamw owns its decay — do not chain it twice
        return optax.adamw(lr, weight_decay=weight_decay)
    txs = []
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    if name == "sgd":
        txs.append(optax.sgd(lr, momentum=momentum if momentum else None))
    elif name == "adam":
        txs.append(optax.adam(lr))
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    return optax.chain(*txs)


def broadcast_mask(mask, target):
    """Broadcast a per-sample mask over any trailing label axes (sequence
    time, segmentation H/W): [bs] → target.shape."""
    if mask.ndim < target.ndim:
        mask = mask.reshape(mask.shape + (1,) * (target.ndim - mask.ndim))
    return jnp.broadcast_to(mask, target.shape)


def masked_cross_entropy(logits, labels, mask):
    """Mean softmax CE over valid (mask=1) samples. Labels are int class ids;
    if labels has a trailing time axis (NWP models) the mask must match."""
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    mask = mask.astype(ce.dtype)
    return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def masked_bce(logits, targets, mask):
    """Multi-label sigmoid BCE (stackoverflow_lr's BCELoss path,
    my_model_trainer_tag_prediction.py)."""
    bce = optax.sigmoid_binary_cross_entropy(logits, targets).mean(axis=-1)
    mask = mask.astype(bce.dtype)
    return jnp.sum(bce * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def focal_from_ce(ce, gamma: float = 2.0, alpha: float = 0.5):
    """α·(1−pt)^γ·CE with pt = exp(−CE), elementwise."""
    return alpha * (1.0 - jnp.exp(-ce)) ** gamma * ce


def masked_focal_loss(logits, labels, mask, gamma: float = 2.0,
                      alpha: float = 0.5):
    """Per-element focal loss (fedseg SegmentationLosses.FocalLoss,
    utils.py:97-111, defaults γ=2 α=0.5).  The reference applies the focal
    transform to the already-averaged CE (a scalar); per-element is the
    published formulation and strictly more useful — documented
    deviation."""
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    focal = focal_from_ce(ce, gamma, alpha)
    mask = mask.astype(focal.dtype)
    return jnp.sum(focal * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def masked_accuracy_sums(logits, labels, mask):
    """Returns (n_correct, n_valid) so accuracies aggregate exactly across
    clients/batches (the reference sums correct/total the same way,
    my_model_trainer_classification.py:57-77)."""
    pred = jnp.argmax(logits, axis=-1)
    ok = (pred == labels).astype(jnp.float32) * mask.astype(jnp.float32)
    return jnp.sum(ok), jnp.sum(mask.astype(jnp.float32))


class ClientTrainer:
    """Functional train/eval operator for one model family.

    Args:
      model: a flax linen Module.
      loss: "ce" | "bce".
      optimizer / lr / momentum / weight_decay: client-side SGD config.
      prox_mu: FedProx proximal coefficient; when > 0, local_train receives
        the round's global params and adds (mu/2)||w - w_global||^2.
      has_time_axis: labels have a trailing sequence axis (char/word LMs).
      eval_ignore_id: label id excluded from EVAL metrics (the TFF
        NWP/shakespeare convention: accuracy ignores <pad> positions,
        google-research/federated stackoverflow_dataset; pad=0 in both
        data/text.py vocab layouts).  Training loss is untouched — the
        reference trains plain CE over all positions.
      train_ignore_id: label id excluded from the TRAINING loss too
        (segmentation void label, reference SegmentationLosses
        ignore_index=255, fedseg/utils.py:72).
      lr: float, or an optax schedule from make_lr_schedule (the
        reference's poly/cos/step LR_Scheduler; restarts per local round
        because opt state is re-initialized per local_train — parity).
      loss: "ce" | "bce" | "focal" (focal: fedseg utils.py:97, γ=2 α=0.5).
      batch_axes: shard_map mesh axis names that split each per-step
        batch's SAMPLE dim across devices (parallel/mesh.py BATCH_AXIS).
        When set, every train step computes the full-batch gradient with
        one psum: the loss normalizes by the GLOBAL valid-sample count,
        grads/loss are psum'd and the empty-batch guard keys on the
        global count — so the trained weights are those of the unsplit
        batch (bit-level up to reduction order) PROVIDED the step is
        deterministic given the batch: with augment or dropout the
        per-shard rng fold-in deliberately decorrelates those draws
        from the unsplit run, so results differ by the augmentation
        noise (not an error).  Mesh engines set this automatically when
        their mesh has a "batch" axis.
    """

    def __init__(self, model, loss: str = "ce", optimizer: str = "sgd",
                 lr=0.03, momentum: float = 0.0,
                 weight_decay: float = 0.0, prox_mu: float = 0.0,
                 has_time_axis: bool = False,
                 train_dtype=jnp.float32,
                 augment: Optional[Callable] = None,
                 eval_ignore_id: Optional[int] = None,
                 train_ignore_id: Optional[int] = None,
                 batch_axes: tuple = (),
                 batch_unroll: int = 1):
        self.model = model
        self.loss_name = loss
        if loss not in ("ce", "bce", "focal"):
            raise ValueError(f"unknown loss {loss!r}")
        self.tx = make_optimizer(optimizer, lr, momentum, weight_decay)
        self.has_schedule = callable(lr)
        self.prox_mu = prox_mu
        self.has_time_axis = has_time_axis
        self.train_dtype = train_dtype
        # training-time augmentation (rng, x) -> x, applied ONLY in the
        # train-step loss (data/augment.py); eval paths never see it
        self.augment = augment
        self.eval_ignore_id = eval_ignore_id
        self.train_ignore_id = train_ignore_id
        self.batch_axes = tuple(batch_axes)
        # default unroll of the batch scan in local_train (perf knob;
        # see local_train docstring for the measured story)
        if int(batch_unroll) < 1:
            raise ValueError(f"batch_unroll must be >= 1, got {batch_unroll}")
        self.batch_unroll = int(batch_unroll)

    def _revary(self, tree):
        """psum over batch_axes makes a value invariant along them; cast it
        back to varying so it composes with the (pvary'd) params/opt state
        under shard_map's vma type check.  Values are unchanged."""
        return jax.tree.map(
            lambda a: jax.lax.pcast(a, self.batch_axes, to="varying"), tree)

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array, sample_input: jax.Array) -> Pytree:
        return self.model.init(rng, sample_input, train=False)

    def init_opt(self, variables: Pytree) -> Pytree:
        return self.tx.init(variables["params"])

    # -- mixed precision ----------------------------------------------------
    def _cast_floats(self, tree, dtype):
        return jax.tree.map(
            lambda a: a.astype(dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    # -- loss ---------------------------------------------------------------
    def _loss(self, params, rest, batch, rng, global_params=None):
        """Masters (params/opt state/stats) stay float32; when train_dtype
        is bfloat16 the forward/backward compute runs through bf16 casts —
        the MXU recipe: bf16 matmuls, f32 accumulation and update."""
        x, y, mask = batch["x"], batch["y"], batch["mask"]
        if self.batch_axes:
            # decorrelate the sample-wise randomness (augment offsets,
            # dropout masks) across batch shards: the carried rng is
            # replicated along the batch axes, and augment draws (bs,)
            # vectors from it — without the fold-in, sample i on every
            # shard would share its crop/flip/cutout draw
            for ax in self.batch_axes:
                if jax.lax.axis_size(ax) > 1:   # size-1 axis: stay a no-op
                    rng = jax.random.fold_in(rng, jax.lax.axis_index(ax))
        if self.augment is not None:
            rng, aug_rng = jax.random.split(rng)
            x = self.augment(aug_rng, x)
        rngs = {"dropout": rng}
        half = self.train_dtype != jnp.float32
        apply_params = self._cast_floats(params, self.train_dtype) if half else params
        # stats collections (BatchNorm running mean/var) are NOT cast: the
        # EMA must accumulate on the f32 master or sub-0.4%-ulp increments
        # vanish on the bf16 grid near convergence
        apply_rest = rest
        if half and jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(self.train_dtype)
        if apply_rest:
            logits, new_rest = self.model.apply(
                {"params": apply_params, **apply_rest}, x, train=True,
                mutable=list(apply_rest.keys()), rngs=rngs)
        else:
            logits = self.model.apply({"params": apply_params}, x, train=True,
                                      rngs=rngs)
            new_rest = apply_rest
        if half:
            logits = logits.astype(jnp.float32)      # loss math in f32
            new_rest = self._cast_floats(new_rest, jnp.float32)
        if self.has_time_axis and mask.ndim < y.ndim:
            mask = broadcast_mask(mask, y)
        if self.train_ignore_id is not None:
            valid = y != self.train_ignore_id
            mask = mask * valid.astype(mask.dtype)
            # void ids may be out of the class range (255): remap to 0 so
            # the gather inside CE stays in-bounds (0*NaN would poison the
            # masked sum otherwise)
            y = jnp.where(valid, y, 0)
        if self.loss_name == "ce":
            loss = masked_cross_entropy(logits, y, mask)
        elif self.loss_name == "bce":
            loss = masked_bce(logits, y, mask)
        else:
            loss = masked_focal_loss(logits, y, mask)
        if self.batch_axes:
            # batch-split normalization: the masked losses divide by this
            # SHARD's valid count; rescale to S_l / C_g so the psum over
            # the batch axes (train_step) yields the unsplit batch's mean
            c_l = jnp.sum(mask.astype(jnp.float32))
            c_g = self._revary(jax.lax.psum(c_l, self.batch_axes))
            loss = loss * c_l / jnp.maximum(c_g, 1.0)
        if self.prox_mu > 0.0 and global_params is not None:
            sq = jax.tree.map(lambda a, b: jnp.sum(jnp.square(a - b)),
                              params, global_params)
            prox = 0.5 * self.prox_mu * jnp.sum(
                jnp.stack(jax.tree.leaves(sq)))
            if self.batch_axes:
                # the prox term is computed identically on every batch
                # shard; divide by the axis size so its psum counts once
                prox = prox / self._revary(
                    jax.lax.psum(jnp.float32(1), self.batch_axes))
            loss = loss + prox
        return loss, new_rest

    # -- one SGD step -------------------------------------------------------
    def train_step(self, state: TrainState, batch, global_params=None) -> tuple[TrainState, jax.Array]:
        params, rest = _split_variables(state.variables)
        rng, step_rng = jax.random.split(state.rng)
        (loss, new_rest), grads = jax.value_and_grad(self._loss, has_aux=True)(
            params, rest, batch, step_rng, global_params)
        n_valid = jnp.sum(batch["mask"])
        if self.batch_axes:
            # the full-batch gradient: each shard computed S_l/C_g-normalized
            # grads over its sample slice; one psum per step completes them.
            # Every batch shard then applies the IDENTICAL update, keeping
            # the per-client weights replicated along the batch axes.
            grads = self._revary(jax.lax.psum(grads, self.batch_axes))
            loss = self._revary(jax.lax.psum(loss, self.batch_axes))
            new_rest = self._revary(jax.lax.pmean(new_rest, self.batch_axes))
            n_valid = self._revary(jax.lax.psum(n_valid, self.batch_axes))
        updates, opt_state = self.tx.update(grads, state.opt_state, params)
        # empty-batch guard: for params, scaling the UPDATES by the has-data
        # flag is exactly equivalent to a post-hoc select (additive updates;
        # u*0 leaves params bitwise unchanged) but fuses into apply_updates
        # instead of costing an extra full-tree pass per step.  Stats
        # collections and optimizer state are not additive, so they keep the
        # select (core/pytree.py:tree_select).  Under batch_axes the guard
        # keys on the GLOBAL count — a shard whose slice is all padding must
        # still apply the other shards' gradient contribution.
        has_data = n_valid > 0
        g = has_data.astype(jnp.float32)
        new_params = optax.apply_updates(
            params, jax.tree.map(lambda u: u * g.astype(u.dtype), updates))
        keep = functools.partial(tree_select, has_data)
        kept_opt = keep(opt_state, state.opt_state)
        if self.has_schedule:
            # padded batches still advance the schedule's step count so
            # ragged clients share one LR trajectory (tree_merge_counts)
            kept_opt = tree_merge_counts(kept_opt, opt_state)
        return TrainState(
            variables={"params": new_params, **keep(new_rest, rest)},
            opt_state=kept_opt,
            rng=rng), jnp.where(has_data, loss, 0.0)

    # -- local training: epochs x batches under lax.scan --------------------
    def local_train(self, variables: Pytree, shard, rng: jax.Array,
                    epochs: int, global_params=None,
                    unroll: Optional[int] = None):
        """Run E local epochs of SGD over one client's padded shard.

        shard: {"x": [B, bs, ...], "y": [B, bs, ...], "mask": [B, bs]}
        Returns (new_variables, mean_loss, n_samples). This is the reference's
        client hot loop (my_model_trainer_classification.py:19-53) as a single
        scanned XLA program.  `unroll` (default: the constructor's
        batch_unroll) unrolls the batch scan — measured on v5e at the
        bench shape: neutral at chunk 8, and at the chunk-2 optimum a
        full-shard unroll wins ~1-2% (tools/profile_bench.py L2U rows).

        The obs span fires at TRACE time only (this function runs under
        jit): it measures how long building the local-training scan
        takes per compile — never the device execution — and, being a
        host-side no-op outside the traced dataflow, cannot perturb the
        compiled program (results stay bitwise obs-on/off).
        """
        unroll = self.batch_unroll if unroll is None else unroll
        with obs.span("trace.local_train", epochs=epochs, unroll=unroll):
            # tree_vary_noop: align the fresh (replicated-typed) optimizer
            # state with the varying type it takes after step 1 under
            # shard_map (core/pytree.py)
            state = TrainState(
                variables=variables,
                opt_state=tree_vary_noop(self.init_opt(variables), shard),
                rng=rng)
            # NOTE on the carry layout (PR-4 copy audit): packing this
            # TrainState carry's float leaves into per-dtype flat
            # vectors (the engine.py flatten_carry_f32 treatment) was
            # built and MEASURED here, and kept OUT: it removes the
            # per-leaf donated-param staging copies at scan entry (once
            # per chunk trip) but forces every conv wgrad through a
            # relayout copy FEEDING the concat (per step) — audited on
            # the CNN round program at +224 KB static copy bytes net
            # (tools/hlo_copy_audit.py; per-step > per-entry).  The
            # chunked cohort loops DO pack their accumulator carries,
            # where the update is a plain elementwise add and packing
            # only removes copies.

            def batch_body(state, batch):
                state, loss = self.train_step(state, batch, global_params)
                cnt = jnp.sum(batch["mask"])
                if self.batch_axes:   # loss is global; weight it globally
                    cnt = self._revary(jax.lax.psum(cnt, self.batch_axes))
                return state, (loss, cnt)

            def epoch_body(state, _):
                state, (losses, counts) = jax.lax.scan(
                    batch_body, state, shard, unroll=unroll)
                # sample-weighted epoch loss: padding batches add nothing
                return state, jnp.sum(losses * counts) / jnp.maximum(
                    jnp.sum(counts), 1.0)

            state, epoch_losses = jax.lax.scan(epoch_body, state, None,
                                               length=epochs)
            n = jnp.sum(shard["mask"])
            if self.batch_axes:   # client's TOTAL sample count (agg weight)
                n = self._revary(jax.lax.psum(n, self.batch_axes))
            return state.variables, jnp.mean(epoch_losses), n

    # -- eval ---------------------------------------------------------------
    def eval_step(self, variables: Pytree, batch):
        """Returns dict of sums: loss_sum, correct, count (mask-aware)."""
        params, rest = _split_variables(variables)
        x, y, mask = batch["x"], batch["y"], batch["mask"]
        logits = self.model.apply({"params": params, **rest}, x, train=False)
        if self.has_time_axis and mask.ndim < y.ndim:
            mask = broadcast_mask(mask, y)
        if self.eval_ignore_id is not None:
            mask = mask * (y != self.eval_ignore_id).astype(mask.dtype)
        if self.train_ignore_id is not None:   # void label: never scored
            valid = y != self.train_ignore_id
            mask = mask * valid.astype(mask.dtype)
            y = jnp.where(valid, y, 0)         # keep the CE gather in-bounds
        if self.loss_name in ("ce", "focal"):
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            if self.loss_name == "focal":
                # eval with the train criterion, like the reference
                ce = focal_from_ce(ce)
            loss_sum = jnp.sum(ce * mask)
            correct, count = masked_accuracy_sums(logits, y, mask)
        else:
            bce = optax.sigmoid_binary_cross_entropy(logits, y).mean(-1)
            loss_sum = jnp.sum(bce * mask)
            # multi-label: count a hit when the top predicted tag is present
            pred = jnp.argmax(logits, axis=-1)
            hit = jnp.take_along_axis(y, pred[..., None], axis=-1)[..., 0]
            correct = jnp.sum(hit * mask)
            count = jnp.sum(mask)
        return {"loss_sum": loss_sum, "correct": correct, "count": count}

    def evaluate(self, variables: Pytree, shard):
        """Scan eval over batches of a padded shard; returns summed metrics.
        (Span = trace-time only, like local_train.)"""
        def body(carry, batch):
            m = self.eval_step(variables, batch)
            return jax.tree.map(jnp.add, carry, m), None

        init = {"loss_sum": jnp.float32(0), "correct": jnp.float32(0),
                "count": jnp.float32(0)}
        with obs.span("trace.evaluate"):
            sums, _ = jax.lax.scan(body, init, shard)
        return sums
